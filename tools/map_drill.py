#!/usr/bin/env python
"""Map chaos drill (ISSUE 14): prove `pbt map` loses NOTHING when
killed anywhere.

A seeded corpus (with one deliberately poisoned record) is mapped twice
through REAL `pbt map` subprocesses:

- the CHAOS line: run 1 is SIGKILLed deterministically in the worst
  window (between a block's object write and its cursor advance —
  PBT_MAP_FAULTS crash hook); while it is down the drill TEARS the
  dead run's artifacts the way hostile storage would — shard 0's
  recorded tail block object is truncated mid-file and shard 1's main
  cursor is torn — then run 2 resumes under an injected transient
  dispatch failure (2 retries) and must complete;
- the WINDOW line (ISSUE 19): a run SIGKILLed at the NEW
  `block_fetched` crash point — the pipelined dispatch window where a
  block's device compute AND host fetch have completed but its commit
  (object write + cursor advance) has not happened yet, while the
  NEXT block is already in flight — then one plain resume;
- the CONTROL line: one uninterrupted run over the same corpus into a
  fresh store.

Gates (exit nonzero on violation — tier-1 runs this as a smoke stage):
  - the resumed chaos store is BYTE-IDENTICAL to the control store
    (same (shard, block) → digest map, same object bytes), and so is
    the resumed WINDOW store (a device-complete-but-uncommitted block
    is re-worked, never half-committed);
  - both stores pass `verify_store` complete+ok, and `pbt map
    --verify` (the real CLI) exits 0 on the chaos store;
  - re-work is bounded: map_block events across both chaos runs exceed
    the unique block count by at most ONE block per shard;
  - quarantined count == the ONE injected poison record, in both
    stores, with the typed reason;
  - the injected transient failure was retried (retries observed) and
    still changed nothing;
  - `pbt map --verify` DETECTS a deliberately flipped byte in a block
    (typed digest_mismatch, nonzero exit) and reports a hole when an
    object is deleted;
  - every emitted event validates against the schema (strict reader),
    and `pbt diagnose --map` over the concatenated chaos streams
    reports the same bounded re-work.

Usage:
  python tools/map_drill.py [--outdir DIR] [--json] [--seed N]
      [--corpus N] [--bench-events PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBT_DISABLE_DONATION", "1")

SEQ_LEN = 48
BUCKETS = "[16,32,48]"
NUM_SHARDS = 2
BLOCK_SIZE = 8
ROWS = 2
MAX_SEGMENTS = 4
AA = "ACDEFGHIKLMNPQRSTVWY"
POISON_INDEX = 5  # lands in shard 0 block 0


def _tiny_cfg():
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )

    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
    )


def _make_run_dir(outdir: str) -> str:
    """A real pretrained-run directory (checkpoint + config.json) for
    the subprocesses' --pretrained."""
    import jax

    from proteinbert_tpu.cli.main import _save_run_config
    from proteinbert_tpu.train import Checkpointer, create_train_state

    cfg = _tiny_cfg()
    rundir = os.path.join(outdir, "run")
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    ck = Checkpointer(rundir, async_save=False)
    ck.save(0, state, {"batches_consumed": 0})
    ck.close()
    _save_run_config(cfg, rundir)
    return rundir


def _make_corpus(outdir: str, n: int, seed: int) -> str:
    import numpy as np

    rng = np.random.default_rng(seed)
    path = os.path.join(outdir, "corpus.tsv")
    with open(path, "w") as f:
        for i in range(n):
            if i == POISON_INDEX:
                # Typed poison: an interior space survives the seqs-file
                # round trip and classifies as invalid_char.
                f.write(f"p{i}\tAC DEFG\n")
                continue
            ln = int(rng.integers(5, 29))
            f.write(f"p{i}\t" + "".join(rng.choice(list(AA), size=ln))
                    + "\n")
    return path


def _map_cmd(rundir: str, store: str, corpus: str, events: str):
    return [sys.executable, "-m", "proteinbert_tpu", "--platform", "cpu",
            "map", "--pretrained", rundir, "--store", store,
            "--seqs-file", corpus, "--num-shards", str(NUM_SHARDS),
            "--block-size", str(BLOCK_SIZE),
            "--rows-per-batch", str(ROWS),
            "--max-segments", str(MAX_SEGMENTS), "--buckets", BUCKETS,
            "--events-jsonl", events]


def _run(cmd, env_extra=None, log_path=None, timeout=600):
    env = dict(os.environ)
    env.update(env_extra or {})
    with open(log_path, "ab") as lf:
        proc = subprocess.run(cmd, stdout=lf, stderr=lf, env=env,
                              timeout=timeout)
    return proc.returncode


def run_drill(args) -> dict:
    from faults import map_fault_spec, tear_file, flip_byte
    from proteinbert_tpu.mapper import (
        FAULT_ENV, EmbeddingStore, ShardCursor, store_digests,
        verify_store,
    )
    from proteinbert_tpu.obs import read_events
    from proteinbert_tpu.obs.diagnose import summarize_map

    outdir = args.outdir or tempfile.mkdtemp(prefix="pbt_map_drill_")
    os.makedirs(outdir, exist_ok=True)
    log_path = os.path.join(outdir, "drill.log")
    rundir = _make_run_dir(outdir)
    corpus = _make_corpus(outdir, args.corpus, args.seed)
    chaos_store = os.path.join(outdir, "chaos_store")
    control_store = os.path.join(outdir, "control_store")
    ev1 = os.path.join(outdir, "chaos_run1.events.jsonl")
    ev2 = os.path.join(outdir, "chaos_run2.events.jsonl")
    evc = os.path.join(outdir, "control.events.jsonl")
    failures = []
    t0 = time.monotonic()

    # ---- chaos run 1: SIGKILL between object write and cursor advance
    # of shard 0 block 1 (after s0b0 and s1b0 committed — round-robin).
    rc1 = _run(_map_cmd(rundir, chaos_store, corpus, ev1),
               env_extra={FAULT_ENV: map_fault_spec(
                   crash=(0, 1, "after_object"))},
               log_path=log_path)
    if rc1 not in (-9, 137):
        failures.append(f"chaos run 1 exited {rc1}, expected a SIGKILL "
                        "death (-9/137) — the crash hook never fired")
    run1_blocks = [r for r in read_events(ev1, strict=True)
                   if r["event"] == "map_block"]
    if len(run1_blocks) != 2:
        failures.append(f"chaos run 1 committed {len(run1_blocks)} "
                        "block(s), expected 2 (s0b0, s1b0) before the "
                        "mid-block kill")

    # ---- while it is down: tear shard 0's recorded tail block object
    # and shard 1's main cursor (the injected torn-cursor + torn-block
    # faults the resume path must absorb with <= 1 block re-work each).
    store = EmbeddingStore(chaos_store)
    s0_state, _ = ShardCursor(chaos_store, 0).load()
    if not s0_state["blocks"]:
        failures.append("shard 0 cursor holds no blocks after run 1")
        torn_digest = None
    else:
        torn_digest = s0_state["blocks"][-1]["digest"]
        tear_file(store.object_path(torn_digest))
    tear_file(ShardCursor(chaos_store, 1).path)

    # ---- chaos run 2: resume under an injected transient dispatch
    # failure (shard 1 block 1 fails twice, then succeeds).
    rc2 = _run(_map_cmd(rundir, chaos_store, corpus, ev2),
               env_extra={FAULT_ENV: map_fault_spec(fail=(1, 1, 2))},
               log_path=log_path)
    if rc2 != 0:
        failures.append(f"chaos run 2 (resume) exited {rc2}; see "
                        f"{log_path}")

    # ---- control: one uninterrupted run.
    rcc = _run(_map_cmd(rundir, control_store, corpus, evc),
               log_path=log_path)
    if rcc != 0:
        failures.append(f"control run exited {rcc}; see {log_path}")

    # ---- window line (ISSUE 19): SIGKILL at the NEW block_fetched
    # point of shard 0 block 1 — fired after that block's device
    # compute and host fetch completed, before its object write and
    # cursor advance, while the pipelined drive loop has the NEXT
    # block already submitted. The cursor never moved, so a plain
    # resume must re-work exactly the uncommitted tail.
    window_store = os.path.join(outdir, "window_store")
    evw1 = os.path.join(outdir, "window_run1.events.jsonl")
    evw2 = os.path.join(outdir, "window_run2.events.jsonl")
    rcw1 = _run(_map_cmd(rundir, window_store, corpus, evw1),
                env_extra={FAULT_ENV: map_fault_spec(
                    crash=(0, 1, "block_fetched"))},
                log_path=log_path)
    if rcw1 not in (-9, 137):
        failures.append(f"window run 1 exited {rcw1}, expected a "
                        "SIGKILL death at block_fetched (-9/137)")
    rcw2 = _run(_map_cmd(rundir, window_store, corpus, evw2),
                log_path=log_path)
    if rcw2 != 0:
        failures.append(f"window run 2 (resume) exited {rcw2}; see "
                        f"{log_path}")

    # ------------------------------------------------------------ audit
    chaos_rep = control_rep = None
    retries = 0
    rework = None
    window_rework = None
    if not failures:
        # Byte identity: same (shard, block) → digest map, same bytes.
        dg_chaos = store_digests(chaos_store)
        dg_control = store_digests(control_store)
        if dg_chaos != dg_control:
            failures.append(
                f"stores differ: chaos {sorted(dg_chaos.items())} vs "
                f"control {sorted(dg_control.items())}")
        else:
            ctrl = EmbeddingStore(control_store)
            for dg in dg_chaos.values():
                with open(store.object_path(dg), "rb") as a, \
                        open(ctrl.object_path(dg), "rb") as b:
                    if a.read() != b.read():
                        failures.append(f"object {dg[:16]}… bytes "
                                        "differ between stores")

        chaos_rep = verify_store(chaos_store)
        control_rep = verify_store(control_store)
        for name, rep in (("chaos", chaos_rep), ("control", control_rep)):
            if not (rep["ok"] and rep["complete"]):
                failures.append(
                    f"{name} store failed verification: "
                    f"holes={rep['holes']} corrupt={rep['corrupt']} "
                    f"coverage={rep['coverage_errors']} "
                    f"complete={rep['complete']}")
            if rep["quarantined"] != 1:
                failures.append(
                    f"{name} store quarantined {rep['quarantined']} "
                    "record(s), expected exactly the 1 injected poison")
        qrec = ShardCursor(chaos_store, 0).read_quarantine()
        if not any(r["id"] == f"p{POISON_INDEX}"
                   and r["reason"] == "invalid_char" for r in qrec):
            failures.append(f"poison p{POISON_INDEX} missing from the "
                            f"quarantine sidecar (got {qrec})")

        # Bounded re-work: committed-block events across both chaos
        # runs vs unique blocks; and retries observed.
        run2_recs = read_events(ev2, strict=True)
        read_events(evc, strict=True)  # control events schema-valid
        run2_blocks = [r for r in run2_recs if r["event"] == "map_block"]
        all_blocks = run1_blocks + run2_blocks
        unique = {(r["shard"], r["block"]) for r in all_blocks}
        rework = len(all_blocks) - len(unique)
        if rework > NUM_SHARDS:
            failures.append(f"re-work {rework} blocks > bound of 1 per "
                            f"shard ({NUM_SHARDS})")
        retries = sum(r.get("retries") or 0 for r in run2_blocks)
        if retries < 2:
            failures.append(f"injected transient failure retried "
                            f"{retries} time(s), expected >= 2")
        ends = [r for r in run2_recs if r["event"] == "map_end"]
        if not ends or ends[-1]["outcome"] != "completed":
            failures.append("chaos run 2 did not seal map_end/completed")

        # Window line audit: byte-identity vs control, verification,
        # and the same 1-block-per-shard re-work bound — the pipelined
        # device-complete-but-uncommitted window adds no new loss mode.
        dg_window = store_digests(window_store)
        if dg_window != dg_control:
            failures.append(
                "window store differs from control after the "
                "block_fetched kill + resume: "
                f"{sorted(dg_window.items())} vs "
                f"{sorted(dg_control.items())}")
        wrep = verify_store(window_store)
        if not (wrep["ok"] and wrep["complete"]):
            failures.append(
                f"window store failed verification: holes="
                f"{wrep['holes']} corrupt={wrep['corrupt']} "
                f"complete={wrep['complete']}")
        w_blocks = [r for p in (evw1, evw2)
                    for r in read_events(p, strict=True)
                    if r["event"] == "map_block"]
        w_unique = {(r["shard"], r["block"]) for r in w_blocks}
        window_rework = len(w_blocks) - len(w_unique)
        if window_rework > NUM_SHARDS:
            failures.append(f"window re-work {window_rework} blocks > "
                            f"bound of 1 per shard ({NUM_SHARDS})")

        # diagnose --map over the concatenated chaos streams agrees on
        # the re-work count (the operator-facing view of the drill).
        combined = []
        for p in (ev1, ev2):
            combined.extend(read_events(p, strict=True))
        diag = summarize_map(combined)
        if diag["rework_blocks"] != rework:
            failures.append(
                f"diagnose --map rework {diag['rework_blocks']} != "
                f"event-audit rework {rework}")

        # ---- the --verify detection gates, through the REAL CLI ----
        import contextlib
        import io

        from proteinbert_tpu.cli.main import main as cli_main

        def cli_verify():
            # The CLI prints its report JSON; keep the drill's own
            # stdout to the one summary object (--json contract).
            with contextlib.redirect_stdout(io.StringIO()):
                try:
                    return cli_main(["map", "--store", chaos_store,
                                     "--verify"])
                except SystemExit as e:
                    return int(e.code or 0)

        if cli_verify() != 0:
            failures.append("pbt map --verify failed on the intact "
                            "chaos store")
        victim = sorted(dg_chaos.values())[0]
        vpath = store.object_path(victim)
        backup = vpath + ".backup"
        shutil.copyfile(vpath, backup)
        flip_byte(vpath)
        if cli_verify() == 0:
            failures.append("pbt map --verify MISSED a flipped byte")
        else:
            rep = verify_store(chaos_store)
            if not any(c["reason"] == "digest_mismatch"
                       for c in rep["corrupt"]):
                failures.append("flipped byte not typed digest_mismatch:"
                                f" {rep['corrupt']}")
        os.replace(backup, vpath)
        shutil.copyfile(vpath, backup)
        os.remove(vpath)
        if cli_verify() == 0:
            failures.append("pbt map --verify MISSED a deleted block")
        else:
            rep = verify_store(chaos_store)
            if not any(h["digest"] == victim for h in rep["holes"]):
                failures.append(f"deleted block not reported as a hole: "
                                f"{rep['holes']}")
        os.replace(backup, vpath)
        if cli_verify() != 0:
            failures.append("chaos store did not verify clean after "
                            "restoring the mauled object")

    summary = {
        "corpus": args.corpus,
        "shards": NUM_SHARDS,
        "blocks": (chaos_rep or {}).get("blocks_checked"),
        "embedded": (chaos_rep or {}).get("embedded"),
        "quarantined": (chaos_rep or {}).get("quarantined"),
        "rework_blocks": rework,
        "window_rework_blocks": window_rework,
        "retries": retries,
        "torn_block": (torn_digest or "")[:16],
        "wall_s": round(time.monotonic() - t0, 1),
        "outdir": outdir,
        "failures": failures,
        "ok": not failures,
    }
    if args.bench_events and not failures:
        # Throughput capture for the trajectory sentinel: seqs/s of the
        # CONTROL run (uninterrupted — the honest rate), platform-split
        # like every other capture.
        from proteinbert_tpu.obs import EventLog

        ctrl_end = [r for r in read_events(evc, strict=True)
                    if r["event"] == "map_end"][-1]
        elog = EventLog(args.bench_events)
        # overlap_ratio rides the control map_end stats (ISSUE 19):
        # overlapped-commit seconds / total commit seconds for the
        # pipelined drive loop — honestly near-meaningless on CPU
        # wall-clock terms but the sentinel tracks it platform-split.
        elog.emit("note", source="map_drill", kind="map_capture",
                  platform="cpu",
                  map_seqs_per_s=ctrl_end["stats"]["seqs_per_s"],
                  map_overlap_ratio=ctrl_end["stats"].get(
                      "overlap_ratio", 0.0),
                  blocks=ctrl_end["stats"]["blocks"],
                  seqs=ctrl_end["stats"]["seqs"],
                  corpus=args.corpus)
        elog.close()
        summary["map_seqs_per_s"] = ctrl_end["stats"]["seqs_per_s"]
        summary["map_overlap_ratio"] = ctrl_end["stats"].get(
            "overlap_ratio", 0.0)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--corpus", type=int, default=44,
                    help="corpus size (2 shards x 3 blocks at the "
                         "default geometry)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--outdir", help="artifact dir (default: temp)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object only")
    ap.add_argument("--bench-events",
                    help="append a note(kind=map_capture) throughput "
                         "record to this bench events stream "
                         "(tools/bench_trajectory.py fits the "
                         "map_seqs_per_s series from it)")
    args = ap.parse_args(argv)
    if args.corpus < 3 * NUM_SHARDS * BLOCK_SIZE - BLOCK_SIZE + 1:
        ap.error(f"--corpus must give every shard >= 3 blocks "
                 f"(>= {3 * NUM_SHARDS * BLOCK_SIZE - BLOCK_SIZE + 1})")
    summary = run_drill(args)
    if args.json:
        print(json.dumps(summary))
    else:
        print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("MAP DRILL FAILED:", "; ".join(summary["failures"]),
              file=sys.stderr)
        return 1
    print(f"map drill OK: SIGKILL mid-block + torn cursor + torn block "
          f"+ poison + transient failure → byte-identical store, "
          f"{summary['rework_blocks']} re-worked block(s) "
          f"(bound {NUM_SHARDS}), {summary['quarantined']} quarantined, "
          f"{summary['retries']} retries, --verify catches "
          f"flip/hole ({summary['wall_s']}s)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
