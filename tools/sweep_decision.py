"""Decide the scan-boundary-lever question from the persisted sweep.

VERDICT r3 item 1's closure condition: once `bench_last_tpu.json` holds
rows for every SCAN_VARIANTS lever (`remat-convs-u2/-u3/-st/-u2st`) at
the north-star shape (1024/256),
either a variant WINS — flip the preset defaults and re-run the trace
attribution — or none does and the null result gets recorded and the
knobs stay documented as experimental. This tool turns the persisted
rows into that decision deterministically, so the call is the data's,
not the operator's mood: a variant must beat the same-shape
`remat-convs` baseline by >WIN_THRESHOLD (default 1.5% — roughly 3x the
observed re-measurement noise at this shape, BASELINE.md's 563-565k
band) to flip anything.

Usage: python tools/sweep_decision.py   # prints one JSON line
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WIN_THRESHOLD = float(os.environ.get("PBT_SWEEP_WIN_THRESHOLD", 0.015))

BASELINE_KEY = ("remat-convs", 1024, 256)
SCAN_VARIANTS = ("remat-convs-u2", "remat-convs-u3", "remat-convs-st",
                 "remat-convs-u2st")
PROVENANCE = (("large", 1024, 32), ("large", 1024, 64), ("long", 2048, 32))


def main() -> int:
    # argv[1] overrides the data path (tests point it at fixtures).
    path = (sys.argv[1] if len(sys.argv) > 1
            else os.path.join(REPO, "bench_last_tpu.json"))
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        print(json.dumps({"decision": "no-data", "error": str(e)}))
        return 1
    rows = {(r["variant"], r["seq_len"], r["batch"]): r
            for r in rec.get("sweep", [])}
    base = rows.get(BASELINE_KEY)
    out = {
        "baseline": base,
        "threshold": WIN_THRESHOLD,
        "scan_variants": {},
        "provenance_rows": {
            "/".join(map(str, k)): (rows[k]["mfu"] if k in rows else None)
            for k in PROVENANCE},
    }
    if base is None:
        out["decision"] = "no-baseline"
        print(json.dumps(out))
        return 1
    best_name, best_gain = None, 0.0
    measured = 0
    for name in SCAN_VARIANTS:
        r = rows.get((name, 1024, 256))
        if r is None:
            out["scan_variants"][name] = None
            continue
        measured += 1
        gain = r["residues_per_sec"] / base["residues_per_sec"] - 1.0
        out["scan_variants"][name] = {
            "mfu": r["mfu"], "gain_vs_baseline": round(gain, 4),
            "captured_at": r.get("captured_at")}
        if gain > best_gain:
            best_name, best_gain = name, gain
    if best_name is not None and best_gain > WIN_THRESHOLD:
        # A measured winner is decisive even if a sibling variant is
        # still missing — flipping to a >threshold improvement cannot
        # be invalidated by the unmeasured row (at worst it wins more).
        out["decision"] = f"flip-default:{best_name}"
        out["action"] = (
            f"{best_name} beats remat-convs by {best_gain:+.1%}: set the "
            "base/long preset scan knob accordingly, re-run "
            "tools/trace_attribution.py to confirm the scan-boundary "
            "cost shrank, and update docs/performance.md")
    elif measured == 0:
        out["decision"] = "unmeasured"
    elif measured < len(SCAN_VARIANTS):
        # A NULL close needs every lever measured (the docstring's
        # closure condition): an unmeasured variant could still clear
        # the bar, so keep the question open.
        out["decision"] = "partially-measured"
        out["action"] = (
            "no measured variant clears the threshold but "
            f"{len(SCAN_VARIANTS) - measured} of {len(SCAN_VARIANTS)} "
            "scan rows are still missing — keep the sweep queued")
    else:
        out["decision"] = "null-result"
        out["action"] = (
            "no scan variant clears the threshold: record the null "
            "result in docs/performance.md and BASELINE.md; knobs stay "
            "experimental, defaults stay scan_unroll=1/_st=False")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
