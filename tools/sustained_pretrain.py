"""Sustained base-preset pretrain with a mid-run kill + resume.

VERDICT r2 Missing #2 / item 2: nothing had ever exercised the base
preset's windowed plateau schedule, checkpoint retention, eval cadence,
and NaN watch TOGETHER over thousands of steps — the regime the
reference's `pretrain()` was built for (reference utils.py:220-345) and
where its own latent post-warmup crash hid (utils.py:257-264).

Protocol (real CLI subprocesses throughout):
  1. Build (once) a structured rehearsal HDF5 corpus.
  2. `pretrain --preset base --data corpus.h5` with eval/checkpoint
     cadence and a warmup short enough that most of the run exercises
     the POST-warmup plateau region; metrics stream to a JSONL.
  3. Watch the JSONL; at --kill-at steps send SIGTERM — the trainer's
     GracefulShutdown checkpoints and exits 75 (requeue-me).
  4. Re-launch the identical command; it must resume from the
     checkpoint (skip-batches data fast-forward) and run to completion.
  5. Assert the metrics stream is gapless across the seam, the LR
     actually moved through warmup into the plateau schedule, and
     every value stayed finite; write a summary JSON.

Scales: --scale mini (tiny preset, CPU, ~2 min — validates this
script's kill/resume machinery) or --scale full (the recorded ≥5000
step base-preset run; needs the TPU).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCALES = {
    "mini": dict(preset="tiny", steps=120, kill_at=50, warmup=20,
                 log_every=5, eval_every=25, ckpt_every=25,
                 corpus=512, batch=None, seq_len=None, max_len=120),
    "full": dict(preset="base", steps=5000, kill_at=2600, warmup=500,
                 log_every=25, eval_every=500, ckpt_every=500,
                 corpus=16384, batch=None, seq_len=None, max_len=500),
}


def build_corpus(path, rows, max_len, num_annotations=512):
    if os.path.exists(path):
        print(f"corpus exists: {path}", file=sys.stderr)
        return
    import numpy as np

    from examples.transfer_experiment import write_corpus_h5
    from proteinbert_tpu.data.synthetic import make_structured_proteins

    t0 = time.time()
    seqs, ann, _ = make_structured_proteins(
        rows, np.random.default_rng(11), num_annotations=num_annotations,
        max_len=max_len)
    write_corpus_h5(path, seqs, ann)
    print(f"built corpus {path}: {rows} rows in {time.time()-t0:.0f}s",
          file=sys.stderr)


def launch(cmd, log_path):
    logf = open(log_path, "a")
    return subprocess.Popen(cmd, cwd=REPO, stdout=logf, stderr=logf), logf


def last_step(jsonl):
    try:
        with open(jsonl) as f:
            lines = f.read().strip().splitlines()
        for line in reversed(lines):
            try:
                return json.loads(line).get("step", 0)
            except ValueError:
                continue
    except OSError:
        pass
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=SCALES, default="mini")
    ap.add_argument("--outdir", default=os.path.join(REPO, "sustained_run"))
    ap.add_argument("--steps", type=int)
    ap.add_argument("--kill-at", type=int, dest="kill_at")
    ap.add_argument("--platform", choices=("cpu", "tpu", "axon"),
                    help="forwarded to the CLI; defaults to cpu at "
                         "--scale mini (a dead TPU tunnel otherwise "
                         "hangs the subprocess at device init)")
    ap.add_argument("--set", action="append", default=[], dest="extra_set",
                    metavar="KEY=VAL",
                    help="extra config overrides appended AFTER the "
                         "built-in ones (later wins in the CLI) — e.g. "
                         "the fault drills pin checkpoint.overlap=false "
                         "so the stager thread's CPU contention cannot "
                         "noise the window stream they assert on")
    args = ap.parse_args()
    platform = args.platform or ("cpu" if args.scale == "mini" else None)
    S = dict(SCALES[args.scale])
    if args.steps:
        S["steps"] = args.steps
    if args.kill_at:
        S["kill_at"] = args.kill_at
    os.makedirs(args.outdir, exist_ok=True)

    corpus = os.path.join(args.outdir, "corpus.h5")
    build_corpus(corpus, S["corpus"], S["max_len"])

    run_dir = os.path.join(args.outdir, "run")
    jsonl = os.path.join(args.outdir, "metrics.jsonl")
    hist = os.path.join(args.outdir, "history.json")
    log_path = os.path.join(args.outdir, "cli.log")
    cmd = [sys.executable, "-m", "proteinbert_tpu",
           *(["--platform", platform] if platform else []),
           "pretrain",
           "--preset", S["preset"], "--data", corpus,
           "--eval-frac", "0.02",
           "--checkpoint-dir", run_dir,
           "--metrics-jsonl", jsonl,
           "--history-json", hist,
           "--set", "mesh.data=1",
           "--set", f"train.max_steps={S['steps']}",
           "--set", f"optimizer.warmup_steps={S['warmup']}",
           "--set", f"train.log_every={S['log_every']}",
           "--set", f"train.eval_every={S['eval_every']}",
           "--set", f"checkpoint.every_steps={S['ckpt_every']}",
           # Eval-keyed plateau (VERDICT r3 Weak #5): the r3 run's eval
           # rose for 1,500 steps while the train-loss plateau held LR
           # flat. One observation per eval interval; patience 3 evals
           # so a sustained-run-scale stall CAN cut within the run.
           # (early_stop is NOT drilled here — it would end the run
           # early and break the gapless-stream assertions below.)
           "--set", "optimizer.plateau_metric=eval_loss",
           "--set", f"optimizer.plateau_window={S['eval_every']}",
           "--set", "optimizer.plateau_patience=3",
           "--set", "optimizer.plateau_cooldown=2",
           # Warm-start save (round 5): the r3 attribution charged the
           # first cadenced save's one-time orbax setup + device→host
           # fetch with the 650-800 collapse stretch; paying it
           # pre-timer makes this run a direct test of the mitigation —
           # its window stream should show only the steady per-boundary
           # cost, ckpt_in_flight-latched.
           "--set", "checkpoint.warm_start=true"]
    for kv in args.extra_set:
        cmd += ["--set", kv]

    # ---- phase 1: run until kill_at, then SIGTERM (preemption drill)
    print("+ " + " ".join(cmd[2:]), file=sys.stderr, flush=True)
    proc, logf = launch(cmd, log_path)
    killed_at = None
    while proc.poll() is None:
        # Tight poll: with a warm persistent compile cache the mini run
        # crosses kill_at → completion in well under a second, and a
        # coarse (2 s) poll then lands the SIGTERM in interpreter
        # teardown — AFTER GracefulShutdown restored default handlers —
        # killing the drill with rc -15 instead of drilling anything.
        time.sleep(0.1)
        step = last_step(jsonl)
        if step >= S["kill_at"]:
            print(f"[drill] step {step} >= {S['kill_at']}: SIGTERM",
                  file=sys.stderr, flush=True)
            proc.send_signal(signal.SIGTERM)
            killed_at = step
            break
    rc1 = proc.wait()
    logf.close()
    if killed_at is None:
        raise SystemExit(
            f"run finished (rc {rc1}) before reaching kill_at="
            f"{S['kill_at']} — nothing was drilled; see {log_path}")
    if rc1 != 75:
        raise SystemExit(
            f"expected preemption exit code 75, got {rc1}; see {log_path}")

    # ---- phase 2: identical command; must resume and complete
    proc, logf = launch(cmd, log_path)
    rc2 = proc.wait()
    logf.close()
    if rc2 != 0:
        raise SystemExit(f"resumed run failed rc={rc2}; see {log_path}")

    # ---- verify the stream
    records = []
    with open(jsonl) as f:
        for line in f:
            try:
                records.append(json.loads(line))
            except ValueError:
                pass
    train_recs = [r for r in records if "loss" in r and "lr" in r]
    steps = [r["step"] for r in train_recs]
    expect = list(range(S["log_every"], S["steps"] + 1, S["log_every"]))
    # The seam step may be logged twice (once per phase, the resumed run
    # recomputes the partial window) — dedupe keeping the LAST record.
    dedup = {r["step"]: r for r in train_recs}
    missing = [s for s in expect if s not in dedup]
    assert not missing, f"gaps in metrics stream at steps {missing}"
    assert all(
        r["loss"] == r["loss"] and r["loss"] is not None
        for r in train_recs), "non-finite loss logged"
    lrs = [dedup[s]["lr"] for s in expect]
    warm_end_idx = max(i for i, s in enumerate(expect) if s <= S["warmup"])
    assert lrs[0] < lrs[warm_end_idx], \
        f"LR never warmed up: {lrs[0]} -> {lrs[warm_end_idx]}"
    evals = [r for r in records if "eval_loss" in r]
    assert evals, "no eval records"

    first, last = dedup[expect[0]], dedup[expect[-1]]
    # Windowed (since-last-log) throughput: the per-window stream is
    # what localizes a transient stall (VERDICT r3 Weak #1/#2 — the r3
    # collapse was invisible behind the cumulative rate). Slow windows
    # are reported with their wall-clock stamps so they can be
    # correlated with ckpt/eval cadence and external (tunnel) events.
    wins = [(s, dedup[s]["window_mfu"], dedup[s].get("t"))
            for s in expect if dedup[s].get("window_mfu") is not None]
    window_report = None
    if wins:
        vals = sorted(w for _, w, _ in wins)
        med = vals[len(vals) // 2]
        slow = [(s, w, t) for s, w, t in wins if w < 0.5 * med]
        window_report = {
            "median_mfu": med,
            "min_mfu": vals[0], "max_mfu": vals[-1],
            "slow_windows": [(s, round(w, 4), t) for s, w, t in slow],
            # Attribution: which slow windows a checkpoint save
            # overlapped (the r3 collapse suspect).
            "slow_with_ckpt_in_flight": [
                s for s, _, _ in slow if dedup[s].get("ckpt_in_flight")],
        }
    # LR cuts (plateau firing): consecutive post-warmup logged LRs
    # dropping by ≥2x.
    lr_cuts = [expect[i] for i in range(1, len(expect))
               if expect[i] > S["warmup"]
               and dedup[expect[i - 1]]["lr"] > 0
               and dedup[expect[i]]["lr"]
               < 0.55 * dedup[expect[i - 1]]["lr"]]
    summary = {
        "scale": args.scale, "steps": S["steps"], "killed_at": killed_at,
        "resume_rc": (rc1, rc2),
        "first_loss": first["loss"], "final_loss": last["loss"],
        "final_lr": last["lr"],
        "eval_losses": [(r["step"], r["eval_loss"]) for r in evals],
        "final_mfu": last.get("mfu"),
        "res_per_sec": last.get("residues_per_sec_per_chip"),
        # Cumulative seconds of checkpoint fetch+write that ran HIDDEN
        # behind training (StepTimer.overlap) — the boundary cost the
        # overlapped pipeline removed from the wall clock; None on
        # streams recorded before round 6.
        "overlapped_boundary_s": last.get("overlap_s"),
        "windows": window_report,
        "lr_cuts_at": lr_cuts,
        "seam": {
            "killed_at": killed_at,
            "loss_before": dedup[max(s for s in expect
                                     if s <= killed_at)]["loss"],
            "loss_after": dedup[min(s for s in expect
                                    if s > killed_at)]["loss"],
        },
    }
    out = os.path.join(args.outdir, "sustained_summary.json")
    with open(out, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
