"""Step-time attribution from a jax.profiler device trace.

The docs/performance.md method, made a runnable tool (VERDICT r2 item 8:
"chase the next MFU step with the trace, not intuition"): capture a
device trace of a few train steps, parse the perfetto trace.json.gz the
profiler writes (plain JSON — no TF/tensorboard dependency), aggregate
device-lane event durations per HLO op name, and report the top ops
plus a category rollup (convs, dots, dynamic-update-slice saves, layout
transposes/copies, collectives, elementwise fusions) normalized per
step. The categories map directly onto the knobs: remat policy (saves),
scan boundaries (transposes), sharding (collectives).

Usage (real chip):
  python tools/trace_attribution.py --seq-len 1024 --batch 256 --steps 3
CPU smoke:
  PBT_TRACE_CPU=1 python tools/trace_attribution.py --tiny --steps 2
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CATEGORIES = (
    # NB: no bare "conv" key — it would swallow HLO "convert" cast ops.
    ("convolution", ("convolution",)),
    ("dot/matmul", ("dot", "gemm", "matmul")),
    ("dynamic-update-slice (scan saves)", ("dynamic-update-slice",
                                           "dynamic_update_slice")),
    ("transpose/copy (layout)", ("transpose", "copy", "bitcast")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "collective", "psum")),
    ("reduce/softmax", ("reduce", "softmax")),
    ("rng/corruption", ("rng", "threefry", "bernoulli")),
)


def categorize(name: str) -> str:
    low = name.lower()
    for cat, keys in CATEGORIES:
        if any(k in low for k in keys):
            return cat
    return "other fusions/elementwise"


def parse_trace(trace_dir: str):
    """{op name: total device-lane µs} from the newest trace-event JSON
    — a jax.profiler `*.trace.json.gz`, or a host-span dump written by
    `obs.tracing.SpanCollector` (`*.trace.json`, optionally .gz): both
    carry the same Perfetto `traceEvents` format, so the telemetry
    subsystem's span dumps and real device traces share one parser. A
    file path is parsed directly; a directory is globbed."""
    if os.path.isfile(trace_dir):
        paths = [trace_dir]
    else:
        paths = sorted(
            glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
            + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                        recursive=True),
            key=os.path.getmtime)
    if not paths:
        raise SystemExit(f"no *.trace.json(.gz) under {trace_dir}")
    opener = gzip.open if paths[-1].endswith(".gz") else open
    with opener(paths[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # Lane discovery. Summing every span in a device pid double-counts:
    # the pid carries an "XLA Modules"/"Steps" lane whose one
    # jit_train_step span covers the whole step ALONGSIDE the per-op
    # "XLA Ops" lane, plus runtime wrapper spans. Prefer threads whose
    # name says "XLA Ops"; only they carry leaf-op attribution.
    pid_name = {}
    tid_name = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            tid_name[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
    device_pids = {p for p, n in pid_name.items()
                   if any(k in n.lower() for k in ("tpu", "device",
                                                   "/device:", "xla"))
                   and "host" not in n.lower()}
    if not device_pids:
        # CPU runs have no device lane; the host lane carries the XLA
        # ops there (smoke mode for this tool — attribution still works,
        # timings just include host scheduling).
        device_pids = {p for p, n in pid_name.items()
                       if "cpu" in n.lower()}
        if device_pids:
            print("note: no TPU lane; attributing the host CPU lane",
                  file=sys.stderr)
    span_dump = False
    if not device_pids:
        # Host-span dump (obs.tracing): a single "host spans" process —
        # attribute every lane present, by SELF time (see below).
        device_pids = {e.get("pid") for e in events if e.get("ph") == "X"}
        span_dump = True
        if device_pids:
            print("note: no device/CPU lane; attributing all span lanes "
                  "by self-time (host-span dump)", file=sys.stderr)
    op_lanes = {(p, t) for (p, t), n in tid_name.items()
                if p in device_pids and "xla ops" in n.lower()}

    def in_scope(e):
        if e.get("pid") not in device_pids:
            return False
        if op_lanes:
            return (e.get("pid"), e.get("tid")) in op_lanes
        return True

    _WRAPPERS = ("execute", "thunk", "pjitfunction", "parsearguments",
                 "collectgarbage", "lower_sharding", "trace_to_jaxpr",
                 "compile")
    per_op: dict = collections.Counter()
    if span_dump:
        # Host spans NEST (obs.tracing tracks depth): summing raw
        # durations counts a parent's time once for itself and again
        # for every child. Attribute SELF time instead — each span's
        # duration minus its enclosed spans' — via an interval stack
        # per thread lane.
        by_tid: dict = {}
        for e in events:
            if e.get("ph") == "X" and in_scope(e):
                by_tid.setdefault((e.get("pid"), e.get("tid")),
                                  []).append(e)
        for evs in by_tid.values():
            evs.sort(key=lambda e: (e.get("ts", 0), -e.get("dur", 0)))
            stack = []  # (end_ts, name) of still-open enclosing spans
            for e in evs:
                ts, dur = e.get("ts", 0), e.get("dur", 0)
                while stack and stack[-1][0] <= ts:
                    stack.pop()
                name = e.get("name", "?")
                per_op[name] += dur
                if stack:
                    per_op[stack[-1][1]] -= dur  # carve out of parent
                stack.append((ts + dur, name))
    else:
        for e in events:
            if e.get("ph") != "X" or not in_scope(e):
                continue
            name = e.get("name", "?")
            low = name.lower()
            # Host python frames / runtime wrapper spans / "end:"
            # markers enclose the op events — counting them
            # double-counts the step.
            if (name.startswith("$") or ".py:" in name
                    or name.startswith("end:")
                    or any(w in low for w in _WRAPPERS)):
                continue
            per_op[name] += e.get("dur", 0)
    if not per_op:
        lanes = sorted(set(pid_name.values()))
        raise SystemExit(
            f"no XLA op events found; lanes: {lanes}. (CPU-backend "
            "traces often carry only python/runtime spans — op-level "
            "attribution needs the real TPU's 'XLA Ops' lane.)")
    return per_op


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=None,
                    help="steps to trace (default 3); REQUIRED with "
                         "--parse-only, where it must state how many "
                         "steps the existing trace holds")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny model (CPU smoke of the tool itself)")
    ap.add_argument("--use-pallas", action="store_true")
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--outdir", default="/tmp/pbt_trace")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--parse-only", metavar="DIR",
                    help="skip running; parse an existing trace dir")
    args = ap.parse_args()

    if args.parse_only:
        if args.steps is None:
            raise SystemExit("--parse-only needs an explicit --steps "
                             "(the step count of the existing trace; "
                             "ms/step is total/steps)")
        per_op = parse_trace(args.parse_only)
        steps = args.steps
    else:
        args.steps = 3 if args.steps is None else args.steps
        import jax
        import numpy as np

        if os.environ.get("PBT_TRACE_CPU"):
            jax.config.update("jax_platforms", "cpu")

        from proteinbert_tpu.configs import (
            DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
            TrainConfig,
        )
        from proteinbert_tpu.train import create_train_state, train_step
        from proteinbert_tpu.utils.profiling import device_trace

        if args.tiny:
            model = ModelConfig(local_dim=32, global_dim=64, key_dim=16,
                                num_heads=4, num_blocks=2,
                                num_annotations=128, dtype="float32")
            args.batch = min(args.batch, 8)
            args.seq_len = min(args.seq_len, 128)
        else:
            model = ModelConfig(local_dim=512, global_dim=512, key_dim=64,
                                num_heads=8, num_blocks=6, dtype="bfloat16",
                                remat=not args.no_remat,
                                remat_policy="convs",
                                use_pallas=args.use_pallas)
        cfg = PretrainConfig(
            model=model,
            data=DataConfig(seq_len=args.seq_len, batch_size=args.batch),
            optimizer=OptimizerConfig(warmup_steps=100),
            train=TrainConfig(max_steps=args.steps))
        rng = np.random.default_rng(0)
        batch = {
            "tokens": rng.integers(
                4, 26, size=(args.batch, args.seq_len)).astype(np.int32),
            "annotations": (rng.random(
                (args.batch, model.num_annotations)) < 0.01
            ).astype(np.float32),
        }
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        batch = jax.device_put(batch)
        state, m = train_step(state, batch, cfg)  # compile + settle
        float(m["loss"])
        with device_trace(args.outdir):
            for _ in range(args.steps):
                state, m = train_step(state, batch, cfg)
            float(m["loss"])  # hard sync inside the trace window
        per_op = parse_trace(args.outdir)
        steps = args.steps

    total_us = sum(per_op.values())
    cats: dict = collections.Counter()
    for name, us in per_op.items():
        cats[categorize(name)] += us
    print(f"\n== device time: {total_us / 1e3 / steps:.2f} ms/step over "
          f"{steps} steps ==\n")
    print("-- categories --")
    for cat, us in cats.most_common():
        print(f"{us / 1e3 / steps:9.2f} ms/step  {100 * us / total_us:5.1f}%"
              f"  {cat}")
    print(f"\n-- top {args.top} ops --")
    for name, us in per_op.most_common(args.top):
        print(f"{us / 1e3 / steps:9.2f} ms/step  {100 * us / total_us:5.1f}%"
              f"  {name[:110]}")


if __name__ == "__main__":
    main()
