"""Benchmark: base-model pretraining throughput on the available chip(s).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Metric: residues/sec/chip on the BASELINE.json base config (6 blocks,
d=512, seq_len 512) denoising pretrain, synthetic data (the reference has
no published numbers to compare against — BASELINE.md; vs_baseline is
therefore measured MFU / the 0.40 north-star MFU target, so 1.0 means
"hit the ≥40% MFU goal").
"""

import json
import time

import numpy as np


def main():
    import jax

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.train import create_train_state, train_step
    from proteinbert_tpu.train.metrics import (
        peak_flops_per_chip, train_flops,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    # Base config per BASELINE.json configs[1]; batch sized for one chip.
    if on_tpu:
        model = ModelConfig(local_dim=512, global_dim=512, key_dim=64,
                            num_heads=8, num_blocks=6, dtype="bfloat16")
        batch, seq_len, steps = 64, 512, 30
    else:  # CPU fallback so the script always emits its line
        model = ModelConfig(local_dim=64, global_dim=128, key_dim=16,
                            num_heads=4, num_blocks=2, num_annotations=512,
                            dtype="float32")
        batch, seq_len, steps = 8, 128, 5

    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=seq_len, batch_size=batch),
        optimizer=OptimizerConfig(warmup_steps=100),
        train=TrainConfig(max_steps=steps),
    )

    rng = np.random.default_rng(0)
    batch_np = {
        "tokens": rng.integers(4, 26, size=(batch, seq_len)).astype(np.int32),
        "annotations": (rng.random((batch, model.num_annotations)) < 0.01
                        ).astype(np.float32),
    }
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    dbatch = jax.device_put(batch_np)

    # Warmup/compile.
    state, m = train_step(state, dbatch, cfg)
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = train_step(state, dbatch, cfg)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = steps / dt
    residues_per_sec = steps_per_sec * batch * seq_len
    mfu = steps_per_sec * train_flops(model, batch, seq_len) / peak_flops_per_chip()

    print(json.dumps({
        "metric": "residues_per_sec_per_chip",
        "value": round(residues_per_sec, 1),
        "unit": "residues/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
