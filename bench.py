"""Benchmark: base-model pretraining throughput on the available chip(s).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} plus
provenance fields: "platform" (which backend produced the number — a CPU
fallback's 0.009 MFU must never read as a 60x TPU regression, VERDICT r1
Weak #2). When the run has to fall back to CPU but bench_last_tpu.json
holds TPU evidence, the TOP-LEVEL record IS that last-good TPU
measurement, flagged "stale": true with its captured_at timestamp, and
the live CPU number is demoted to "live_fallback" (VERDICT r3 item 5 —
the previous shape buried the TPU record in a nested blob so long the
driver's parser choked on the line).

Metric: residues/sec/chip on the BASELINE.json NORTH-STAR config — the
6-block/d=512 base model at seq_len 1024 ("≥40% MFU ... at seq_len 1024",
BASELINE.json) — denoising pretrain, synthetic data (the reference has
no published numbers to compare against — BASELINE.md; vs_baseline is
therefore measured MFU / the 0.40 north-star MFU target, so 1.0 means
"hit the ≥40% MFU goal"). Rounds 1-2 measured seq_len 512; the sweep
keeps one 512 variant for cross-round continuity.

A small sweep of execution variants is timed and the best reported:
- remat with the "convs" policy at large batch (save the two conv
  outputs per block — ~85% of block FLOPs — and recompute only the
  cheap tail in backward; measured +8% over full remat);
- xla+remat at large batch (full rematerialisation removes the fp32
  LayerNorm saves that otherwise cap batch at 64 on a 16G chip and make
  the non-remat step HBM-bound);
- the Pallas fused local-track kernel (kernels/fused_block.py) at the
  batch its VMEM plan likes — its custom VJP already rematerialises, so
  it runs WITHOUT cfg.remat (pairing them recomputes twice).
A variant that fails to compile is skipped (the bench must always emit
its line). Timing syncs by fetching the loss scalar to host — on the
tunneled single-chip setup `block_until_ready` alone does not await
remote execution, which silently under- or over-reports.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_last_tpu.json")


def variant_timeout() -> int:
    """One definition for parent wait and child self-destruct margin —
    a drifted default would turn every slow variant into a false
    'failed; skipped'."""
    return int(os.environ.get("PBT_BENCH_VARIANT_TIMEOUT", 900))


def stale_warn_hours() -> float:
    """Age past which a promoted stale TPU headline is shouted about
    (VERDICT r4 weak #5): promote-last-good is right for a flapping
    tunnel, but a `stale:true, vs_baseline 1.42` that stays green
    forever could mask a regression introduced after the capture."""
    try:
        return float(os.environ.get("PBT_STALE_WARN_HOURS", 48))
    except ValueError:
        return 48.0


def last_good_captured_at(lg):
    """The HEADLINE row's own measurement stamp from a last-good record,
    falling back to the file-level stamp. A later partial sweep (e.g.
    --only pallas) restamps the file-level captured_at without
    re-measuring the headline shape, so age must be judged from the
    row that actually backs the promoted numbers."""
    row_at = next(
        (r.get("captured_at") for r in lg.get("sweep", [])
         if (r.get("variant"), r.get("seq_len"), r.get("batch"))
         == (lg.get("variant"), lg.get("seq_len"), lg.get("batch"))),
        None)
    return row_at or lg.get("captured_at")


def stale_age_hours(captured_at, now=None):
    """Hours since a `captured_at` stamp (bench's
    %Y-%m-%dT%H:%M:%S%z format), or None when absent/unparseable —
    an unreadable stamp must degrade to 'age unknown', not crash the
    one code path whose whole job is emitting the JSON line."""
    if not captured_at:
        return None
    from datetime import datetime, timezone

    try:
        t = datetime.strptime(captured_at, "%Y-%m-%dT%H:%M:%S%z")
    except (ValueError, TypeError):
        return None
    now = now if now is not None else datetime.now(timezone.utc)
    return max(0.0, (now - t).total_seconds() / 3600.0)


def atomic_json_dump(obj, path):
    """Write-then-rename so a killed writer can't truncate the target —
    bench_last_tpu.json guards the only TPU evidence across tunnel flaps
    and tpu_watch.py SIGKILLs sweeps at its timeout."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=2)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def probe_tpu(timeout=None, attempts=None, retry_wait=None):
    """(tpu_ok, reason) — whether the TPU backend initializes, decided in
    a SUBPROCESS.

    The tunneled axon TPU plugin can hang indefinitely at PJRT client
    creation when the tunnel is down (observed for hours at a time). If
    this process touched jax.devices() directly in that state, the bench
    would never emit its JSON line — so the first backend init happens in
    a killable child, and on timeout/failure the parent forces the CPU
    backend before ITS first jax use. The tunnel also FLAPS (observed
    down for minutes then back), so a timed-out probe retries a few
    times before surrendering the TPU number to the CPU fallback. The
    defaults tolerate a ~15-minute flap (VERDICT r2 item 1: driver
    captures kept landing in the CPU fallback with the shorter r2
    window) while still leaving room for the CPU fallback to emit the
    line under a ~20-minute outer timeout. (Attempts/waits are
    env-tunable: PBT_BENCH_PROBE_ATTEMPTS / _WAIT / _TIMEOUT — but an
    EXPLICIT argument wins over env, so tpu_watch.py's cheap single-probe
    poll survives an operator who exported bench tuning vars.)
    """
    if timeout is None:
        timeout = int(os.environ.get("PBT_BENCH_PROBE_TIMEOUT", 90))
    if attempts is None:
        attempts = int(os.environ.get("PBT_BENCH_PROBE_ATTEMPTS", 6))
    if retry_wait is None:
        retry_wait = int(os.environ.get("PBT_BENCH_PROBE_WAIT", 75))
    reason = "no probe ran"
    for attempt in range(attempts):
        if attempt:
            time.sleep(retry_wait)
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            reason = "backend init timed out (tunnel down?)"
            print(f"TPU probe {attempt + 1}/{attempts}: {reason}",
                  file=sys.stderr)
            continue
        if out.returncode != 0:
            # Deterministic failure (broken install, missing plugin) —
            # retrying would only add minutes of sleeps; only hangs
            # (= possible tunnel flaps) are worth waiting out.
            return False, f"backend init failed (rc {out.returncode})"
        platform = out.stdout.strip()
        return platform == "tpu", f"backend platform is {platform!r}"
    return False, reason


def force_cpu_backend() -> None:
    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def enable_compile_cache() -> None:
    """Persistent XLA compilation cache for bench processes.

    With one subprocess per variant, every child pays its own compile
    (~20-40 s through the tunnel). The cache keys by HLO+config, so a
    re-measured variant — the common case across tunnel windows, watch
    sweeps, and the driver's round-end capture — skips straight to
    measurement. Must go through the config API before any device use
    (env vars are read at interpreter start by the axon sitecustomize,
    same constraint as tests/conftest.py).

    SKIPPED on jax 0.4.x CPU runs with donation active: that version's
    CPU backend is unsafe with cache-deserialized executables under
    buffer donation — observed as segfaults AND silent wrong numerics
    (tests/conftest.py has the full account). With PBT_DISABLE_DONATION
    set (the test harness does) the cache is safe and stays on; the TPU
    sweep children keep it unconditionally."""
    import jax

    from proteinbert_tpu.utils.compat import has_num_cpu_devices_option

    if (not has_num_cpu_devices_option()
            and os.environ.get("JAX_PLATFORMS", "") == "cpu"
            and not os.environ.get("PBT_DISABLE_DONATION")):
        print("persistent compile cache disabled (jax 0.4.x CPU: "
              "cache-deserialized executables are donation-unsafe; set "
              "PBT_DISABLE_DONATION=1 to trade donation for the cache)",
              file=sys.stderr)
        return

    # An operator- or CI-provided cache dir wins: overriding it would
    # split the warm cache and re-pay exactly the compiles it holds.
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_bench_cache"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        # LRU-bound the dir: sweep configs drift every round, and without
        # eviction the repo-local cache grows by stale executables forever.
        jax.config.update("jax_compilation_cache_max_size",
                          2 * 1024 * 1024 * 1024)
    except Exception as e:  # cache is an accelerant, never a blocker
        print(f"compile cache unavailable: {e}", file=sys.stderr)


def build_record(best, platform):
    res_per_sec, mfu, name, seq_len, batch = best
    return {
        "metric": "residues_per_sec_per_chip",
        "value": round(res_per_sec, 1),
        "unit": "residues/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "platform": platform,
        # Full shape provenance: the 512-seq continuity variant is within
        # ~1.5% of the 1024 north-star shape, and a record without
        # seq/batch could pass one off as the other on a noisy run.
        "variant": name,
        "seq_len": seq_len,
        "batch": batch,
    }


def persist_last_good(sweep):
    """Merge this sweep into the last-good-TPU record and write it.

    MERGE, don't overwrite (full sweep per VERDICT r2 item 1): rows are
    keyed by (variant, seq_len, batch); a re-measured shape replaces its
    old row, shapes not reached this sweep keep their previous numbers
    and timestamps. A mid-sweep tunnel drop therefore can only ADD
    evidence — a 1-variant partial run never demotes a stronger,
    completer record. The headline fields report the best merged row.
    """
    now = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    rows = {}
    try:
        with open(LAST_GOOD_PATH) as f:
            old = json.load(f)
        if old.get("platform") == "tpu":
            for r in old.get("sweep", []):
                rows[(r["variant"], r["seq_len"], r["batch"])] = r
            if not old.get("sweep") and "variant" in old:
                # Legacy (round-2) record: headline only, no sweep and
                # no shape fields — keep it as a row so its evidence
                # survives until every shape is re-measured.
                rows[(old["variant"], old.get("seq_len"),
                      old.get("batch"))] = {
                    "variant": old["variant"],
                    "seq_len": old.get("seq_len"),
                    "batch": old.get("batch"),
                    "residues_per_sec": old["value"],
                    "mfu": round(old["vs_baseline"] * 0.40, 4),
                    "captured_at": old.get("captured_at"),
                }
    except (OSError, ValueError):
        pass
    for r in sweep:
        rows[(r["variant"], r["seq_len"], r["batch"])] = {
            **r, "captured_at": now}
    merged = sorted(rows.values(),
                    key=lambda r: -r["residues_per_sec"])
    top = merged[0]
    best = (top["residues_per_sec"], top["mfu"], top["variant"],
            top["seq_len"], top["batch"])
    try:
        atomic_json_dump({**build_record(best, "tpu"), "sweep": merged,
                          "captured_at": now}, LAST_GOOD_PATH)
    except OSError as e:
        print(f"could not persist last-good TPU record: {e}",
              file=sys.stderr)
    # Mirror the capture onto the shared telemetry stream (obs `note`
    # events, same schema as training runs) so sweep history is readable
    # by pbt diagnose / validate_events instead of a private format.
    try:
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="sweep_capture",
                rows=len(merged), best_variant=top["variant"],
                best_residues_per_sec=top["residues_per_sec"],
                best_mfu=top["mfu"])
        ev.close()
    except Exception as e:  # stream is best-effort, the record is safe
        print(f"bench events stream unavailable: {e}", file=sys.stderr)


def time_step(cfg, batch_np, steps):
    """ms/step with a device→host scalar fetch as the hard sync."""
    import jax

    from proteinbert_tpu.train import create_train_state, train_step

    state = create_train_state(jax.random.PRNGKey(0), cfg)
    dbatch = jax.device_put(batch_np)

    state, m = train_step(state, dbatch, cfg)  # compile
    float(m["loss"])
    for _ in range(3):  # settle caches / power state
        state, m = train_step(state, dbatch, cfg)
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = train_step(state, dbatch, cfg)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps


def build_variants(on_tpu, gate_pallas=True):
    """The variant list, as (name, model_cfg, seq_len, batch) plus the
    timing-step count — in a function so the parent sweep process and a
    `--run-index` child (which re-derives the list instead of having a
    config pickled at it) agree on indices by construction. Pallas
    variants whose shape has no VMEM plan are filtered HERE so indices
    refer to the gated list in both processes.

    gate_pallas=False skips that filter (and with it the only jax
    import on this path) so tpu_watch.py can size its sweep timeout
    from the variant COUNT without touching the backend — the ungated
    count is an upper bound, which is exactly what a timeout needs."""
    from proteinbert_tpu.configs import ModelConfig

    if on_tpu:
        base = ModelConfig(local_dim=512, global_dim=512, key_dim=64,
                           num_heads=8, num_blocks=6, dtype="bfloat16")
        convs = dataclasses.replace(base, remat=True, remat_policy="convs")
        # ORDER = PRIORITY: the tunnel can drop mid-sweep and the parent
        # persists after every variant, so the variants a short window
        # must refresh come first — the north-star shape and the
        # headline long-context row, then the large/long provenance
        # rows, then the settled scan-boundary levers (measured round 5,
        # null result — kept as regression rows, no longer urgent) and
        # the re-confirmation shapes.
        variants = [  # (name, model, seq_len, batch)
            # North-star shape: seq_len 1024 (same tokens/step as 512@512).
            ("remat-convs", convs, 1024, 256),
        ]
        # Large (12-block/d=1024) and long-context (L=2048) preset shapes
        # at their measured-best single-chip batches, so the flagship
        # BASELINE.md claims (0.69 MFU Large, 0.57 long) get timestamped
        # machine-readable provenance in bench_last_tpu.json instead of
        # living only in round-2 prose (VERDICT r3 Weak #3). Small
        # batches keep each row inside the per-variant timeout. The
        # models come FROM the presets so a preset change can never make
        # these rows silently certify a different shape than they claim.
        from proteinbert_tpu.configs import get_preset

        variants += [
            # The repo headline row (fastest measured shape) right after
            # the north-star: a short window refreshes both.
            ("long", get_preset("long").model, 8192, 8),
            ("large", get_preset("large").model, 1024, 32),
            ("large", get_preset("large").model, 1024, 64),
            # The rest of the single-chip long-context curve — 2048/32,
            # 4096/16, and 16384/4 are iso-tokens/step with 8192/8
            # (65,536; the 2048/64 row is the double-batch point, NOT
            # part of the iso curve): the model is position-embedding-
            # free (conv local track + global attention), so L extends
            # freely (flat MFU through 8192; the 16384 row marks the
            # B=4 batch floor where the seq-parallel path takes over).
            ("long", get_preset("long").model, 2048, 32),
            ("long", get_preset("long").model, 2048, 64),
            ("long", get_preset("long").model, 4096, 16),
            ("long", get_preset("long").model, 16384, 4),
        ]
        variants += [
            # Scan-boundary levers: measured round 5 at the north-star
            # shape, NULL result (st -0.1%, u2 -5.4%, u3 -6.8%, u2st
            # -5.2% — sweep_decision.py records the call). Kept as
            # regression rows so a compiler upgrade that flips the
            # trade shows up in the sweep; no longer priority-ordered.
            ("remat-convs-u2",
             dataclasses.replace(convs, scan_unroll=2), 1024, 256),
            ("remat-convs-u3",
             dataclasses.replace(convs, scan_unroll=3), 1024, 256),
            ("remat-convs-st",
             dataclasses.replace(convs, scan_split_transpose=True),
             1024, 256),
            ("remat-convs-u2st",
             dataclasses.replace(convs, scan_unroll=2,
                                 scan_split_transpose=True),
             1024, 256),
            # Batch is the biggest lever (docs/performance.md); push the
            # north-star shape until HBM says stop — the in-loop skip
            # keeps an OOM from killing the sweep.
            ("remat-convs", convs, 1024, 128),
            ("remat-convs", convs, 1024, 384),
            ("remat-convs", convs, 1024, 512),
            # Full remat at the same shape so the convs-policy comparison
            # stays same-batch (ADVICE r1).
            ("xla-remat", dataclasses.replace(base, remat=True), 1024, 256),
            # Cross-round continuity with the rounds-1/2 seq_len-512 record.
            ("remat-convs", convs, 512, 512),
            ("remat-convs", convs, 512, 256),
            # Pallas at its supported shape (C=512/L=512: full weights
            # VMEM-resident — the kernel's official scope, BASELINE.md).
            # At L=1024 pallas_supported is False and use_pallas would
            # silently bench the XLA fallback, so it is gated below.
            # B=256/512 rows answer VERDICT r2 item 3's same-batch
            # kernel-vs-remat-convs question (the VJP saves only
            # (params, x, broadcast) — nothing forbids large B).
            ("pallas", dataclasses.replace(base, use_pallas=True), 512, 64),
            ("pallas", dataclasses.replace(base, use_pallas=True), 512, 256),
            ("pallas", dataclasses.replace(base, use_pallas=True), 512, 512),
        ]
        steps = 15
        if gate_pallas:
            from proteinbert_tpu.kernels import pallas_supported

            variants = [
                v for v in variants
                if not (v[1].use_pallas
                        and not pallas_supported(v[1].local_dim, v[2],
                                                 v[1].dtype))
            ]
    else:  # CPU fallback so the script always emits its line
        base = ModelConfig(local_dim=64, global_dim=128, key_dim=16,
                           num_heads=4, num_blocks=2, num_annotations=512,
                           dtype="float32")
        variants = [("xla", base, 128, 8)]
        steps = 5
    return variants, steps


def run_variant(index, on_tpu):
    """Measure ONE variant in-process and return its sweep row (with the
    backend platform that actually executed it).

    This is the `--run-index` child body: the parent sweep runs each
    variant in a killable subprocess so a single pathological case — a
    remote AOT compile that never returns on a dropped tunnel, observed
    to eat 20+ minutes of a tunnel-up window — costs at most
    PBT_BENCH_VARIANT_TIMEOUT seconds instead of the whole capture."""
    import jax

    enable_compile_cache()

    from proteinbert_tpu.configs import (
        DataConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.train.metrics import (
        peak_flops_per_chip, train_flops,
    )

    variants, steps = build_variants(on_tpu)
    name, model, seq_len, batch = variants[index]
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=seq_len, batch_size=batch),
        optimizer=OptimizerConfig(warmup_steps=100),
        train=TrainConfig(max_steps=steps),
    )
    rng = np.random.default_rng(index)
    batch_np = {
        "tokens": rng.integers(4, 26, size=(batch, seq_len)
                               ).astype(np.int32),
        "annotations": (rng.random((batch, model.num_annotations)) < 0.01
                        ).astype(np.float32),
    }
    dt = time_step(cfg, batch_np, steps)
    res_per_sec = batch * seq_len / dt
    # MFU from the ACTUAL per-batch FLOPs (non-pad tokens), not the
    # padded shape — identical for this all-real synthetic batch, but
    # the denominator is now honest for any future padded row (the
    # --pack bench relies on the same fix).
    mfu = (train_flops(model, batch, seq_len,
                       nonpad_tokens=int((batch_np["tokens"] != 0).sum()))
           / dt / peak_flops_per_chip())
    print(f"variant={name} seq={seq_len} batch={batch}: "
          f"{dt * 1e3:.1f} ms/step "
          f"res/s={res_per_sec:,.0f} MFU={mfu:.3f}", file=sys.stderr)
    return {
        "variant": name, "seq_len": seq_len, "batch": batch,
        "ms_per_step": round(dt * 1e3, 2),
        "residues_per_sec": round(res_per_sec, 1),
        "mfu": round(mfu, 4),
        # Gate field for the parent's persist: if the tunnel dropped
        # between probe and this child's first jax use and the backend
        # fell back, stamping these numbers "tpu" would fabricate the
        # record.
        "platform": jax.devices()[0].platform,
    }


def run_boundary():
    """`bench.py --boundary`: train-stream stall seconds per checkpoint
    boundary, synchronous vs overlapped, on CPU — so the overlap win is
    CI-measurable without a TPU tunnel. Emits ONE JSON line.

    The measured quantity is the host-side stall: how long the dispatch
    loop stands inside the boundary instead of enqueuing train steps.
    Both modes drain (fetch the loss) BEFORE the measured region — the
    drain is train work, not boundary cost — then time:
      sync:       device→host fetch + orbax save call
      overlapped: on-device snapshot dispatch + stager handoff
    The overlapped stage is flushed between boundaries OUTSIDE the
    measured region (its fetch+write runs behind the inter-boundary
    train steps, exactly as in the trainer), and its hidden seconds are
    reported as overlap_hidden_s_per_boundary.
    """
    import shutil
    import tempfile

    import jax

    force_cpu_backend()
    enable_compile_cache()

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.train import (
        Checkpointer, create_train_state, snapshot_train_state, train_step,
    )
    from proteinbert_tpu.utils.profiling import BoundaryStallMeter

    # Default 5: an odd sample count makes the median a real middle
    # element, not the upper of two — the gate statistic on a noisy
    # shared-CPU host.
    boundaries = int(os.environ.get("PBT_BOUNDARY_BENCH_BOUNDARIES", 5))
    steps_between = int(os.environ.get("PBT_BOUNDARY_BENCH_STEPS", 8))
    # Big enough that the sync fetch+save is a measurable host cost on
    # CPU (tens of MB of fp32 params + 2x Adam moments), small enough to
    # stay comfortably inside CI memory. PBT_BOUNDARY_BENCH_DIM scales
    # the shape down for plumbing tests (compile time dominates there);
    # the ≥5x acceptance claim is the default-size run.
    dim = int(os.environ.get("PBT_BOUNDARY_BENCH_DIM", 96))
    model = ModelConfig(local_dim=dim, global_dim=2 * dim, key_dim=16,
                        num_heads=4, num_blocks=2,
                        num_annotations=max(32 * dim, 512),
                        dtype="float32")
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=128, batch_size=8),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=10_000),
    )
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(4, 26, size=(8, 128)).astype(np.int32),
        "annotations": (rng.random((8, model.num_annotations)) < 0.01
                        ).astype(np.float32),
    }

    def run_mode(overlapped):
        tmp = tempfile.mkdtemp(prefix="pbt_boundary_bench_")
        ck = Checkpointer(os.path.join(tmp, "ck"), max_to_keep=2,
                          async_save=True)
        meter = BoundaryStallMeter()
        hidden = []
        try:
            state = create_train_state(jax.random.PRNGKey(0), cfg)
            state, m = train_step(state, batch, cfg)  # compile
            float(m["loss"])
            # Untimed warmup boundary: the FIRST save pays one-time
            # orbax directory init + thread spinup (and the snapshot
            # jit's compile) — the warm_start story; both modes must be
            # measured at their steady per-boundary cost.
            if overlapped:
                ck.save_staged(1, snapshot_train_state(state))
                ck.flush_staged()
            else:
                ck.save(1, jax.device_get(state))
            ck.wait()
            step = 1
            for _ in range(boundaries):
                for _ in range(steps_between):
                    state, m = train_step(state, batch, cfg)
                    step += 1
                # A production cadence puts minutes of steps between
                # boundaries; the smoke steps here are milliseconds, so
                # give the in-flight stage the room a real cadence has
                # by TRAINING until it lands — those extra steps are the
                # overlap itself (dispatched while the stager fetches
                # and writes), not idle waiting. The trainer's
                # backpressure rule (flush-before-next-stage) still
                # covers the pathological cadence and is exercised by
                # tests/test_train.py.
                extra = 0
                while overlapped and ck.staged_in_flight() and extra < 50_000:
                    state, m = train_step(state, batch, cfg)
                    step += 1
                    extra += 1
                stats = ck.poll_staged()
                if stats:
                    hidden.append(stats["overlap_s"])
                float(m["loss"])  # drain: train work, outside the stall
                if overlapped:
                    with meter.boundary():
                        snap = snapshot_train_state(state)
                        ck.save_staged(step, snap)
                else:
                    with meter.boundary():
                        host_state = jax.device_get(state)
                        ck.save(step, host_state)
            # The final stage is joined with NO training dispatched
            # behind it — its seconds were not hidden, so they must not
            # inflate the overlap_hidden mean.
            ck.flush_staged()
            ck.wait()
        finally:
            ck.close()
            shutil.rmtree(tmp, ignore_errors=True)
        out = meter.summary()
        if hidden:
            out["hidden_mean_s"] = sum(hidden) / len(hidden)
        return out

    sync = run_mode(overlapped=False)
    over = run_mode(overlapped=True)
    # Median per-boundary stall: with a handful of boundaries, one GC
    # pause inside a single measurement swings the mean 2-3x on a
    # loaded CI host; the median is the stable comparison statistic
    # (both means stay in the record for completeness).
    record = {
        "metric": "ckpt_boundary_stall_s",
        "platform": "cpu",
        "boundaries": boundaries,
        "steps_between": steps_between,
        "sync_stall_s_per_boundary": round(sync["median_s"], 4),
        "overlapped_stall_s_per_boundary": round(over["median_s"], 4),
        "sync_stall_mean_s": round(sync["mean_s"], 4),
        "overlapped_stall_mean_s": round(over["mean_s"], 4),
        "stall_reduction_x": round(sync["median_s"] / max(over["median_s"],
                                                          1e-9), 1),
        "overlap_hidden_s_per_boundary": round(
            over.get("hidden_mean_s", 0.0), 4),
    }
    print(json.dumps(record))


def run_pack():
    """`bench.py --pack`: packed vs unpacked pretraining throughput on a
    realistic UniRef-like length distribution — one JSON line, CPU-
    measurable (ISSUE 4 acceptance).

    Two iterators over the SAME synthetic corpus (lognormal lengths,
    median ~350) at the SAME batch shape (B, L): the plain padded
    iterator and the segment-aware packed one (data/packing.py). Each
    mode times its own jitted train step and reports BOTH raw
    residues/s (B·L positions per second — the number that flatters
    padding) and pad-adjusted EFFECTIVE residues/s (non-pad tokens per
    second — the number that measures useful work). MFU likewise comes
    in raw (padded-shape FLOPs) and effective (actual per-batch FLOPs,
    train_flops(..., nonpad_tokens=...) — the satellite's honest-MFU
    fix) flavors. The capture is mirrored as a `note` event on the
    bench event stream (bench_events.jsonl), like the TPU sweeps.

    Knobs: PBT_PACK_BENCH_SEQ_LEN (default 1024), PBT_PACK_BENCH_BATCH
    (8), PBT_PACK_BENCH_DIM (64; plumbing tests shrink it),
    PBT_PACK_BENCH_STEPS (5), PBT_PACK_BENCH_MEDIAN_LEN (350).
    """
    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        force_cpu_backend()
    enable_compile_cache()

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_packed_iterator,
        make_pretrain_iterator,
    )
    from proteinbert_tpu.train import create_train_state, train_step
    from proteinbert_tpu.train.metrics import (
        peak_flops_per_chip, train_flops,
    )

    seq_len = int(os.environ.get("PBT_PACK_BENCH_SEQ_LEN", 1024))
    batch = int(os.environ.get("PBT_PACK_BENCH_BATCH", 8))
    dim = int(os.environ.get("PBT_PACK_BENCH_DIM", 64))
    steps = int(os.environ.get("PBT_PACK_BENCH_STEPS", 5))
    median = int(os.environ.get("PBT_PACK_BENCH_MEDIAN_LEN", 350))

    model = ModelConfig(local_dim=dim, global_dim=2 * dim, key_dim=16,
                        num_heads=4, num_blocks=2,
                        num_annotations=max(8 * dim, 256), dtype="float32")
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=seq_len, batch_size=batch),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=steps))

    # UniRef-like lengths: lognormal with the requested median, clipped
    # to the crop cap (sequences longer than seq_len-2 pack alone).
    rng = np.random.default_rng(0)
    n = max(64 * batch, 512)
    lengths = np.clip(
        rng.lognormal(mean=np.log(median), sigma=0.6, size=n),
        20, 4 * median).astype(np.int64)
    from proteinbert_tpu.data.vocab import ALPHABET

    alphabet = np.array(list(ALPHABET))
    seqs = ["".join(rng.choice(alphabet, size=int(L))) for L in lengths]
    ann = (rng.random((n, model.num_annotations)) < 0.01).astype(np.float32)
    ds = InMemoryPretrainingDataset(seqs, ann, seq_len)

    def measure(batch_np):
        dt = time_step(cfg, batch_np, steps)
        nonpad = int((batch_np["tokens"] != 0).sum())
        total = batch_np["tokens"].size
        peak = peak_flops_per_chip()
        return {
            "ms_per_step": round(dt * 1e3, 2),
            "pad_fraction": round(1.0 - nonpad / total, 4),
            "raw_residues_per_sec": round(total / dt, 1),
            "effective_residues_per_sec": round(nonpad / dt, 1),
            "mfu_raw": round(
                train_flops(model, batch, seq_len) / dt / peak, 4),
            "mfu_effective": round(
                train_flops(model, batch, seq_len, nonpad_tokens=nonpad)
                / dt / peak, 4),
        }

    unpacked = measure(next(make_pretrain_iterator(ds, batch, seed=0)))
    packed = measure(next(make_packed_iterator(ds, batch, seed=0)))

    # ---- fused-vs-reference packed A/B (ISSUE 10 satellite) ----------
    failures = []
    fused_ab = None
    if int(os.environ.get("PBT_PACK_BENCH_FUSED_AB", 1)):
        fused_ab = _pack_fused_ab(model, ds, batch, failures)

    # ---- attention fused-vs-reference A/B (ISSUE 13 satellite) -------
    attn_ab = None
    if int(os.environ.get("PBT_PACK_BENCH_ATTN_AB", 1)):
        attn_ab = _pack_attn_ab(model, ds, batch, failures)

    # ---- one-pass vs two-kernel trunk A/B (ISSUE 16 tentpole) --------
    onepass_ab = None
    if int(os.environ.get("PBT_PACK_BENCH_ONEPASS_AB", 1)):
        onepass_ab = _pack_onepass_ab(model, ds, batch, failures)

    record = {
        "metric": "packed_throughput",
        "platform": jax.devices()[0].platform,
        "seq_len": seq_len, "batch": batch, "model_dim": dim,
        "median_len": median,
        "unpacked": unpacked,
        "packed": packed,
        "effective_speedup_x": round(
            packed["effective_residues_per_sec"]
            / max(unpacked["effective_residues_per_sec"], 1e-9), 2),
        "fused_ab": fused_ab,
        "attn_ab": attn_ab,
        "onepass_ab": onepass_ab,
        "failures": failures,
    }
    try:  # mirror onto the shared bench event stream (best-effort)
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="pack_capture",
                platform=record["platform"], seq_len=seq_len, batch=batch,
                effective_speedup_x=record["effective_speedup_x"],
                packed_pad_fraction=packed["pad_fraction"],
                unpacked_pad_fraction=unpacked["pad_fraction"])
        if fused_ab is not None:
            # Separate note so tools/bench_trajectory.py fits the
            # fused-packed series independently of the pack capture.
            ev.emit("note", source="bench", kind="pack_fused_capture",
                    platform=record["platform"], seq_len=seq_len,
                    batch=batch, fused_dim=fused_ab["fused_dim"],
                    fused_supported=fused_ab["supported"],
                    fused_speedup_x=fused_ab["fused_speedup_x"],
                    parity_max_abs_diff=fused_ab["parity_max_abs_diff"],
                    pallas_executables=fused_ab["pallas_executables"],
                    segment_fallbacks=fused_ab["segment_fallbacks"],
                    failures=len(failures))
        if attn_ab is not None:
            # The attention-arm capture (ISSUE 13): its speedup feeds
            # the pack_attn_speedup_x sentinel series, and the packed
            # step's MFU rides along as the pack_mfu_effective series —
            # the compound packing × fused-kernels claim, recorded on
            # whatever platform actually ran (the `platform` field
            # splits CPU-interpret plumbing numbers from TPU captures).
            ev.emit("note", source="bench", kind="pack_attn_capture",
                    platform=record["platform"], seq_len=seq_len,
                    batch=batch, attn_dim=attn_ab["attn_dim"],
                    attn_supported=attn_ab["supported"],
                    attn_speedup_x=attn_ab["attn_speedup_x"],
                    parity_max_abs_diff=attn_ab["parity_max_abs_diff"],
                    pallas_executables=attn_ab["pallas_executables"],
                    segment_fallbacks=attn_ab["segment_fallbacks"],
                    mfu_raw=packed["mfu_raw"],
                    mfu_effective=packed["mfu_effective"],
                    failures=len(failures))
        if onepass_ab is not None:
            # The one-pass-trunk capture (ISSUE 16): its speedup feeds
            # the pack_onepass_speedup_x sentinel series and the packed
            # step's MFU rides along as onepass_mfu_effective — the
            # whole-block-in-VMEM claim, recorded on whatever platform
            # actually ran (the `platform` field splits CPU-interpret
            # plumbing numbers from TPU captures).
            ev.emit("note", source="bench", kind="onepass_capture",
                    platform=record["platform"], seq_len=seq_len,
                    batch=batch, onepass_dim=onepass_ab["onepass_dim"],
                    onepass_supported=onepass_ab["supported"],
                    onepass_speedup_x=onepass_ab["onepass_speedup_x"],
                    parity_max_abs_diff=onepass_ab["parity_max_abs_diff"],
                    pallas_executables=onepass_ab["pallas_executables"],
                    segment_fallbacks=onepass_ab["segment_fallbacks"],
                    onepass_pallas_calls=onepass_ab["onepass_pallas_calls"],
                    mfu_raw=packed["mfu_raw"],
                    mfu_effective=packed["mfu_effective"],
                    failures=len(failures))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)
    print(json.dumps(record))
    if failures:
        for f in failures:
            print(f"PACK CONTRACT FAILURE: {f}", file=sys.stderr)
        sys.exit(1)


def _pack_fused_ab(model, ds, batch, failures):
    """Fused-vs-reference packed A/B (`bench.py --pack`, ISSUE 10): the
    SAME packed batch runs the segment-aware Pallas fused path and the
    XLA reference path at a lane-aligned dim (the fused kernel needs
    C % 128 == 0, so the main capture's historical dim series stays
    untouched and the A/B gets its own PBT_PACK_BENCH_FUSED_DIM,
    default 128).

    GATED (appended to `failures`, nonzero exit):
    - fused-vs-reference parity within the documented jitted 1e-5
      tolerance on local and global logits;
    - on a supported shape, the fused arm must actually take the
      Pallas path — since the one-pass trunk fusion (ISSUE 16) the
      model-level dispatch lands on
      `onepass_kernel_path_total{path=pallas,reason=packed}` (the
      fused-block family only counts when the one-pass plan doesn't
      fit), so the gate accepts a bump on EITHER family, with ZERO
      reason=segments fallbacks on both;
    - the PBT_FORCE_REFERENCE_KERNEL debug override must route a fresh
      trace onto the reference path (and agree with it bit-for-bit).

    Wall-clock speedup is REPORTED, not gated: off-TPU the kernel runs
    in interpret mode, so the CPU number is a plumbing check — the TPU
    capture is the MFU claim (docs/performance.md, packed fast path).
    """
    from functools import partial

    import jax
    import jax.numpy as jnp

    from proteinbert_tpu.configs import ModelConfig
    from proteinbert_tpu.data import make_packed_iterator
    from proteinbert_tpu.kernels import fused_block as fb
    from proteinbert_tpu.kernels import one_pass as op
    from proteinbert_tpu.models import proteinbert

    fused_dim = int(os.environ.get("PBT_PACK_BENCH_FUSED_DIM", 128))
    reps = int(os.environ.get("PBT_PACK_BENCH_FUSED_REPS", 3))
    forced_env = fb.force_reference_requested()

    pbatch = next(make_packed_iterator(ds, batch, seed=0))
    seq_len = int(pbatch["tokens"].shape[1])
    S = int(pbatch["annotations"].shape[1])
    fused_model = ModelConfig(**{**model.__dict__,
                                 "local_dim": fused_dim,
                                 "use_pallas": True})
    ref_model = ModelConfig(**{**model.__dict__,
                               "local_dim": fused_dim,
                               "use_pallas": False})
    params = proteinbert.init(jax.random.PRNGKey(0), fused_model)

    @partial(jax.jit, static_argnames="mcfg")
    def fwd(p, tokens, seg, ann, mcfg):
        return proteinbert.apply(p, tokens, ann, mcfg, segment_ids=seg)

    t = jnp.asarray(pbatch["tokens"])
    s = jnp.asarray(pbatch["segment_ids"])
    a = jnp.asarray(pbatch["annotations"])
    supported = fb.pallas_segments_supported(
        fused_dim, seq_len, S, fused_model.dtype,
        fused_model.narrow_kernel, fused_model.wide_kernel,
        fused_model.wide_dilation)

    before = dict(fb.PATH_TOTAL)
    op_before = dict(op.ONEPASS_PATH_TOTAL)
    out_f = jax.block_until_ready(fwd(params, t, s, a, fused_model))
    after = dict(fb.PATH_TOTAL)
    op_after = dict(op.ONEPASS_PATH_TOTAL)
    pallas_bumps = (after.get(("pallas", "packed"), 0)
                    - before.get(("pallas", "packed"), 0)
                    + op_after.get(("pallas", "packed"), 0)
                    - op_before.get(("pallas", "packed"), 0))
    seg_falls = (after.get(("reference", "segments"), 0)
                 - before.get(("reference", "segments"), 0)
                 + op_after.get(("reference", "segments"), 0)
                 - op_before.get(("reference", "segments"), 0))
    out_r = jax.block_until_ready(fwd(params, t, s, a, ref_model))

    max_diff = max(
        float(np.abs(np.asarray(x, np.float32)
                     - np.asarray(y, np.float32)).max())
        for x, y in zip(out_f, out_r))
    if not all(np.allclose(np.asarray(x, np.float32),
                           np.asarray(y, np.float32),
                           atol=1e-5, rtol=1e-5)
               for x, y in zip(out_f, out_r)):
        failures.append(
            f"packed fused-vs-reference parity broke: max |diff| "
            f"{max_diff:.2e} outside the documented 1e-5 jitted "
            "tolerance")
    if supported and not forced_env:
        if pallas_bumps < 1:
            failures.append(
                "packed fused arm did not take the Pallas path on a "
                f"supported shape (C={fused_dim}, L={seq_len}, S={S})")
        if seg_falls:
            failures.append(
                f"{seg_falls} reason=segments fallback(s) on a "
                "supported shape — the packed fast path regressed")

    def clock(mcfg):
        # Await the warm dispatch: an un-awaited async call would bleed
        # up to one full forward of device work into the timed loop.
        jax.block_until_ready(fwd(params, t, s, a, mcfg))
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fwd(params, t, s, a, mcfg))
        return (time.perf_counter() - t0) / reps

    dt_f, dt_r = clock(fused_model), clock(ref_model)

    # Debug-override probe: a FRESH jit function forces a new trace, so
    # the env var (read at trace time) must land it on the reference
    # path — and the reference path is deterministic, so the outputs
    # match the use_pallas=False arm bit-for-bit.
    forced = None
    if not forced_env:
        os.environ[fb.FORCE_REFERENCE_ENV] = "1"
        try:
            b2 = dict(fb.PATH_TOTAL)
            forced_fn = jax.jit(
                lambda p, tt, ss, aa: proteinbert.apply(
                    p, tt, aa, fused_model, segment_ids=ss))
            out_fo = jax.block_until_ready(forced_fn(params, t, s, a))
            a2 = dict(fb.PATH_TOTAL)
            bumps = (a2.get(("reference", "forced"), 0)
                     - b2.get(("reference", "forced"), 0))
            bit = all(np.array_equal(np.asarray(x), np.asarray(y))
                      for x, y in zip(out_fo, out_r))
            forced = {"forced_bumps": bumps, "bit_identical": bit}
            if bumps < 1:
                failures.append(
                    "PBT_FORCE_REFERENCE_KERNEL did not route a fresh "
                    "trace onto the reference path")
            elif not bit:
                failures.append(
                    "forced-reference probe diverged from the "
                    "use_pallas=False reference arm")
        finally:
            del os.environ[fb.FORCE_REFERENCE_ENV]

    return {
        "fused_dim": fused_dim, "seq_len": seq_len, "max_segments": S,
        "supported": bool(supported),
        "pallas_executables": int(pallas_bumps),
        "segment_fallbacks": int(seg_falls),
        "parity_max_abs_diff": float(f"{max_diff:.3e}"),
        "fused_ms_per_fwd": round(dt_f * 1e3, 2),
        "reference_ms_per_fwd": round(dt_r * 1e3, 2),
        # Reported, not gated: interpret-mode CPU wall-clock is a
        # plumbing number, the TPU capture is the claim.
        "fused_speedup_x": round(dt_r / max(dt_f, 1e-9), 3),
        "forced_reference_probe": forced,
        "path_total": {f"{p}/{r}": n
                       for (p, r), n in sorted(fb.PATH_TOTAL.items())},
    }


def _pack_attn_ab(model, ds, batch, failures):
    """Attention fused-vs-reference A/B (`bench.py --pack`, ISSUE 13):
    the SAME packed batch's segment layout drives the ragged Pallas
    attention kernel (kernels/attention.fused_packed_attention) and the
    masked-XLA reference (`packed_global_attention_apply`) at a
    lane-aligned local dim (PBT_PACK_BENCH_ATTN_DIM, default 128 — the
    kernel needs C % 128 == 0, so the main capture's dim series stays
    untouched).

    GATED (appended to `failures`, nonzero exit):
    - fused-vs-reference parity within the documented jitted 1e-5
      tolerance on the per-segment (B, S, G) attention output;
    - on a supported shape, the fused arm must take the Pallas path
      (`attention_kernel_path_total{path=pallas,reason=packed}` bumps)
      with ZERO reason=segments fallbacks;
    - the PBT_FORCE_REFERENCE_KERNEL debug override must route a fresh
      trace onto the reference path (and agree with it bit-for-bit).

    Wall-clock speedup is REPORTED, not gated: off-TPU the kernel runs
    in interpret mode, so the CPU number is a plumbing check — the TPU
    capture is the MFU claim (docs/performance.md, packed fast path)."""
    import jax
    import jax.numpy as jnp

    from proteinbert_tpu.data import make_packed_iterator
    from proteinbert_tpu.kernels import attention as ka
    from proteinbert_tpu.ops.attention import (
        global_attention_init, packed_global_attention_apply,
    )

    attn_dim = int(os.environ.get("PBT_PACK_BENCH_ATTN_DIM", 128))
    reps = int(os.environ.get("PBT_PACK_BENCH_ATTN_REPS", 3))
    from proteinbert_tpu.kernels import fused_block as fb

    forced_env = fb.force_reference_requested()

    pbatch = next(make_packed_iterator(ds, batch, seed=0))
    seg = jnp.asarray(pbatch["segment_ids"])
    B, L = seg.shape
    S = int(pbatch["annotations"].shape[1])
    G, key_dim, H = model.global_dim, model.key_dim, model.num_heads
    params = global_attention_init(jax.random.PRNGKey(0), attn_dim, G,
                                   key_dim, H)
    local = jax.random.normal(jax.random.PRNGKey(1), (B, L, attn_dim),
                              jnp.float32)
    gseg = jax.random.normal(jax.random.PRNGKey(2), (B, S, G),
                             jnp.float32)
    supported = ka.pallas_attention_supported(attn_dim, G, L, S,
                                              key_dim, H, "float32")

    fused_fn = jax.jit(lambda p, x, g, s: ka.fused_packed_attention(
        p, x, g, s))
    ref_fn = jax.jit(lambda p, x, g, s: packed_global_attention_apply(
        p, x, g, s))
    before = dict(ka.ATTN_PATH_TOTAL)
    out_f = jax.block_until_ready(fused_fn(params, local, gseg, seg))
    after = dict(ka.ATTN_PATH_TOTAL)
    pallas_bumps = (after.get(("pallas", "packed"), 0)
                    - before.get(("pallas", "packed"), 0))
    seg_falls = (after.get(("reference", "segments"), 0)
                 - before.get(("reference", "segments"), 0))
    out_r = jax.block_until_ready(ref_fn(params, local, gseg, seg))

    max_diff = float(np.abs(np.asarray(out_f, np.float32)
                            - np.asarray(out_r, np.float32)).max())
    if not np.allclose(np.asarray(out_f, np.float32),
                       np.asarray(out_r, np.float32),
                       atol=1e-5, rtol=1e-5):
        failures.append(
            f"attention fused-vs-reference parity broke: max |diff| "
            f"{max_diff:.2e} outside the documented 1e-5 jitted "
            "tolerance")
    if supported and not forced_env:
        if pallas_bumps < 1:
            failures.append(
                "attention fused arm did not take the Pallas path on a "
                f"supported shape (C={attn_dim}, L={L}, S={S})")
        if seg_falls:
            failures.append(
                f"{seg_falls} attention reason=segments fallback(s) on "
                "a supported shape — the packed fast path regressed")

    def clock(fn):
        jax.block_until_ready(fn(params, local, gseg, seg))
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn(params, local, gseg, seg))
        return (time.perf_counter() - t0) / reps

    dt_f, dt_r = clock(fused_fn), clock(ref_fn)

    # Debug-override probe (same contract as the fused-block arm): a
    # fresh jit forces a new trace, so the env var (read at trace
    # time) must land it on the reference path bit-for-bit.
    forced = None
    if not forced_env:
        os.environ[fb.FORCE_REFERENCE_ENV] = "1"
        try:
            b2 = dict(ka.ATTN_PATH_TOTAL)
            forced_fn = jax.jit(
                lambda p, x, g, s: ka.fused_packed_attention(p, x, g, s))
            out_fo = jax.block_until_ready(
                forced_fn(params, local, gseg, seg))
            a2 = dict(ka.ATTN_PATH_TOTAL)
            bumps = (a2.get(("reference", "forced"), 0)
                     - b2.get(("reference", "forced"), 0))
            bit = np.array_equal(np.asarray(out_fo), np.asarray(out_r))
            forced = {"forced_bumps": bumps, "bit_identical": bit}
            if bumps < 1:
                failures.append(
                    "PBT_FORCE_REFERENCE_KERNEL did not route a fresh "
                    "attention trace onto the reference path")
            elif not bit:
                failures.append(
                    "forced-reference attention probe diverged from "
                    "the masked-XLA reference arm")
        finally:
            del os.environ[fb.FORCE_REFERENCE_ENV]

    return {
        "attn_dim": attn_dim, "seq_len": L, "max_segments": S,
        "global_dim": G, "key_dim": key_dim, "num_heads": H,
        "supported": bool(supported),
        "pallas_executables": int(pallas_bumps),
        "segment_fallbacks": int(seg_falls),
        "parity_max_abs_diff": float(f"{max_diff:.3e}"),
        "fused_ms_per_fwd": round(dt_f * 1e3, 2),
        "reference_ms_per_fwd": round(dt_r * 1e3, 2),
        # Reported, not gated: interpret-mode CPU wall-clock is a
        # plumbing number, the TPU capture is the claim. Floored at
        # 1e-3 so the schema's positive-finite contract on the
        # sentinel series holds even on a pathologically slow
        # interpret run.
        "attn_speedup_x": max(round(dt_r / max(dt_f, 1e-9), 3), 1e-3),
        "forced_reference_probe": forced,
        "path_total": {f"{p}/{r}": n
                       for (p, r), n in sorted(ka.ATTN_PATH_TOTAL.items())},
    }


def _pack_onepass_ab(model, ds, batch, failures):
    """One-pass-vs-two-kernel trunk A/B (`bench.py --pack`, ISSUE 16):
    the SAME packed batch's segment layout drives the fused one-pass
    trunk kernel (kernels/one_pass.fused_onepass_segments — local track
    AND ragged attention in ONE VMEM-resident grid program) against the
    two-kernel Pallas composition (fused_local_track_segments →
    fused_packed_attention) at a lane-aligned local dim
    (PBT_PACK_BENCH_ONEPASS_DIM, default 128).

    GATED (appended to `failures`, nonzero exit):
    - one-pass vs composition parity within the documented jitted 1e-5
      tolerance on BOTH outputs (the (B, L, C) local track and the
      (B, S, G) per-segment attention);
    - on a supported shape, the one-pass arm must take the Pallas path
      (`onepass_kernel_path_total{path=pallas,reason=packed}` bumps)
      with ZERO reason=segments fallbacks;
    - the HBM round-trip is ACTUALLY eliminated: the one-pass trace
      contains exactly ONE pallas_call (the composition two), so the
      inter-track (B, L, C) activation never leaves VMEM between the
      local track and attention — no intermediate buffer exists for
      XLA to spill;
    - the PBT_FORCE_REFERENCE_KERNEL debug override must route a fresh
      trace onto the reference composition (and agree bit-for-bit).

    Wall-clock speedup is REPORTED, not gated: off-TPU both arms run
    in interpret mode, so the CPU number is a plumbing check — the TPU
    capture is the MFU claim (docs/performance.md, one-pass trunk)."""
    import jax
    import jax.numpy as jnp

    from proteinbert_tpu.configs import ModelConfig
    from proteinbert_tpu.data import make_packed_iterator
    from proteinbert_tpu.kernels import attention as ka
    from proteinbert_tpu.kernels import fused_block as fb
    from proteinbert_tpu.kernels import one_pass as op
    from proteinbert_tpu.models import proteinbert

    onepass_dim = int(os.environ.get("PBT_PACK_BENCH_ONEPASS_DIM", 128))
    reps = int(os.environ.get("PBT_PACK_BENCH_ONEPASS_REPS", 3))
    forced_env = fb.force_reference_requested()
    interp = jax.default_backend() != "tpu"

    pbatch = next(make_packed_iterator(ds, batch, seed=0))
    seg = jnp.asarray(pbatch["segment_ids"])
    B, L = seg.shape
    S = int(pbatch["annotations"].shape[1])
    G, key_dim, H = model.global_dim, model.key_dim, model.num_heads
    bcfg = ModelConfig(**{**model.__dict__, "local_dim": onepass_dim,
                          "use_pallas": True})
    block = proteinbert.block_init(jax.random.PRNGKey(0), bcfg)
    track = {k: block[k] for k in ("narrow_conv", "wide_conv",
                                   "local_ln1", "local_dense",
                                   "local_ln2")}
    attn = block["attention"]
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, onepass_dim),
                          jnp.float32)
    bcast = jax.random.normal(jax.random.PRNGKey(2), (B, S, onepass_dim),
                              jnp.float32)
    gseg = jax.random.normal(jax.random.PRNGKey(3), (B, S, G),
                             jnp.float32)
    supported = op.pallas_onepass_supported(onepass_dim, G, L, S,
                                            key_dim, H, "float32")

    def one(tp, ap, xx, bb, gg, ss):
        return op.fused_onepass_segments(tp, ap, xx, bb, gg, ss,
                                         interpret=interp)

    def two(tp, ap, xx, bb, gg, ss):
        loc = fb.fused_local_track_segments(tp, xx, bb, ss, 1, 5, interp)
        return loc, ka.fused_packed_attention(ap, loc, gg, ss,
                                              interpret=interp)

    one_fn, two_fn = jax.jit(one), jax.jit(two)
    before = dict(op.ONEPASS_PATH_TOTAL)
    out_f = jax.block_until_ready(
        one_fn(track, attn, x, bcast, gseg, seg))
    after = dict(op.ONEPASS_PATH_TOTAL)
    pallas_bumps = (after.get(("pallas", "packed"), 0)
                    - before.get(("pallas", "packed"), 0))
    seg_falls = (after.get(("reference", "segments"), 0)
                 - before.get(("reference", "segments"), 0))
    out_r = jax.block_until_ready(
        two_fn(track, attn, x, bcast, gseg, seg))

    max_diff = max(
        float(np.abs(np.asarray(a, np.float32)
                     - np.asarray(b, np.float32)).max())
        for a, b in zip(out_f, out_r))
    if not all(np.allclose(np.asarray(a, np.float32),
                           np.asarray(b, np.float32),
                           atol=1e-5, rtol=1e-5)
               for a, b in zip(out_f, out_r)):
        failures.append(
            f"one-pass vs two-kernel parity broke: max |diff| "
            f"{max_diff:.2e} outside the documented 1e-5 jitted "
            "tolerance")
    kernel_calls = comp_calls = None
    if supported and not forced_env:
        if pallas_bumps < 1:
            failures.append(
                "one-pass arm did not take the Pallas path on a "
                f"supported shape (C={onepass_dim}, L={L}, S={S})")
        if seg_falls:
            failures.append(
                f"{seg_falls} one-pass reason=segments fallback(s) on "
                "a supported shape — the fast path regressed")
        # The HBM-round-trip claim, checked structurally: one kernel
        # boundary in the one-pass trace (vs two in the composition)
        # means the inter-track activation has no buffer to spill to —
        # it lives in VMEM for the whole block pass.
        kernel_calls = str(jax.make_jaxpr(one)(
            track, attn, x, bcast, gseg, seg)).count("pallas_call")
        comp_calls = str(jax.make_jaxpr(two)(
            track, attn, x, bcast, gseg, seg)).count("pallas_call")
        if kernel_calls != 1:
            failures.append(
                f"one-pass trace has {kernel_calls} pallas_call "
                "boundaries (want exactly 1) — the inter-track "
                "activation round-trips HBM")

    def clock(fn):
        jax.block_until_ready(fn(track, attn, x, bcast, gseg, seg))
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn(track, attn, x, bcast, gseg, seg))
        return (time.perf_counter() - t0) / reps

    dt_f, dt_r = clock(one_fn), clock(two_fn)

    # Debug-override probe: forcing routes the one-pass dispatch onto
    # the two-kernel composition whose own force checks land both legs
    # on the XLA reference — deterministic, so a forced fresh trace
    # matches a forced composition trace bit-for-bit.
    forced = None
    if not forced_env:
        os.environ[fb.FORCE_REFERENCE_ENV] = "1"
        try:
            b2 = dict(op.ONEPASS_PATH_TOTAL)

            # Fresh function objects: re-jitting the SAME function can
            # hit the trace cache and skip the trace-time env read.
            def one_probe(tp, ap, xx, bb, gg, ss):
                return op.fused_onepass_segments(tp, ap, xx, bb, gg, ss,
                                                 interpret=interp)

            def two_probe(tp, ap, xx, bb, gg, ss):
                loc = fb.fused_local_track_segments(tp, xx, bb, ss,
                                                    1, 5, interp)
                return loc, ka.fused_packed_attention(ap, loc, gg, ss,
                                                      interpret=interp)

            out_fo = jax.block_until_ready(
                jax.jit(one_probe)(track, attn, x, bcast, gseg, seg))
            out_ro = jax.block_until_ready(
                jax.jit(two_probe)(track, attn, x, bcast, gseg, seg))
            a2 = dict(op.ONEPASS_PATH_TOTAL)
            bumps = (a2.get(("reference", "forced"), 0)
                     - b2.get(("reference", "forced"), 0))
            bit = all(np.array_equal(np.asarray(a), np.asarray(b))
                      for a, b in zip(out_fo, out_ro))
            forced = {"forced_bumps": bumps, "bit_identical": bit}
            if bumps < 1:
                failures.append(
                    "PBT_FORCE_REFERENCE_KERNEL did not route a fresh "
                    "one-pass trace onto the reference path")
            elif not bit:
                failures.append(
                    "forced-reference one-pass probe diverged from the "
                    "forced two-kernel composition")
        finally:
            del os.environ[fb.FORCE_REFERENCE_ENV]

    return {
        "onepass_dim": onepass_dim, "seq_len": L, "max_segments": S,
        "global_dim": G, "key_dim": key_dim, "num_heads": H,
        "supported": bool(supported),
        "pallas_executables": int(pallas_bumps),
        "segment_fallbacks": int(seg_falls),
        "onepass_pallas_calls": kernel_calls,
        "composition_pallas_calls": comp_calls,
        "parity_max_abs_diff": float(f"{max_diff:.3e}"),
        "onepass_ms_per_fwd": round(dt_f * 1e3, 2),
        "composition_ms_per_fwd": round(dt_r * 1e3, 2),
        # Reported, not gated: interpret-mode CPU wall-clock is a
        # plumbing number, the TPU capture is the claim. Floored at
        # 1e-3 so the schema's positive-finite contract on the
        # sentinel series holds even on a pathologically slow
        # interpret run.
        "onepass_speedup_x": max(round(dt_r / max(dt_f, 1e-9), 3), 1e-3),
        "forced_reference_probe": forced,
        "path_total": {f"{p}/{r}": n for (p, r), n
                       in sorted(op.ONEPASS_PATH_TOTAL.items())},
    }


def parse_length_mix(spec):
    """`--serve-length-mix` spec → (median, sigma, seed) for the
    log-normal request-length population (clamped to the model window
    downstream). Accepts 'median=48,sigma=0.6,seed=7' with any subset
    of keys; None means 'use the historical defaults' (median
    seq_len//10, sigma 0.45, seed 0 — byte-identical traffic to every
    earlier capture)."""
    out = {"median": None, "sigma": 0.45, "seed": 0}
    if spec:
        for part in spec.split(","):
            if not part:
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            if key not in out:
                raise SystemExit(
                    f"--serve-length-mix: unknown key {key!r} "
                    f"(have {sorted(out)})")
            out[key] = float(val) if key == "sigma" else int(float(val))
    return out["median"], out["sigma"], out["seed"]


def _serve_ragged_ab(Server, params, cfg, seqs, max_batch, max_wait_s,
                     n_clients, failures):
    """Phase 4 of `bench.py --serve` (ISSUE 9): bucketed vs ragged
    packed serving on IDENTICAL traffic. Gates (appended to `failures`):
    per-request parity within the documented jitted ≤1e-5 tolerance,
    no lost requests, ragged warm-executable count O(kinds). Reports:
    sustained requests/s per mode (median over interleaved rounds),
    executable/warmup accounting, and pad_wasted (pad_fraction-weighted
    execute seconds) per mode from the serve_batch event streams."""
    import shutil
    import tempfile
    import threading
    from statistics import median as _median

    from proteinbert_tpu.obs import Telemetry, read_events

    rounds = int(os.environ.get("PBT_SERVE_BENCH_RAGGED_ROUNDS", 3))
    # Ragged row count: the executable's fixed (rows, seq_len) grid
    # should hold about the same REQUEST count per dispatch as the
    # bucketed max_batch does at the traffic's typical span — a grid
    # sized for max_batch full-length rows would run mostly-empty at
    # short-sequence loads and pay full-grid FLOPs for it (the
    # capacity-matching rule, docs/serving.md "ragged batching").
    seq_len = cfg.data.seq_len
    buckets = np.asarray(cfg.data.buckets or (seq_len,))
    spans = buckets[np.searchsorted(
        buckets, np.minimum([len(s) + 2 for s in seqs], seq_len))]
    auto_rows = int(np.clip(round(max_batch * float(spans.mean())
                                  / seq_len), 1, max_batch))
    ragged_rows = int(os.environ.get("PBT_SERVE_BENCH_RAGGED_ROWS",
                                     auto_rows))
    # The dense span ladder: in ragged mode the bucket set is purely a
    # span-quantization rule (the compiled shape stays (rows, seq_len)),
    # so a ladder 2x denser than the compiled bucketed one costs ZERO
    # executables — the pad_wasted lever. Its numerics are gated against
    # the offline dense-bucketed reference below (same span semantics).
    step = int(buckets[0])
    dense_buckets = tuple(range(step, seq_len + 1, step))
    if dense_buckets[-1] != seq_len:
        dense_buckets = dense_buckets + (seq_len,)
    tdir = tempfile.mkdtemp(prefix="pbt_serve_ragged_")
    # Fused-path coverage across the whole A/B (ISSUE 10): under
    # use_pallas, the ragged arms' packed executables must land on the
    # Pallas fast path when the kernel supports the shape — gated
    # below from the trace-time PATH_TOTAL delta. The attention kernel
    # (ISSUE 13) is gated the same way from ATTN_PATH_TOTAL.
    from proteinbert_tpu.kernels import attention as _ka
    from proteinbert_tpu.kernels import fused_block as _fb

    path_before = dict(_fb.PATH_TOTAL)
    attn_before = dict(_ka.ATTN_PATH_TOTAL)
    arms = (("bucketed", "bucketed", None),
            ("ragged", "ragged", None),
            ("ragged_dense", "ragged", dense_buckets))
    servers, teles, warm = {}, {}, {}
    for name, mode, arm_buckets in arms:
        tele = Telemetry(events_path=os.path.join(tdir, f"{name}.jsonl"))
        srv = Server(params, cfg, buckets=arm_buckets,
                     max_batch=(ragged_rows if mode == "ragged"
                                else max_batch),
                     max_wait_s=max_wait_s, queue_depth=4 * len(seqs),
                     cache_size=0, warm_kinds=("embed",), telemetry=tele,
                     trace_sample_rate=0.0, serve_mode=mode)
        # Timed batches: pad_fraction lands on every serve_batch event
        # (the pad_wasted accounting below); sampled-out traces keep
        # the per-request hot path at its measured <1% cost.
        srv.scheduler.time_batches = True
        t0 = time.perf_counter()
        srv.start()
        warm[name] = round(time.perf_counter() - t0, 2)
        servers[name], teles[name] = srv, tele

    def run_load(srv, clients):
        results = {}

        def client(worker):
            for i in range(worker, len(seqs), clients):
                try:
                    results[i] = srv.embed(seqs[i], timeout=120)
                except Exception as e:  # noqa: BLE001 — report, don't hang
                    failures.append(f"ragged A/B request {i}: "
                                    f"{type(e).__name__}: {e}")
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        deadline = time.monotonic() + 5.0
        prev = -1
        while time.monotonic() < deadline:  # quiesce (phase 2's rule)
            cur = srv.scheduler.stats_counts()[1]  # locked read
            if (cur == prev and len(srv.queue) == 0
                    and srv.scheduler.pending_rows() == 0):
                break
            prev = cur
            time.sleep(0.02)
        return results, dt

    # Warm pass per mode (its results double as the parity population —
    # per-request outputs are independent of batch composition in both
    # modes), then interleaved measured rounds.
    ref = {}
    for mode, srv in servers.items():
        ref[mode], _ = run_load(srv, n_clients)
        if len(ref[mode]) != len(seqs):
            failures.append(
                f"ragged A/B ({mode}): lost requests — "
                f"{len(seqs) - len(ref[mode])} of {len(seqs)} never "
                "resolved")
    rps = {m: [] for m in servers}
    for _ in range(rounds):
        for mode, srv in servers.items():
            res, dt = run_load(srv, n_clients)
            rps[mode].append(len(res) / dt)

    # ---- parity gates (deterministic numerics, so GATED) -------------
    # (a) matched-ladder ragged vs the live bucketed server, per
    # request; (b) dense-ladder ragged vs the OFFLINE dense-bucketed
    # reference (`inference.embed(bucketed=True)` at the dense ladder —
    # same span semantics, compiled the classic way).
    from proteinbert_tpu import inference as _inf

    dense_offline = _inf.embed(params, cfg, seqs, bucketed=True,
                               buckets=dense_buckets,
                               batch_size=max_batch)

    def parity_of(get_ref, name):
        checked = within = bit = 0
        max_diff = 0.0
        for i in range(len(seqs)):
            b, r = get_ref(i), ref[name].get(i)
            if b is None or r is None:
                continue  # the lost-request failure above already fired
            checked += 1
            ok = True
            for k in ("global", "local_mean"):
                max_diff = max(max_diff,
                               float(np.abs(b[k] - r[k]).max()))
                if not np.allclose(b[k], r[k], atol=1e-5, rtol=1e-5):
                    ok = False
            within += ok
            bit += all(np.array_equal(b[k], r[k])
                       for k in ("global", "local_mean"))
        return {"checked": checked, "within_tolerance": within,
                "bit_identical": bit,
                "max_abs_diff": float(f"{max_diff:.3e}")}

    parity = parity_of(ref["bucketed"].get, "ragged")
    if parity["within_tolerance"] != parity["checked"]:
        failures.append(
            f"ragged parity broke: "
            f"{parity['checked'] - parity['within_tolerance']}"
            f"/{parity['checked']} requests outside the documented "
            f"1e-5 tolerance (max |diff| {parity['max_abs_diff']:.2e})")
    parity_dense = parity_of(
        lambda i: {k: dense_offline[k][i]
                   for k in ("global", "local_mean")}, "ragged_dense")
    if parity_dense["within_tolerance"] != parity_dense["checked"]:
        failures.append(
            f"dense-ladder ragged parity vs the offline dense-bucketed "
            f"reference broke: "
            f"{parity_dense['checked'] - parity_dense['within_tolerance']}"
            f"/{parity_dense['checked']} outside 1e-5 "
            f"(max |diff| {parity_dense['max_abs_diff']:.2e})")

    stats = {m: servers[m].stats() for m in servers}
    # O(kinds) executable gate: one warm kind ("embed") must mean ONE
    # ragged executable — deterministic, so gated (unlike wall-clock) —
    # for BOTH ladders (the dense ladder must cost zero executables).
    for name in ("ragged", "ragged_dense"):
        if stats[name]["executables"] > 1:
            failures.append(
                f"{name} executable count {stats[name]['executables']} "
                "> O(kinds)=1 for the single warmed kind")
    # ---- fused fast-path coverage gate (ISSUE 10 acceptance) ---------
    path_delta = {k: _fb.PATH_TOTAL.get(k, 0) - path_before.get(k, 0)
                  for k in set(_fb.PATH_TOTAL) | set(path_before)
                  if _fb.PATH_TOTAL.get(k, 0) != path_before.get(k, 0)}
    fused_path = {
        "use_pallas": bool(cfg.model.use_pallas),
        "delta": {f"{p}/{r}": n for (p, r), n in sorted(path_delta.items())},
    }
    if cfg.model.use_pallas and not _fb.force_reference_requested():
        seg_supported = _fb.pallas_segments_supported(
            cfg.model.local_dim, seq_len,
            servers["ragged"].dispatcher.max_segments, cfg.model.dtype,
            cfg.model.narrow_kernel, cfg.model.wide_kernel,
            cfg.model.wide_dilation)
        fused_path["segments_supported"] = bool(seg_supported)
        if seg_supported:
            if path_delta.get(("pallas", "packed"), 0) < 1:
                failures.append(
                    "ragged A/B under use_pallas: no packed executable "
                    "took the Pallas fast path on a supported shape")
            if path_delta.get(("reference", "segments"), 0):
                failures.append(
                    f"ragged A/B under use_pallas: "
                    f"{path_delta[('reference', 'segments')]} "
                    "reason=segments fallback(s) on a supported shape")
    # ---- attention fast-path coverage gate (ISSUE 13 acceptance) -----
    attn_delta = {k: _ka.ATTN_PATH_TOTAL.get(k, 0) - attn_before.get(k, 0)
                  for k in set(_ka.ATTN_PATH_TOTAL) | set(attn_before)
                  if _ka.ATTN_PATH_TOTAL.get(k, 0) != attn_before.get(k, 0)}
    fused_path["attention_delta"] = {
        f"{p}/{r}": n for (p, r), n in sorted(attn_delta.items())}
    if cfg.model.use_pallas and not _fb.force_reference_requested():
        attn_supported = _ka.pallas_attention_supported(
            cfg.model.local_dim, cfg.model.global_dim, seq_len,
            servers["ragged"].dispatcher.max_segments,
            cfg.model.key_dim, cfg.model.num_heads, cfg.model.dtype)
        fused_path["attention_supported"] = bool(attn_supported)
        if attn_supported:
            if attn_delta.get(("pallas", "packed"), 0) < 1:
                failures.append(
                    "ragged A/B under use_pallas: no packed executable "
                    "took the Pallas ATTENTION fast path on a "
                    "supported shape")
            if attn_delta.get(("reference", "segments"), 0):
                failures.append(
                    f"ragged A/B under use_pallas: "
                    f"{attn_delta[('reference', 'segments')]} attention "
                    "reason=segments fallback(s) on a supported shape")
    for srv in servers.values():
        srv.drain(timeout=60)
    for tele in teles.values():
        tele.close()

    def pad_stats(mode):
        recs = [r for r in read_events(
            os.path.join(tdir, f"{mode}.jsonl"), strict=True)
            if r["event"] == "serve_batch"]
        exec_s = sum(r.get("batch_seconds") or 0.0 for r in recs)
        pad_s = sum((r.get("pad_fraction") or 0.0)
                    * (r.get("batch_seconds") or 0.0) for r in recs)
        pads = [r["pad_fraction"] for r in recs
                if isinstance(r.get("pad_fraction"), (int, float))]
        segs = [r["segments"] for r in recs
                if isinstance(r.get("segments"), int)]
        return {
            "batches": len(recs),
            "execute_s": round(exec_s, 4),
            "pad_wasted_s": round(pad_s, 4),
            "pad_wasted_share": (round(pad_s / exec_s, 4)
                                 if exec_s else None),
            "mean_pad_fraction": (round(sum(pads) / len(pads), 4)
                                  if pads else None),
            "mean_segments_per_batch": (round(sum(segs) / len(segs), 2)
                                        if segs else None),
        }

    per_mode = {}
    for name in servers:
        per_mode[name] = {
            "requests_per_sec": round(_median(rps[name]), 2),
            "rps_per_round": [round(v, 2) for v in rps[name]],
            "executables": stats[name]["executables"],
            "warmup_s": warm[name],
            "warmup_seconds_gauge": stats[name]["warmup_seconds"],
            "batches": stats[name]["batches"],
            "pad": pad_stats(name),
        }
    shutil.rmtree(tdir, ignore_errors=True)
    speedup = (per_mode["ragged"]["requests_per_sec"]
               / max(per_mode["bucketed"]["requests_per_sec"], 1e-9))
    speedup_dense = (per_mode["ragged_dense"]["requests_per_sec"]
                     / max(per_mode["bucketed"]["requests_per_sec"],
                           1e-9))
    return {
        "rounds": rounds,
        "requests": len(seqs),
        "ragged_rows": ragged_rows,
        "mean_span": round(float(spans.mean()), 1),
        "dense_buckets": list(dense_buckets),
        "bucketed": per_mode["bucketed"],
        "ragged": per_mode["ragged"],
        "ragged_dense": per_mode["ragged_dense"],
        # Wall-clock: REPORTED, not gated (the CPU capture for the
        # ≥1.2x acceptance claim lives in docs/performance.md).
        "ragged_speedup_x": round(speedup, 2),
        "ragged_dense_speedup_x": round(speedup_dense, 2),
        "speedup_ge_1_2x": bool(max(speedup, speedup_dense) >= 1.2),
        "parity": parity,
        "parity_dense": parity_dense,
        "fused_path": fused_path,
    }


def _mirror_ragged_note(record):
    """Best-effort mirror of the ragged A/B capture onto the shared
    bench event stream (the sentinel's input)."""
    try:
        from proteinbert_tpu.obs.events import EventLog

        ab = record["ragged_ab"]
        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="serve_ragged_capture",
                platform=record["platform"], seq_len=record["seq_len"],
                n_requests=record["n_requests"],
                ragged_speedup_x=ab["ragged_speedup_x"],
                bucketed_rps=ab["bucketed"]["requests_per_sec"],
                ragged_rps=ab["ragged"]["requests_per_sec"],
                bucketed_executables=ab["bucketed"]["executables"],
                ragged_executables=ab["ragged"]["executables"],
                bucketed_pad_wasted_share=(
                    ab["bucketed"]["pad"]["pad_wasted_share"]),
                ragged_pad_wasted_share=(
                    ab["ragged"]["pad"]["pad_wasted_share"]),
                parity_within_tolerance=ab["parity"]["within_tolerance"],
                parity_checked=ab["parity"]["checked"],
                failures=len(record["failures"]))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)


def _serve_quant_ab(Server, params, cfg, seqs, max_batch, max_wait_s,
                    n_clients, failures):
    """Phase 5 (ISSUE 12): the SAME request population through a fp32
    bucketed server and a quant=int8 server (weight-only int8
    executables, fp32 parity shadow sampling EVERY batch so the live
    `serve_quant_parity_max` machinery is exercised end to end).

    GATED: every request served on both arms; per-request output
    deviation between the arms within PBT_SERVE_BENCH_QUANT_TOL
    (default 0.15 — weight quantization is a lossy compression, so
    the gate is the documented bound, not the jitted 1e-5); the
    dispatcher's own sampled parity agrees with the externally
    measured one; the quantized trunk's resident weight bytes <= 0.40x
    fp32 (the HBM-footprint claim at these tiny dims; large dims do
    better). REPORTED: per-arm throughput and warmup — wall-clock on a
    shared box is evidence, not a gate."""
    import threading

    from proteinbert_tpu.obs import Telemetry

    rounds = int(os.environ.get("PBT_SERVE_BENCH_QUANT_ROUNDS", 2))
    tol = float(os.environ.get("PBT_SERVE_BENCH_QUANT_TOL", 0.15))
    arms = {}
    outputs = {}
    for arm in ("fp32", "int8"):
        kw = ({"quant": "int8", "quant_parity_every": 1}
              if arm == "int8" else {})
        srv = Server(params, cfg, max_batch=max_batch,
                     max_wait_s=max_wait_s, queue_depth=4 * len(seqs),
                     cache_size=0, warm_kinds=("embed",),
                     telemetry=Telemetry(), trace_sample_rate=None,
                     **kw)
        t0 = time.perf_counter()
        srv.start()
        warm_s = time.perf_counter() - t0
        results = {}

        def client(worker):
            for i in range(worker, len(seqs), n_clients):
                try:
                    results[i] = srv.embed(seqs[i], timeout=120)
                except Exception as e:  # noqa: BLE001
                    failures.append(f"quant A/B ({arm}) request {i}: "
                                    f"{type(e).__name__}: {e}")

        t0 = time.perf_counter()
        for _ in range(rounds):
            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        dt = time.perf_counter() - t0
        if len(results) != len(seqs):
            failures.append(f"quant A/B ({arm}) lost requests: "
                            f"{len(results)}/{len(seqs)}")
        outputs[arm] = results
        stats = srv.stats()
        arms[arm] = {
            "requests_per_sec": round(rounds * len(seqs) / dt, 2),
            "warmup_s": round(warm_s, 3),
            "executables": stats["executables"],
            "quant": stats["quant"],
        }
        srv.drain(timeout=60)
    parity_max = 0.0
    for i in outputs["fp32"]:
        if i not in outputs["int8"]:
            continue
        for k in outputs["fp32"][i]:
            parity_max = max(parity_max, float(np.max(np.abs(
                outputs["fp32"][i][k] - outputs["int8"][i][k]))))
    if parity_max > tol:
        failures.append(f"quant arm drifted past the documented bound: "
                        f"per-request parity max {parity_max:.5f} > "
                        f"{tol}")
    q = arms["int8"]["quant"] or {}
    sampled_max = q.get("parity_max", 0.0)
    if not q.get("parity_samples"):
        failures.append("quantized arm recorded no live parity samples "
                        "(quant_parity_every machinery broken)")
    elif sampled_max > tol:
        failures.append(f"dispatcher-sampled quant parity "
                        f"{sampled_max:.5f} > {tol}")
    elif abs(sampled_max - parity_max) > 0.25 * max(parity_max, 1e-6) \
            + 1e-4:
        # The AGREEMENT gate: with parity_every=1 every live batch is
        # shadowed, so the dispatcher's own max over requests must
        # track the externally measured cross-server max (slack covers
        # jitted shape-dependent reassociation between the two servers'
        # batch formations). A shadow that measures nothing (e.g.
        # comparing an arm against itself → 0.0) fails HERE instead of
        # passing both independent bounds.
        failures.append(
            f"dispatcher-sampled parity {sampled_max:.6f} does not "
            f"track the externally measured {parity_max:.6f} — the "
            f"live parity shadow is not measuring real deviation")
    ratio = q.get("weight_bytes_ratio", 1.0)
    if ratio > 0.40:
        failures.append(f"quantized trunk weight bytes ratio {ratio} "
                        "> 0.40x fp32 — the HBM-footprint claim broke")
    return {
        "fp32": arms["fp32"],
        "int8": arms["int8"],
        "quant_speedup_x": round(
            arms["int8"]["requests_per_sec"]
            / max(arms["fp32"]["requests_per_sec"], 1e-9), 3),
        "parity": {"max_abs": round(parity_max, 9), "tolerance": tol,
                   "sampled": q.get("parity_samples", 0),
                   "sampled_max": q.get("parity_max")},
        "weight_bytes_ratio": ratio,
    }


def _mirror_quant_note(record):
    """Best-effort mirror of the quantized-arm A/B capture onto the
    shared bench event stream (the sentinel fits
    serve_quant_requests_per_sec / serve_quant_parity_max from it)."""
    try:
        from proteinbert_tpu.obs.events import EventLog

        ab = record["quant_ab"]
        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="serve_quant_capture",
                platform=record["platform"], seq_len=record["seq_len"],
                n_requests=record["n_requests"],
                quant_requests_per_sec=ab["int8"]["requests_per_sec"],
                fp32_requests_per_sec=ab["fp32"]["requests_per_sec"],
                quant_speedup_x=ab["quant_speedup_x"],
                parity_max=ab["parity"]["max_abs"],
                weight_bytes_ratio=ab["weight_bytes_ratio"],
                failures=len(record["failures"]))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)


def _serve_fleet_ab(Server, params, cfg, seqs, max_batch, max_wait_s,
                    n_clients, failures):
    """Phase 6 (ISSUE 18): trace-propagation overhead across a real
    two-replica fleet — the SAME request population routed through two
    identically configured routers over the SAME two HTTP replicas,
    one with `propagate_trace=True` (X-PBT-Trace header + one
    fleet_attempt record per try) and one with it off. Measured rounds
    INTERLEAVE arm-by-arm (matched pairs, like the phase-2c tracing
    A/B) and the per-arm MEDIAN is compared.

    GATED (invariants, not wall-clock): every request on both arms
    returns 200 through the router with an X-PBT-Request-Id header,
    and a replica answers a directly injected X-PBT-Trace id back as
    its X-PBT-Request-Id — the end-to-end join. REPORTED:
    `fleet_trace_overhead_pct` (on-vs-off throughput delta, the
    lower-is-better sentinel series — the PR 6 <1% per-request gate in
    phase 2c prices the stamping itself deterministically)."""
    import threading
    import urllib.request

    from proteinbert_tpu.obs import Telemetry
    from proteinbert_tpu.serve.fleet import FleetRouter
    from proteinbert_tpu.serve.http import make_http_server

    rounds = int(os.environ.get("PBT_SERVE_BENCH_FLEET_ROUNDS", 3))
    bodies = [json.dumps({"seq": s}).encode() for s in seqs]

    replicas, httpds, urls = [], [], []
    for i in range(2):
        srv = Server(params, cfg, max_batch=max_batch,
                     max_wait_s=max_wait_s, queue_depth=4 * len(seqs),
                     cache_size=0, warm_kinds=("embed",),
                     telemetry=Telemetry(), trace_sample_rate=0.0,
                     replica_id=f"r{i}")
        srv.start()  # shares the process-wide jit cache — cheap
        httpd = make_http_server(srv, port=0)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        replicas.append(srv)
        httpds.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")

    # The end-to-end join, checked directly at one replica: an
    # injected fleet id must come back as X-PBT-Request-Id.
    probe = urllib.request.Request(
        urls[0] + "/v1/embed", data=bodies[0],
        headers={"Content-Type": "application/json",
                 "X-PBT-Trace": "bench-fleet-probe"})
    with urllib.request.urlopen(probe, timeout=60) as resp:
        echoed = resp.headers.get("X-PBT-Request-Id")
        resp.read()
    if echoed != "bench-fleet-probe":
        failures.append(
            f"fleet A/B: replica answered X-PBT-Request-Id {echoed!r} "
            "for an injected X-PBT-Trace 'bench-fleet-probe' — the "
            "propagated join is broken")

    arms = []
    for arm, propagate in (("on", True), ("off", False)):
        router = FleetRouter(
            [(f"r{i}", urls[i]) for i in range(2)],
            telemetry=Telemetry(), health_interval_s=0.0,
            max_retries=1, cache_size=0, request_timeout_s=120.0,
            propagate_trace=propagate).start()
        arms.append((arm, router))

    def run_round(router) -> float:
        results = {}

        def client(worker: int) -> None:
            for i in range(worker, len(seqs), n_clients):
                try:
                    status, _body, hdrs = router.route("/v1/embed",
                                                       bodies[i])
                    results[i] = (status, hdrs.get("X-PBT-Request-Id"))
                except Exception as e:  # noqa: BLE001 — report, not hang
                    failures.append(f"fleet A/B request {i}: "
                                    f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        bad = [i for i, (status, rid) in results.items()
               if status != 200 or not rid]
        if len(results) != len(seqs) or bad:
            failures.append(
                f"fleet A/B: {len(seqs) - len(results)} lost, "
                f"{len(bad)} non-200/unlabeled of {len(seqs)}")
        return len(seqs) / dt

    rps = {arm: [] for arm, _ in arms}
    for arm, router in arms:
        run_round(router)  # warm pass (connection setup, jit reuse)
    for _ in range(rounds):
        for arm, router in arms:
            rps[arm].append(run_round(router))

    for _, router in arms:
        router.drain()
    for httpd in httpds:
        httpd.shutdown()
        httpd.server_close()
    for srv in replicas:
        srv.drain(timeout=60)

    from statistics import median as _median

    rps_on = _median(rps["on"])
    rps_off = _median(rps["off"])
    overhead_pct = (1.0 - rps_on / max(rps_off, 1e-9)) * 100.0
    return {
        "rounds": rounds,
        "rps_per_round": {a: [round(v, 2) for v in vals]
                          for a, vals in rps.items()},
        "fleet_rps_on": round(rps_on, 2),
        "fleet_rps_off": round(rps_off, 2),
        "fleet_trace_overhead_pct": round(overhead_pct, 3),
    }


def _mirror_fleet_note(record):
    """Best-effort mirror of the fleet propagation A/B onto the shared
    bench event stream (the sentinel fits fleet_trace_overhead_pct
    from it, lower-is-better). The pct is the MEDIAN over `rounds` A/B
    rounds and the note carries that round count (ISSUE 19 satellite:
    the series is a near-zero-centered difference, so the sentinel
    holds an absolute noise floor for it — see tools/bench_trajectory
    `_ABS_FLOOR` — and the rounds field keeps the capture auditable)."""
    try:
        from proteinbert_tpu.obs.events import EventLog

        ab = record["fleet_ab"]
        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="fleet_trace_capture",
                platform=record["platform"], seq_len=record["seq_len"],
                n_requests=record["n_requests"],
                fleet_trace_overhead_pct=ab["fleet_trace_overhead_pct"],
                fleet_rps_on=ab["fleet_rps_on"],
                fleet_rps_off=ab["fleet_rps_off"],
                rounds=ab["rounds"],
                failures=len(record["failures"]))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)


def _serve_pipeline_ab(Server, params, cfg, seqs, max_batch, max_wait_s,
                       n_clients, failures):
    """Phase 7 (ISSUE 19): pipelined-dispatch A/B — the SAME request
    population through a depth-1 server (strictly serial submit →
    fetch → seal per batch) and a depth-2 server (bounded in-flight
    window: the scheduler forms batch N+1 while the completer thread
    finalizes batch N).

    GATED (invariants, not wall-clock — appended to `failures`):
    - async-vs-sync BIT-parity: one full same-bucket micro-batch,
      formed deterministically on both depths (phase 3a's rule:
      max_wait 60s + exactly max_batch same-bucket submits in FIFO
      order → identical rows through the identical executable), must
      produce bit-identical per-request outputs — the submit/fetch
      split may move the host fetch, never the math;
    - zero lost/duplicate seals under drain() with work in flight: a
      full burst submitted and immediately drained must resolve every
      future exactly once, and the fully-traced serve_request stream
      must carry exactly one record per submitted request with no
      duplicated ids;
    - overlap observed on the serve path: the depth-2 window actually
      filled (pipeline inflight_max >= 2) under sustained load;
    - the map path: a tiny `run_map` pipeline-on vs pipeline-off over
      the same corpus writes BYTE-identical stores (same digest maps —
      commit order is the contract), with overlap observed
      (map overlap_ratio > 0) on the pipelined run.

    REPORTED: sustained requests/s per depth (median over interleaved
    rounds) and `serve_pipeline_speedup_x` — the sentinel series
    (platform-split). Wall-clock is evidence, not a gate (the honest-
    CPU rule): off-TPU the host fetch the pipeline overlaps is
    microseconds, so the ratio hovers near 1.0 — the CPU points keep
    the series alive and honestly labeled while the gates above carry
    the contract."""
    import shutil
    import tempfile
    import threading
    from statistics import median as _median

    from proteinbert_tpu.obs import Telemetry, read_events

    rounds = int(os.environ.get("PBT_SERVE_BENCH_PIPELINE_ROUNDS", 3))
    tdir = tempfile.mkdtemp(prefix="pbt_serve_pipeline_")

    servers, teles = {}, {}
    for name, depth in (("serial", 1), ("pipelined", 2)):
        tele = Telemetry(events_path=os.path.join(tdir, f"{name}.jsonl"))
        srv = Server(params, cfg, max_batch=max_batch,
                     max_wait_s=max_wait_s, queue_depth=4 * len(seqs),
                     cache_size=0, warm_kinds=("embed",), telemetry=tele,
                     trace_sample_rate=1.0, pipeline_depth=depth)
        srv.start()
        servers[name], teles[name] = srv, tele

    def run_load(srv, clients):
        results = {}

        def client(worker):
            for i in range(worker, len(seqs), clients):
                try:
                    results[i] = srv.embed(seqs[i], timeout=120)
                except Exception as e:  # noqa: BLE001 — report, don't hang
                    failures.append(f"pipeline A/B request {i}: "
                                    f"{type(e).__name__}: {e}")
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        deadline = time.monotonic() + 5.0
        prev = -1
        while time.monotonic() < deadline:  # quiesce (phase 2's rule)
            cur = srv.scheduler.stats_counts()[1]  # locked read
            if (cur == prev and len(srv.queue) == 0
                    and srv.scheduler.pending_rows() == 0):
                break
            prev = cur
            time.sleep(0.02)
        return results, dt

    # Warm pass per depth (lost-request gate), then interleaved
    # measured rounds (matched pairs, like every other serve A/B).
    for name, srv in servers.items():
        res, _ = run_load(srv, n_clients)
        if len(res) != len(seqs):
            failures.append(
                f"pipeline A/B ({name}): lost requests — "
                f"{len(seqs) - len(res)} of {len(seqs)} never resolved")
    rps = {m: [] for m in servers}
    for _ in range(rounds):
        for name, srv in servers.items():
            res, dt = run_load(srv, n_clients)
            rps[name].append(len(res) / dt)

    # ---- async-vs-sync bit-parity on a deterministic batch -----------
    by_bucket = {}
    for s in seqs:
        blen = servers["serial"].dispatcher.bucket_len(len(s))
        by_bucket.setdefault(blen, []).append(s)
    group = max(by_bucket.values(), key=len)
    group = (group * max_batch)[:max_batch]
    outs = {}
    for depth in (1, 2):
        psrv = Server(params, cfg, max_batch=len(group), max_wait_s=60.0,
                      cache_size=0, warm_kinds=(), pipeline_depth=depth)
        psrv.start()  # depth 2 needs the live completer thread
        futs = [psrv.submit("embed", s) for s in group]
        outs[depth] = [f.result(timeout=120) for f in futs]
        psrv.drain(timeout=60)
    bit = sum(
        all(np.array_equal(a[k], b[k]) for k in ("global", "local_mean"))
        for a, b in zip(outs[1], outs[2]))
    if bit != len(group):
        failures.append(
            f"pipeline A/B parity broke: {len(group) - bit}/{len(group)} "
            "async-path outputs not BIT-identical to the serial path on "
            "an identical deterministically formed batch")

    # ---- exactly-once sealing under drain with work in flight --------
    burst = [servers["pipelined"].submit("embed", s) for s in seqs]
    servers["pipelined"].drain(timeout=120)
    unresolved = sum(1 for f in burst if not f.done())
    errored = sum(1 for f in burst if f.done() and f.exception())
    if unresolved or errored:
        failures.append(
            f"pipeline A/B drain-with-work-in-flight: {unresolved} "
            f"unresolved / {errored} errored of {len(burst)} burst "
            "futures — the window lost or poisoned seals")

    pstats = servers["pipelined"].scheduler.pipeline_stats()
    if pstats["inflight_max"] < 2:
        failures.append(
            f"pipeline A/B: depth-2 window never filled (inflight_max "
            f"{pstats['inflight_max']} < 2) — no overlap observed on "
            "the serve path")

    servers["serial"].drain(timeout=60)
    for tele in teles.values():
        tele.close()

    # Every submitted request → exactly one fully-traced serve_request
    # record, no duplicated ids (the exactly-once seal, observed from
    # the event stream rather than asserted from the implementation).
    recs = [r for r in read_events(
        os.path.join(tdir, "pipelined.jsonl"), strict=True)
        if r["event"] == "serve_request"]
    ids = [r["request_id"] for r in recs]
    expected = (1 + rounds) * len(seqs) + len(burst)
    if len(ids) != expected or len(set(ids)) != len(ids):
        failures.append(
            f"pipeline A/B seal accounting: {len(ids)} serve_request "
            f"records ({len(ids) - len(set(ids))} duplicated ids) for "
            f"{expected} submitted requests — lost or duplicate seals")

    # ---- map path: pipelined run_map writes the SAME bytes -----------
    from proteinbert_tpu.mapper import run_map, store_digests

    map_seqs = [seqs[i % len(seqs)] for i in range(24)]
    map_ids = [f"m{i}" for i in range(len(map_seqs))]
    map_res, map_dirs = {}, {}
    for name, flag in (("on", True), ("off", False)):
        sdir = os.path.join(tdir, f"map_{name}")
        map_dirs[name] = sdir
        map_res[name] = run_map(params, cfg, map_ids, map_seqs, sdir,
                                num_shards=2, block_size=4,
                                rows_per_batch=max_batch,
                                pipeline=flag)
        if map_res[name]["outcome"] != "completed":
            failures.append(
                f"pipeline A/B map ({name}): outcome "
                f"{map_res[name]['outcome']!r}, expected 'completed'")
    map_identical = (store_digests(map_dirs["on"])
                     == store_digests(map_dirs["off"]))
    if not map_identical:
        failures.append(
            "pipeline A/B map: pipelined store digests differ from the "
            "serial store — commit order or bytes drifted")
    if map_res["on"].get("overlap_ratio", 0.0) <= 0.0:
        failures.append(
            "pipeline A/B map: overlap_ratio is 0 with pipelining on — "
            "no overlap observed on the map path")

    shutil.rmtree(tdir, ignore_errors=True)

    rps_serial = _median(rps["serial"])
    rps_pipe = _median(rps["pipelined"])
    return {
        "rounds": rounds,
        "rps_per_round": {m: [round(v, 2) for v in vals]
                          for m, vals in rps.items()},
        "serial_rps": round(rps_serial, 2),
        "pipeline_rps": round(rps_pipe, 2),
        "serve_pipeline_speedup_x": round(
            rps_pipe / max(rps_serial, 1e-9), 3),
        "serve_overlap_ratio": pstats["overlap_ratio"],
        "inflight_max": pstats["inflight_max"],
        "finalize_seconds_total": pstats["finalize_seconds_total"],
        "parity": {"checked": len(group), "bit_identical": bit},
        "seal": {"expected": expected, "serve_request_events": len(ids),
                 "unique_ids": len(set(ids))},
        "map": {"overlap_ratio": map_res["on"].get("overlap_ratio", 0.0),
                "byte_identical": map_identical},
    }


def _mirror_pipeline_note(record):
    """Best-effort mirror of the pipelined-dispatch A/B onto the shared
    bench event stream (the sentinel fits serve_pipeline_speedup_x
    from it; platform-split, so off-TPU points stay honestly labeled
    rather than polluting a TPU trajectory)."""
    try:
        from proteinbert_tpu.obs.events import EventLog

        ab = record["pipeline_ab"]
        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="serve_pipeline_capture",
                platform=record["platform"], seq_len=record["seq_len"],
                n_requests=record["n_requests"],
                serve_pipeline_speedup_x=ab["serve_pipeline_speedup_x"],
                pipeline_rps=ab["pipeline_rps"],
                serial_rps=ab["serial_rps"],
                serve_overlap_ratio=ab["serve_overlap_ratio"],
                inflight_max=ab["inflight_max"],
                failures=len(record["failures"]))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)


def run_serve(length_mix=None):
    """`bench.py --serve`: sustained-load online serving vs the
    one-request-at-a-time offline baseline — one JSON line, CPU-
    measurable (ISSUE 5 acceptance).

    Three phases over one tiny trunk (untrained params: FLOPs and
    dispatch behavior are weight-independent):

    1. **baseline** — sequential single-request `inference.embed`
       calls (batch 1, every request padded to the full seq_len): the
       only serving story the repo had before the serve/ subsystem.
    2. **served** — the same request population pushed through
       `serve.Server` (continuous micro-batching over length buckets,
       cache OFF so every row pays a real model call), in two load
       shapes: a SATURATED closed loop (N concurrent client threads,
       enough to keep every bucket's group full — the throughput
       number and the ≥3x-vs-baseline claim), then a LIGHT load
       (fewer clients than one micro-batch) where end-to-end latency
       is the scheduler's contract rather than queueing theory: p99
       must stay under max_wait + one batch time (slowest observed
       batch, plus a small OS-jitter allowance on a shared CI box).
    3. **contracts** — (a) served-vs-offline BIT-parity per bucket: a
       full micro-batch formed deterministically through submit()+
       poll() must equal `inference.embed(bucketed=True)` at the same
       (bucket_len, batch_class) shape; (b) queue overflow on a server
       with a tiny bounded queue: every overflow victim observes a
       typed QueueFullError (rejected, never dropped).

    Exit code is nonzero when a CONTRACT fails (parity, lost requests,
    un-rejected overflow); the speedup is reported, not gated — wall-
    clock ratios on a noisy CI box are evidence, not invariants. The
    capture is mirrored as a `note` on bench_events.jsonl like the
    other sweeps.

    4. **ragged A/B** (ISSUE 9) — the SAME mixed-length population
       through a bucketed server and a ragged packed server
       (`serve_mode="ragged"`: requests pack into fixed-shape
       (max_batch, seq_len) rows, one warm executable per kind).
       GATED: every ragged per-request output matches the bucketed
       dispatcher's within the documented jitted ≤1e-5 tolerance
       (bucket-quantized spans — docs/serving.md), no request lost,
       ragged warm-executable count stays O(kinds). REPORTED: the
       sustained-load speedup (the ≥1.2x acceptance capture), warm
       executable counts, warmup seconds, and per-mode `pad_wasted`
       (pad_fraction-weighted execute seconds) from the serve_batch
       streams.

    `length_mix` (--serve-length-mix 'median=48,sigma=0.9,seed=7')
    reshapes the log-normal request-length population so the benchmark
    measures the padding waste ragged serving exists to remove; default
    traffic is byte-identical to earlier captures.

    PBT_SERVE_BENCH_PHASES selects phases: "all" (default), "core"
    (1-3 only — the historical smoke), "ragged" (phase 4 only — the
    tier-1 ragged stage), "quant" (phase 5), "fleet" (phase 6 — the
    ISSUE 18 trace-propagation on-vs-off A/B over two HTTP replicas,
    feeding the fleet_trace_overhead_pct sentinel series), "pipeline"
    (phase 7 — the ISSUE 19 pipelined-dispatch depth-1 vs depth-2 A/B:
    async-vs-sync bit-parity, exactly-once sealing under drain with
    work in flight, overlap observed on BOTH the serve and map paths,
    feeding the serve_pipeline_speedup_x sentinel series).

    Knobs: PBT_SERVE_BENCH_SEQ_LEN (512), PBT_SERVE_BENCH_DIM (64),
    PBT_SERVE_BENCH_REQUESTS (96), PBT_SERVE_BENCH_CLIENTS (16),
    PBT_SERVE_BENCH_MAX_BATCH (8), PBT_SERVE_BENCH_TRACE_ROUNDS (5),
    PBT_SERVE_BENCH_RAGGED_ROUNDS (3), PBT_SERVE_BENCH_FLEET_ROUNDS
    (3), PBT_SERVE_BENCH_PIPELINE_ROUNDS (3),
    PBT_SERVE_BENCH_MEDIAN_LEN (seq_len // 8).
    """
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        force_cpu_backend()
    enable_compile_cache()

    from proteinbert_tpu import inference
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.data.vocab import ALPHABET
    from proteinbert_tpu.serve import QueueFullError, Server
    from proteinbert_tpu.train import create_train_state

    phases_env = os.environ.get("PBT_SERVE_BENCH_PHASES", "all").strip()
    wanted = ({"core", "ragged", "quant", "fleet", "pipeline"}
              if phases_env == "all"
              else {p for p in phases_env.split(",") if p})
    bad = wanted - {"core", "ragged", "quant", "fleet", "pipeline"}
    if bad or not wanted:
        raise SystemExit(f"PBT_SERVE_BENCH_PHASES must name phases from "
                         f"core,ragged,quant,fleet,pipeline or 'all'; "
                         f"got {phases_env!r}")

    seq_len = int(os.environ.get("PBT_SERVE_BENCH_SEQ_LEN", 512))
    dim = int(os.environ.get("PBT_SERVE_BENCH_DIM", 64))
    n_requests = int(os.environ.get("PBT_SERVE_BENCH_REQUESTS", 96))
    n_clients = int(os.environ.get("PBT_SERVE_BENCH_CLIENTS", 32))
    max_batch = int(os.environ.get("PBT_SERVE_BENCH_MAX_BATCH", 8))
    median = int(os.environ.get("PBT_SERVE_BENCH_MEDIAN_LEN", seq_len // 10))
    max_wait_s = 0.01

    # PBT_SERVE_BENCH_USE_PALLAS=1: serve through the fused Pallas
    # local track (interpret mode off-TPU) — with a lane-aligned DIM
    # (128+) the ragged arms run the segment-aware packed fast path and
    # phase 4 GATES that coverage (ISSUE 10 acceptance).
    use_pallas = bool(int(os.environ.get("PBT_SERVE_BENCH_USE_PALLAS", 0)))
    model = ModelConfig(local_dim=dim, global_dim=2 * dim, key_dim=16,
                        num_heads=4, num_blocks=2,
                        num_annotations=max(4 * dim, 128),
                        dtype="float32", use_pallas=use_pallas)
    buckets = tuple(sorted({max(16, seq_len // 8), seq_len // 4,
                            seq_len // 2, seq_len}))
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=seq_len, batch_size=max_batch,
                        buckets=buckets),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=1))
    params = create_train_state(jax.random.PRNGKey(0), cfg).params

    # UniRef-like ragged lengths, clipped to the model window. With no
    # --serve-length-mix this is BYTE-IDENTICAL traffic to every
    # earlier capture (median seq_len//10, sigma 0.45, seed 0).
    mix_median, mix_sigma, mix_seed = parse_length_mix(length_mix)
    if mix_median is None:
        mix_median = median
    else:
        median = mix_median
    rng = np.random.default_rng(mix_seed)
    lengths = np.clip(
        rng.lognormal(mean=np.log(mix_median), sigma=mix_sigma,
                      size=n_requests),
        10, seq_len - 2).astype(np.int64)
    alphabet = np.array(list(ALPHABET))
    seqs = ["".join(rng.choice(alphabet, size=int(L))) for L in lengths]

    if "core" not in wanted:
        # Off-core run (the tier-1 ragged/quant smoke stages): skip the
        # baseline/tracing/overflow phases and gate just the selected
        # A/B contracts.
        failures = []
        record = {
            "metric": ("serve_ragged" if "ragged" in wanted
                       else "serve_quant" if "quant" in wanted
                       else "serve_fleet" if "fleet" in wanted
                       else "serve_pipeline"),
            "platform": jax.devices()[0].platform,
            "seq_len": seq_len, "model_dim": dim, "median_len": median,
            "length_sigma": mix_sigma, "buckets": list(buckets),
            "max_batch": max_batch, "n_requests": n_requests,
            "failures": failures,
        }
        if "ragged" in wanted:
            record["ragged_ab"] = _serve_ragged_ab(
                Server, params, cfg, seqs, max_batch, max_wait_s,
                n_clients, failures)
            _mirror_ragged_note(record)
        if "quant" in wanted:
            record["quant_ab"] = _serve_quant_ab(
                Server, params, cfg, seqs, max_batch, max_wait_s,
                n_clients, failures)
            _mirror_quant_note(record)
        if "fleet" in wanted:
            record["fleet_ab"] = _serve_fleet_ab(
                Server, params, cfg, seqs, max_batch, max_wait_s,
                n_clients, failures)
            _mirror_fleet_note(record)
        if "pipeline" in wanted:
            record["pipeline_ab"] = _serve_pipeline_ab(
                Server, params, cfg, seqs, max_batch, max_wait_s,
                n_clients, failures)
            _mirror_pipeline_note(record)
        print(json.dumps(record))
        if failures:
            for f in failures:
                print(f"SERVE CONTRACT FAILURE: {f}", file=sys.stderr)
            sys.exit(1)
        return

    # ---- phase 1: sequential single-request offline baseline ----------
    inference.embed(params, cfg, [seqs[0]], batch_size=1)  # compile
    base_n = min(n_requests, max(2 * max_batch, 24))
    t0 = time.perf_counter()
    for s in seqs[:base_n]:
        inference.embed(params, cfg, [s], batch_size=1)
    base_dt = time.perf_counter() - t0
    baseline = {"requests": base_n,
                "requests_per_sec": round(base_n / base_dt, 2),
                "ms_per_request": round(base_dt / base_n * 1e3, 2)}

    # ---- phase 2: sustained concurrent load through the server --------
    from proteinbert_tpu.obs import Telemetry

    failures = []
    # Metrics-only telemetry (no events file): the registry's
    # serve_batch_seconds histogram supplies the p99-bound batch time.
    # trace_sample_rate=None: the headline server is UNTRACED — the
    # tracing cost is measured separately in phase 2c.
    server = Server(params, cfg, max_batch=max_batch, max_wait_s=max_wait_s,
                    queue_depth=4 * n_requests, cache_size=0,
                    warm_kinds=("embed",), telemetry=Telemetry(),
                    trace_sample_rate=None)
    t0 = time.perf_counter()
    server.start()
    warm_s = time.perf_counter() - t0
    def run_load(srv, indices, clients) -> tuple:
        results = {}

        def client(worker: int) -> None:
            for i in indices[worker::clients]:
                try:
                    results[i] = srv.embed(seqs[i], timeout=120)
                except Exception as e:  # noqa: BLE001 — report, don't hang
                    failures.append(f"request {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        # Quiesce: a request's future resolves BEFORE the scheduler
        # records its latency, so returning the moment all futures are
        # done races the last batch's bookkeeping (and a stale
        # saturated-phase sample landing in the light window would be a
        # spurious p99 failure). rows_total is bumped after the whole
        # batch's latencies are observed — wait for it to go stable
        # with nothing queued or pending.
        deadline = time.monotonic() + 5.0
        prev = -1
        while time.monotonic() < deadline:
            cur = srv.scheduler.stats_counts()[1]  # locked read
            pending = srv.scheduler.pending_rows()
            if cur == prev and len(srv.queue) == 0 and pending == 0:
                break
            prev = cur
            time.sleep(0.02)
        return results, dt

    # Saturated closed loop: enough concurrent clients that every
    # bucket's group keeps filling — the throughput measurement.
    sat_results, sat_dt = run_load(server, list(range(n_requests)),
                                   n_clients)
    sat_stats = server.stats()
    if len(sat_results) != n_requests:
        failures.append(
            f"lost requests: {n_requests - len(sat_results)} of "
            f"{n_requests} never resolved")

    # Light load: fewer clients than one micro-batch, so nothing queues
    # behind a saturated device — end-to-end latency is the scheduler
    # contract (≤ max_wait + one batch time), not queueing delay.
    light_n = max(max_batch, n_requests // 4)
    light_window = type(server.latencies)()
    server.latencies = light_window  # fresh percentile ring
    light_results, _ = run_load(server, list(range(light_n)),
                                max(2, max_batch // 2))
    batch_h = server.tele.metrics.histogram("serve_batch_seconds")
    max_batch_s = batch_h.max if batch_h.count else 0.0
    server.drain(timeout=60)
    p99 = light_window.percentile(99) or 0.0
    # Allowance on top of the contract bound: the scheduler's idle park
    # (max_wait/2) plus thread-wakeup jitter on a shared CI box. The
    # bound is REPORTED (light_p99_within_bound), not a gate failure:
    # wall-clock on a noisy CI box is evidence, not an invariant — the
    # light window holds ~light_n samples, so its p99 is effectively
    # the max sample and one OS scheduling hiccup would flake tier-1.
    p99_bound = max_wait_s + max_batch_s + max_wait_s / 2 + 0.01
    if len(light_results) != light_n:
        failures.append(f"light phase lost requests: "
                        f"{light_n - len(light_results)} of {light_n} "
                        "never resolved")
    served = {
        "requests": len(sat_results),
        "clients": n_clients,
        "requests_per_sec": round(n_requests / sat_dt, 2),
        "saturated_p50_ms": round(
            (sat_stats["latency"]["p50_s"] or 0.0) * 1e3, 2),
        "saturated_p99_ms": round(
            (sat_stats["latency"]["p99_s"] or 0.0) * 1e3, 2),
        "light_p50_ms": round((light_window.percentile(50) or 0.0) * 1e3,
                              2),
        "light_p99_ms": round(p99 * 1e3, 2),
        "max_wait_ms": round(max_wait_s * 1e3, 2),
        "max_batch_ms": round(max_batch_s * 1e3, 2),
        "light_p99_bound_ms": round(p99_bound * 1e3, 2),
        "light_p99_within_bound": bool(p99 <= p99_bound),
        "batches": sat_stats["batches"],
        "mean_rows_per_batch": round(
            sat_stats["batched_rows"] / max(sat_stats["batches"], 1), 2),
        "warmup_s": round(warm_s, 2),
    }

    # ---- phase 2c: request tracing — overhead + correctness -----------
    # Three matched conditions over the same saturated population:
    #   null        — telemetry NULL (the must-stay-a-no-op path);
    #   sampled_out — telemetry on, trace_sample_rate=0: every request
    #                 carries the cheap clock marks but nothing emits
    #                 (the "<1% of served-request latency" claim);
    #   full        — sample rate 1.0 + events file + span collector.
    # All three servers warm first, then measured passes INTERLEAVE
    # round-robin (matched pairs): CPU-frequency/contention drift on a
    # shared box hits every condition equally instead of whichever ran
    # last, and the per-condition MEDIAN over rounds is compared.
    # CORRECTNESS is GATED on the full condition (invariants, not
    # wall-clock): every request yields a schema-valid serve_request
    # event whose contiguous stages sum to its e2e latency, and spans
    # land in the collector. The overhead percentages are REPORTED —
    # wall-clock ratios on a shared CI box are evidence, not a gate.
    import tempfile

    from proteinbert_tpu.obs import read_events

    trace_dir = tempfile.mkdtemp(prefix="pbt_serve_trace_")
    trace_events = os.path.join(trace_dir, "events.jsonl")
    # Measured A/B passes per condition (report-only medians; the <1%
    # gate below is the deterministic timeit measurement) — tunable so
    # budgeted runs (tier-1 smoke) can trim the load matrix.
    rounds = int(os.environ.get("PBT_SERVE_BENCH_TRACE_ROUNDS", 5))

    sampled_tele = Telemetry(events_path=os.path.join(trace_dir,
                                                      "sampled.jsonl"))
    ttele = Telemetry(events_path=trace_events, spans=True)
    conditions = (("null", None, None),
                  ("sampled_out", sampled_tele, 0.0),
                  ("full", ttele, 1.0))
    ab_servers = []
    rps = {}
    for name, tele_c, rate in conditions:
        srv = Server(params, cfg, max_batch=max_batch,
                     max_wait_s=max_wait_s, queue_depth=4 * n_requests,
                     cache_size=0, warm_kinds=("embed",),
                     telemetry=tele_c, trace_sample_rate=rate)
        srv.start()  # reuses the process-wide jit cache — cheap
        run_load(srv, list(range(n_requests)), n_clients)  # warm pass
        ab_servers.append((name, srv))
        rps[name] = []
    for _ in range(rounds):
        for name, srv in ab_servers:
            results, dt = run_load(srv, list(range(n_requests)),
                                   n_clients)
            rps[name].append(len(results) / dt)
    for _, srv in ab_servers:
        srv.drain(timeout=60)
    sampled_tele.close()
    ttele.close()

    from statistics import median as _median

    null_rps = _median(rps["null"])
    sampled_rps = _median(rps["sampled_out"])
    full_rps = _median(rps["full"])
    sampled_overhead = (1.0 - sampled_rps / max(null_rps, 1e-9)) * 100.0
    full_overhead = (1.0 - full_rps / max(null_rps, 1e-9)) * 100.0
    trace_recs = [r for r in read_events(trace_events, strict=True)
                  if r["event"] == "serve_request"]
    expected = (rounds + 1) * n_requests  # warm + measured passes
    if len(trace_recs) != expected:
        failures.append(
            f"tracing: expected {expected} serve_request events "
            f"at sample rate 1.0, got {len(trace_recs)}")
    bad_sums = 0
    for r in trace_recs:
        if abs(sum(r["stages"].values()) - r["e2e_s"]) > 1e-5:
            bad_sums += 1
    if bad_sums:
        failures.append(
            f"tracing: {bad_sums}/{len(trace_recs)} serve_request "
            "events whose stages do not sum to e2e_s")
    if len(ttele.spans or ()) == 0:
        failures.append("tracing: span collector stayed empty")
    # Sampled-out emissions would break the sampling contract: at rate
    # 0 no SUCCESSFUL request may emit (errors/rejections always do,
    # by design — only ok/cache_hit outcomes are violations here).
    sampled_recs = [r for r in read_events(
        os.path.join(trace_dir, "sampled.jsonl"), strict=True)
        if r["event"] == "serve_request"
        and r["outcome"] in ("ok", "cache_hit")]
    if sampled_recs:
        failures.append(
            f"tracing: {len(sampled_recs)} successful serve_request "
            "events emitted at sample rate 0")
    # The "<1% of served-request latency" contract, measured the way
    # the claim is stated: the EXACT per-request hot path a sampled-out
    # request pays (trace create + every clock mark + batch stamp +
    # seal, no stage dict — Server._seal skips it with no consumer),
    # timed deterministically, against the FASTEST latency any request
    # sees (the sequential baseline — saturated/light served latencies
    # are strictly larger, so <1% here is <1% everywhere). The A/B
    # throughput medians above are kept for honesty, but on a 2-core
    # box their round-to-round swing is far wider than 1%: the ratio
    # measures scheduler-thread contention, not the trace cost.
    import timeit as _timeit

    from proteinbert_tpu.serve.trace import RequestTrace

    def _trace_hot_path():
        tr = RequestTrace("bench-1f", "embed", time.monotonic(),
                          sampled=False)
        # Fleet propagation rides the same hot path (ISSUE 18): every
        # routed request joins the router's trace id and answers with
        # public_id() — so the <1% gate prices that stamping in too.
        tr.join("f1a2-3f", "r0")
        tr.public_id()
        tr.mark_enqueued(time.monotonic())
        tr.mark_ingested(time.monotonic())
        tr.mark_popped(time.monotonic())
        t0 = time.monotonic()
        tr.mark_run(t0, time.monotonic())
        tr.mark_batch(seq_len, max_batch, max_batch, 0.3, 0.001, 0.002)
        tr.finish("ok", time.monotonic())
        return tr.e2e_s()

    reps = 20000
    trace_cost_us = min(
        _timeit.timeit(_trace_hot_path, number=reps) / reps * 1e6
        for _ in range(3))
    baseline_latency_us = baseline["ms_per_request"] * 1e3
    trace_cost_pct = 100.0 * trace_cost_us / baseline_latency_us

    tracing = {
        "rounds": rounds,
        "rps_per_round": {name: [round(v, 2) for v in vals]
                          for name, vals in rps.items()},
        "null_requests_per_sec": round(null_rps, 2),
        "sampled_out_requests_per_sec": round(sampled_rps, 2),
        "full_requests_per_sec": round(full_rps, 2),
        "sampled_out_overhead_pct": round(sampled_overhead, 2),
        "full_overhead_pct": round(full_overhead, 2),
        "trace_cost_us_per_request": round(trace_cost_us, 2),
        "trace_cost_pct_of_fastest_latency": round(trace_cost_pct, 3),
        "sampled_out_within_1pct": bool(trace_cost_pct < 1.0),
        "serve_request_events": len(trace_recs),
        "stage_sum_mismatches": bad_sums,
        "spans": len(ttele.spans or ()),
    }
    if trace_cost_pct >= 1.0:
        failures.append(
            f"tracing: sampled-out per-request cost {trace_cost_us:.1f}us "
            f"is {trace_cost_pct:.2f}% of the fastest served-request "
            f"latency ({baseline_latency_us:.0f}us) — breaks the <1% "
            "contract")
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)

    # ---- phase 3a: served-vs-offline bit-parity per bucket ------------
    parity = {}
    by_bucket = {}
    for s in seqs:
        by_bucket.setdefault(server.dispatcher.bucket_len(len(s)), []).append(s)
    for bucket, group in sorted(by_bucket.items()):
        group = group[:max_batch]
        psrv = Server(params, cfg, max_batch=len(group), max_wait_s=60.0,
                      cache_size=0, warm_kinds=())
        futures = [psrv.submit("embed", s) for s in group]
        psrv.scheduler.poll()  # deterministic single-batch formation
        offline = inference.embed(params, cfg, group, bucketed=True,
                                  buckets=buckets, batch_size=len(group))
        ok = all(
            np.array_equal(f.result(timeout=0)["global"],
                           offline["global"][i])
            and np.array_equal(f.result(timeout=0)["local_mean"],
                               offline["local_mean"][i])
            for i, f in enumerate(futures))
        parity[str(bucket)] = {"rows": len(group), "bit_identical": ok}
        if not ok:
            failures.append(f"served-vs-offline parity broke in "
                            f"bucket {bucket}")

    # ---- phase 3b: overflow is rejected, never dropped ----------------
    depth = max(2, max_batch // 2)
    osrv = Server(params, cfg, max_batch=max_batch, max_wait_s=60.0,
                  queue_depth=depth, cache_size=0, warm_kinds=())
    burst = [osrv.submit("embed", s) for s in seqs[: depth + 6]]
    rejected = sum(
        1 for f in burst
        if f.done() and isinstance(f.exception(), QueueFullError))
    osrv.abort()
    resolved = sum(1 for f in burst if f.done())
    overflow = {"submitted": len(burst), "queue_depth": depth,
                "rejected_queue_full": rejected,
                "all_observed": resolved == len(burst)}
    if rejected != 6:
        failures.append(f"expected 6 overflow rejections, saw {rejected}")
    if resolved != len(burst):
        failures.append("overflow burst had silently dropped requests")

    # ---- phase 4: ragged packed serving A/B (ISSUE 9) -----------------
    ragged_ab = (_serve_ragged_ab(Server, params, cfg, seqs, max_batch,
                                  max_wait_s, n_clients, failures)
                 if "ragged" in wanted else None)

    # ---- phase 5: quantized executable arm A/B (ISSUE 12) -------------
    quant_ab = (_serve_quant_ab(Server, params, cfg, seqs, max_batch,
                                max_wait_s, n_clients, failures)
                if "quant" in wanted else None)

    # ---- phase 6: fleet trace-propagation A/B (ISSUE 18) --------------
    fleet_ab = (_serve_fleet_ab(Server, params, cfg, seqs, max_batch,
                                max_wait_s, n_clients, failures)
                if "fleet" in wanted else None)

    # ---- phase 7: pipelined-dispatch depth A/B (ISSUE 19) -------------
    pipeline_ab = (_serve_pipeline_ab(Server, params, cfg, seqs,
                                      max_batch, max_wait_s, n_clients,
                                      failures)
                   if "pipeline" in wanted else None)

    record = {
        "metric": "serve_load",
        "platform": jax.devices()[0].platform,
        "seq_len": seq_len, "model_dim": dim, "median_len": median,
        "length_sigma": mix_sigma,
        "buckets": list(buckets), "max_batch": max_batch,
        "n_requests": n_requests,
        "baseline_sequential": baseline,
        "served": served,
        "speedup_x": round(served["requests_per_sec"]
                           / max(baseline["requests_per_sec"], 1e-9), 2),
        "tracing": tracing,
        "parity_per_bucket": parity,
        "overflow": overflow,
        "ragged_ab": ragged_ab,
        "quant_ab": quant_ab,
        "fleet_ab": fleet_ab,
        "pipeline_ab": pipeline_ab,
        "failures": failures,
    }
    if ragged_ab is not None:
        _mirror_ragged_note(record)
    if quant_ab is not None:
        _mirror_quant_note(record)
    if fleet_ab is not None:
        _mirror_fleet_note(record)
    if pipeline_ab is not None:
        _mirror_pipeline_note(record)
    try:  # mirror onto the shared bench event stream (best-effort)
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="serve_capture",
                platform=record["platform"], seq_len=seq_len,
                n_requests=n_requests, speedup_x=record["speedup_x"],
                served_requests_per_sec=served["requests_per_sec"],
                light_p99_ms=served["light_p99_ms"],
                trace_overhead_pct=tracing["sampled_out_overhead_pct"],
                trace_full_overhead_pct=tracing["full_overhead_pct"],
                rejected_queue_full=overflow["rejected_queue_full"],
                failures=len(failures))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)
    print(json.dumps(record))
    if failures:
        for f in failures:
            print(f"SERVE CONTRACT FAILURE: {f}", file=sys.stderr)
        sys.exit(1)


def run_neighbors():
    """`bench.py --neighbors`: the serve-the-index-not-the-trunk claim
    (ISSUE 17 acceptance) — one JSON line, CPU-measurable.

    One tiny trunk (untrained params: dispatch behavior and index
    geometry are weight-independent) drives the WHOLE production
    pipeline: `mapper.run_map` embeds a corpus into a durable store,
    `index.build_index` quantizes it into the int8 IVF index, and a
    ragged `serve.Server` with the index attached answers
    `/v1/neighbors` requests end to end.

    GATED (nonzero exit on failure):
    - **recall@10 ≥ 0.95** vs exact brute-force cosine over the fp32
      store vectors, at the served nprobe (the `heads_eval_score_min`-
      style quality floor — quantization + coarse probing must not
      change what the index answers);
    - **int8 index ≤ 0.30x** the fp32 vector bytes (builder-reported
      `bytes_ratio`);
    - **sustained lookup QPS ≥ 10x the trunk-embed QPS** — the batched
      warm scorer vs the served trunk path on the same box. The ratio
      compares the index lookup leg to the trunk leg: a neighbors
      query is index-bound, not trunk-bound, once its embedding
      exists;
    - **served-vs-offline parity**: `/v1/neighbors` through the server
      returns the same ids, in order, as `index.lookup_one` over the
      offline `inference.embed` vector;
    - every request served, no lost futures.

    Mirrored as `note(kind=neighbors_capture)` on bench_events.jsonl →
    the `neighbors_qps` / `neighbors_recall_at_10` sentinel series
    (tools/bench_trajectory.py; recall is higher-is-better).

    Knobs: PBT_NEIGHBORS_BENCH_CORPUS (192), _QUERIES (32),
    _CENTROIDS (16), _NPROBE (8), _SEQ_LEN (128), _DIM (32),
    _ROUNDS (8), _CLIENTS (8), _EMBED_REQUESTS (32).
    """
    import tempfile
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        force_cpu_backend()
    enable_compile_cache()

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.data.vocab import ALPHABET
    from proteinbert_tpu.index import build_index
    from proteinbert_tpu.index.scorer import (
        NeighborIndex, evaluate_recall, store_vectors_in_index_order,
    )
    from proteinbert_tpu.mapper.engine import run_map
    from proteinbert_tpu.serve import Server
    from proteinbert_tpu.train import create_train_state

    corpus_n = int(os.environ.get("PBT_NEIGHBORS_BENCH_CORPUS", 192))
    n_queries = int(os.environ.get("PBT_NEIGHBORS_BENCH_QUERIES", 32))
    centroids = int(os.environ.get("PBT_NEIGHBORS_BENCH_CENTROIDS", 16))
    nprobe = int(os.environ.get("PBT_NEIGHBORS_BENCH_NPROBE", 8))
    seq_len = int(os.environ.get("PBT_NEIGHBORS_BENCH_SEQ_LEN", 128))
    dim = int(os.environ.get("PBT_NEIGHBORS_BENCH_DIM", 32))
    rounds = int(os.environ.get("PBT_NEIGHBORS_BENCH_ROUNDS", 8))
    n_clients = int(os.environ.get("PBT_NEIGHBORS_BENCH_CLIENTS", 8))
    n_embed = int(os.environ.get("PBT_NEIGHBORS_BENCH_EMBED_REQUESTS", 32))

    # global_dim = 2*dim ≥ 64 keeps the int8 bytes ratio under the
    # 0.30x gate: ratio ≈ 1/4 (codes) + 1/(2*dim) (int32 assign)
    # + blocks/N (per-block fp32 scales) — at dim < 32 the assign
    # overhead alone pushes past the bound (docs/neighbors.md, sizing).
    model = ModelConfig(local_dim=dim, global_dim=2 * dim, key_dim=16,
                        num_heads=4, num_blocks=2,
                        num_annotations=128, dtype="float32")
    buckets = tuple(sorted({max(16, seq_len // 4), seq_len // 2,
                            seq_len}))
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=seq_len, batch_size=8, buckets=buckets),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=1))
    params = create_train_state(jax.random.PRNGKey(0), cfg).params

    rng = np.random.default_rng(17)
    alphabet = np.array(list(ALPHABET))
    lengths = np.clip(
        rng.lognormal(mean=np.log(seq_len // 4), sigma=0.45,
                      size=corpus_n),
        10, seq_len - 2).astype(np.int64)
    ids = [f"seq{i:05d}" for i in range(corpus_n)]
    seqs = ["".join(rng.choice(alphabet, size=int(L))) for L in lengths]

    failures = []
    record = {
        "metric": "neighbors",
        "platform": jax.devices()[0].platform,
        "seq_len": seq_len, "model_dim": dim,
        "global_dim": 2 * dim, "corpus_n": corpus_n,
        "centroids": centroids, "nprobe": nprobe,
        "failures": failures,
    }

    with tempfile.TemporaryDirectory(prefix="pbt_nbr_bench_") as tmp:
        store_dir = os.path.join(tmp, "store")
        index_dir = os.path.join(tmp, "index")

        # ---- corpus → store → index (the production build path) ----
        t0 = time.perf_counter()
        map_out = run_map(params, cfg, ids, seqs, store_dir,
                          num_shards=2, block_size=64)
        record["map_seconds"] = round(time.perf_counter() - t0, 3)
        if map_out["outcome"] != "completed":
            failures.append(f"map outcome {map_out['outcome']!r}")
        t0 = time.perf_counter()
        stats = build_index(store_dir, index_dir,
                            num_centroids=centroids, block_size=256)
        record["index_build_seconds"] = round(time.perf_counter() - t0,
                                              3)
        record["index_bytes_ratio"] = round(stats["bytes_ratio"], 4)
        record["index_vectors"] = stats["vectors"]
        if stats["outcome"] != "completed":
            failures.append(f"index outcome {stats['outcome']!r}")
        # GATE: the compression claim — int8 codes + int32 assign +
        # per-block scales vs 4 bytes/channel fp32.
        if stats["bytes_ratio"] > 0.30:
            failures.append(
                f"int8 index is {stats['bytes_ratio']:.3f}x the fp32 "
                "vector bytes (gate: <= 0.30x)")

        index = NeighborIndex.load(index_dir)
        vectors = store_vectors_in_index_order(store_dir)

        # ---- GATE: recall@10 vs exact brute force, at served nprobe --
        q_rows = rng.choice(corpus_n, size=min(n_queries, corpus_n),
                            replace=False)
        recall = evaluate_recall(index, vectors,
                                 np.asarray(vectors[q_rows]),
                                 k=10, nprobe=nprobe)
        record["recall_at_10"] = round(recall, 4)
        if recall < 0.95:
            failures.append(
                f"recall@10 {recall:.3f} at nprobe={nprobe} "
                "(gate: >= 0.95 vs exact brute force)")

        # ---- sustained lookup QPS: the batched warm scorer ----------
        qbatch = np.asarray(vectors[q_rows])
        index.lookup_rows(qbatch, k=10, nprobe=nprobe)  # warm/compile
        t0 = time.perf_counter()
        for _ in range(rounds):
            index.lookup_rows(qbatch, k=10, nprobe=nprobe)
        lookup_dt = time.perf_counter() - t0
        neighbors_qps = rounds * len(q_rows) / lookup_dt
        record["neighbors_qps"] = round(neighbors_qps, 1)
        record["lookup_executables"] = index.executables()

        # ---- trunk-embed QPS: the served trunk path -----------------
        server = Server(params, cfg, max_batch=8, max_wait_s=0.005,
                        queue_depth=4 * n_embed, cache_size=0,
                        serve_mode="ragged", trace_sample_rate=None,
                        index=index, nprobe=nprobe)
        server.start()
        try:
            results = {}

            def client(worker: int) -> None:
                for i in range(worker, n_embed, n_clients):
                    try:
                        results[i] = server.embed(seqs[i], timeout=120)
                    except Exception as e:  # noqa: BLE001
                        failures.append(f"embed {i}: "
                                        f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            embed_dt = time.perf_counter() - t0
            if len(results) != n_embed:
                failures.append(f"served {len(results)}/{n_embed} "
                                "embed requests")
            embed_qps = n_embed / embed_dt
            record["embed_qps"] = round(embed_qps, 2)
            ratio = neighbors_qps / embed_qps if embed_qps else 0.0
            record["neighbors_qps_ratio"] = round(ratio, 1)
            # GATE: serving the index must beat re-serving the trunk by
            # an order of magnitude — the reason the subsystem exists.
            if ratio < 10.0:
                failures.append(
                    f"lookup QPS is only {ratio:.1f}x trunk-embed QPS "
                    "(gate: >= 10x)")

            # ---- GATE: served-vs-offline parity ---------------------
            # Offline leg reuses the server's own embedding (the same
            # ragged executable — trunk numerics differ across batch
            # shapes, so a bucketed inference.embed vector is not the
            # comparison target): the claim is that the served lookup
            # leg IS the offline scorer, bit for bit.
            checked = 0
            for i in map(int, q_rows[:8]):
                served = server.neighbors(seqs[i], k=5,
                                          timeout=120)["neighbors"]
                off_vec = server.embed(seqs[i], timeout=120)["global"]
                offline = index.lookup_one(off_vec, k=5, nprobe=nprobe)
                if [x[0] for x in served] != [x[0] for x in offline]:
                    failures.append(
                        f"served/offline top-k mismatch for {ids[i]}: "
                        f"{[x[0] for x in served]} vs "
                        f"{[x[0] for x in offline]}")
                checked += 1
            record["parity_checked"] = checked
            record["serve_stats"] = {
                k: server.stats()["neighbors"][k]
                for k in ("num_vectors", "nprobe",
                          "lookup_executables", "by_outcome")}
        finally:
            server.drain(timeout=60)

    # Mirror onto the shared bench stream (the sentinel's input).
    try:
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="neighbors_capture",
                platform=record["platform"],
                corpus_n=corpus_n, centroids=centroids, nprobe=nprobe,
                neighbors_qps=record["neighbors_qps"],
                neighbors_recall_at_10=record["recall_at_10"],
                embed_qps=record["embed_qps"],
                neighbors_qps_ratio=record["neighbors_qps_ratio"],
                index_bytes_ratio=record["index_bytes_ratio"],
                failures=len(failures))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)

    print(json.dumps(record))
    if failures:
        for f in failures:
            print(f"NEIGHBORS GATE FAILURE: {f}", file=sys.stderr)
        sys.exit(1)


def run_heads():
    """`bench.py --heads`: the multi-tenant platform loop end to end —
    finetune → register → serve mixed-head traffic → eval — one JSON
    line, CPU-measurable (ISSUE 8 acceptance; the run_tier1.sh heads
    smoke stage).

    Phases over one tiny trunk:

    1. **finetune + register** — K tiny heads (one per task kind, 1
       epoch, synthetic labeled data, freeze_trunk so the registered
       trunk fingerprint IS the resident trunk's) land in a registry
       via the `train/finetune.finetune(registry=)` path, emitting
       `head_registered` events.
    2. **eval harness** — every head scored by heads/eval.py
       (per-residue accuracy / accuracy+AUC proxy / Spearman),
       `head_eval` events schema-validated; `eval_score_min` is the
       worst normalized score across heads — the finetune-quality
       series the bench-trajectory sentinel fits.
    3. **serving A/B** — the same mixed request population through two
       servers: MIXED (requests group by bucket only, every micro-batch
       runs ONE shared trunk pass and per-head tails) vs PARTITIONED
       (`partition_heads=True`: per-head groups — what serving degrades
       to without the shared-trunk insight). Median requests/s over
       PBT_HEADS_BENCH_ROUNDS interleaved rounds; the speedup is
       REPORTED (wall-clock on a shared box is evidence, not a gate).
    4. **contracts, GATED** — one deterministic micro-batch mixing ≥3
       distinct heads is bit-identical per row to sequential
       split-apply offline inference; the shared-trunk executable count
       stays FLAT across all serving traffic including a hot
       `add_head` on the live server; no request is ever lost; all
       emitted events validate against the schema.

    Knobs: PBT_HEADS_BENCH_SEQ_LEN (128), PBT_HEADS_BENCH_DIM (32),
    PBT_HEADS_BENCH_REQUESTS (60), PBT_HEADS_BENCH_CLIENTS (12),
    PBT_HEADS_BENCH_MAX_BATCH (8), PBT_HEADS_BENCH_ROUNDS (3),
    PBT_HEADS_BENCH_EPOCHS (1).
    """
    import tempfile
    import threading
    from statistics import median as _median

    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        force_cpu_backend()
    enable_compile_cache()

    from proteinbert_tpu.configs import (
        DataConfig, FinetuneConfig, ModelConfig, OptimizerConfig,
        PretrainConfig, TaskConfig, TrainConfig,
    )
    from proteinbert_tpu.data.synthetic import make_task_batches
    from proteinbert_tpu.data.vocab import ALPHABET
    from proteinbert_tpu.heads import HeadRegistry, trunk_fingerprint
    from proteinbert_tpu.heads import apply as heads_apply
    from proteinbert_tpu.heads.eval import evaluate_heads
    from proteinbert_tpu.obs import Telemetry, read_events
    from proteinbert_tpu.serve import TASK_KIND, Server
    from proteinbert_tpu.train import create_train_state
    from proteinbert_tpu.train.finetune import finetune

    seq_len = int(os.environ.get("PBT_HEADS_BENCH_SEQ_LEN", 128))
    dim = int(os.environ.get("PBT_HEADS_BENCH_DIM", 32))
    n_requests = int(os.environ.get("PBT_HEADS_BENCH_REQUESTS", 60))
    n_clients = int(os.environ.get("PBT_HEADS_BENCH_CLIENTS", 12))
    max_batch = int(os.environ.get("PBT_HEADS_BENCH_MAX_BATCH", 8))
    rounds = int(os.environ.get("PBT_HEADS_BENCH_ROUNDS", 3))
    epochs = int(os.environ.get("PBT_HEADS_BENCH_EPOCHS", 1))

    model = ModelConfig(local_dim=dim, global_dim=2 * dim, key_dim=16,
                        num_heads=4, num_blocks=2, num_annotations=128,
                        dtype="float32")
    buckets = (seq_len // 2, seq_len)
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=seq_len, batch_size=max_batch,
                        buckets=buckets),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=1))
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    # finetune_step donates its state — and the finetune state's trunk
    # ALIASES pretrained_trunk's arrays — so hand finetune a host copy
    # and keep `params` (the resident serving trunk) untouched.
    trunk_host = jax.tree.map(np.asarray, params)

    failures = []
    work = tempfile.mkdtemp(prefix="pbt_heads_bench_")
    events_path = os.path.join(work, "events.jsonl")
    tele = Telemetry(events_path=events_path)
    registry = HeadRegistry(os.path.join(work, "registry"))

    # ---- phase 1: finetune K heads and register them ------------------
    tasks = [("token_classification", 4), ("sequence_classification", 3),
             ("sequence_regression", 1)]
    rng = np.random.default_rng(0)
    head_ids = []
    ft_s = {}
    for i, (kind, n_out) in enumerate(tasks):
        fcfg = FinetuneConfig(
            model=model,
            task=TaskConfig(kind=kind, num_outputs=n_out, epochs=epochs,
                            freeze_trunk=True),
            data=DataConfig(seq_len=seq_len, batch_size=8),
            optimizer=OptimizerConfig(learning_rate=3e-3, warmup_steps=5,
                                      schedule="warmup_cosine",
                                      total_steps=200),
            train=TrainConfig(seed=i))
        batches = make_task_batches(32, np.random.default_rng(i), kind,
                                    n_out, seq_len, 8)
        t0 = time.perf_counter()
        out = finetune(fcfg, lambda epoch: iter(batches),
                       eval_batches=lambda: iter(batches),
                       pretrained_trunk=trunk_host, telemetry=tele,
                       registry=registry, register_name=f"bench-{kind}")
        ft_s[kind] = round(time.perf_counter() - t0, 2)
        head_ids.append(out["head_id"])
    if len(set(head_ids)) != len(tasks):
        failures.append(f"expected {len(tasks)} distinct registered "
                        f"heads, got {head_ids}")

    # ---- phase 2: downstream eval harness -----------------------------
    fp = trunk_fingerprint(params)
    heads = [registry.load(h, trunk_fp=fp) for h in head_ids]
    eval_results = evaluate_heads(
        params, model, heads,
        lambda head: make_task_batches(
            32, np.random.default_rng(99), head.task.kind,
            head.task.num_outputs, seq_len, 8),
        telemetry=tele)
    eval_score_min = min(m["score"] for m in eval_results.values())

    # ---- phase 2b: downstream eval through the QUANTIZED trunk --------
    # The int8 serving arm's numerics exactly (ISSUE 12): dequantize∘
    # quantize is precisely what the quantized executables compute from
    # their int8 weights, so evaluating the heads on that trunk scores
    # the quantized arm's downstream quality without spinning a server.
    # GATED: the worst quantized score must stay within
    # PBT_HEADS_BENCH_QUANT_SCORE_DELTA (default 0.1) of the fp32
    # worst — the `heads_eval_score_min` sentinel's green-light for the
    # quantized arm (ROADMAP item 1 acceptance; the
    # heads_eval_score_min_quant series tracks it across rounds).
    from proteinbert_tpu.parallel.quant import (
        dequantize_params, quantize_params,
    )

    quant_trunk = dequantize_params(quantize_params(params))
    eval_results_quant = evaluate_heads(
        quant_trunk, model, heads,
        lambda head: make_task_batches(
            32, np.random.default_rng(99), head.task.kind,
            head.task.num_outputs, seq_len, 8),
        telemetry=tele)
    eval_score_min_quant = min(
        m["score"] for m in eval_results_quant.values())
    quant_score_delta = float(os.environ.get(
        "PBT_HEADS_BENCH_QUANT_SCORE_DELTA", 0.1))
    if eval_score_min_quant < eval_score_min - quant_score_delta:
        failures.append(
            f"quantized-trunk downstream eval degraded past the "
            f"documented delta: min score {eval_score_min_quant:.4f} "
            f"vs fp32 {eval_score_min:.4f} "
            f"(allowed -{quant_score_delta})")

    # ---- phase 3: mixed vs head-partitioned serving -------------------
    lengths = np.clip(rng.lognormal(mean=np.log(seq_len // 6), sigma=0.4,
                                    size=n_requests),
                      8, seq_len - 2).astype(np.int64)
    alphabet = np.array(list(ALPHABET))
    seqs = ["".join(rng.choice(alphabet, size=int(L))) for L in lengths]
    assign = [head_ids[i % len(head_ids)] for i in range(n_requests)]

    def run_load(srv, clients):
        results = {}

        def client(worker):
            for i in range(worker, n_requests, clients):
                try:
                    results[i] = srv.predict_task(assign[i], seqs[i],
                                                  timeout=120)
                except Exception as e:  # noqa: BLE001
                    failures.append(
                        f"request {i}: {type(e).__name__}: {e}")
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.perf_counter() - t0
        deadline = time.monotonic() + 5.0
        prev = -1
        while time.monotonic() < deadline:
            cur = srv.scheduler.stats_counts()[1]  # locked read
            if cur == prev and len(srv.queue) == 0 \
                    and srv.scheduler.pending_rows() == 0:
                break
            prev = cur
            time.sleep(0.02)
        return results, dt

    rps = {"mixed": [], "partitioned": []}
    # One batch class keeps the warmup to one trunk compile per bucket
    # (the A/B measures scheduling, not the compile matrix).
    servers = {}
    for name, part in (("mixed", False), ("partitioned", True)):
        srv = Server(params, cfg, max_batch=max_batch, max_wait_s=0.005,
                     queue_depth=4 * n_requests, cache_size=0,
                     warm_kinds=(), batch_classes=(max_batch,),
                     telemetry=Telemetry(), trace_sample_rate=None,
                     registry=registry, heads=head_ids,
                     partition_heads=part)
        srv.start()
        run_load(srv, n_clients)  # warm pass
        servers[name] = srv
    for _ in range(rounds):  # interleaved matched rounds
        for name, srv in servers.items():
            results, dt = run_load(srv, n_clients)
            rps[name].append(len(results) / dt)
            if len(results) != n_requests:
                failures.append(
                    f"{name}: lost {n_requests - len(results)} of "
                    f"{n_requests} requests")
    mixed_stats = servers["mixed"].stats()
    part_stats = servers["partitioned"].stats()
    trunk_execs_before = servers["mixed"].dispatcher.trunk_executable_count

    # Hot add on the LIVE mixed server: a fresh head (same structure as
    # the sequence head → its tail executable is already warm) must
    # not add a trunk compile.
    from proteinbert_tpu.models import finetune as ft_model

    extra_task = TaskConfig(kind="sequence_classification", num_outputs=3)
    extra_params = ft_model.head_init(jax.random.PRNGKey(42), model,
                                      extra_task)
    extra_id = registry.save(
        jax.tree.map(np.asarray, extra_params), extra_task, fp,
        name="bench-hot-add")
    servers["mixed"].add_head(extra_id)
    got = servers["mixed"].predict_task(extra_id, seqs[0], timeout=60)
    trunk_execs_after = servers["mixed"].dispatcher.trunk_executable_count
    if trunk_execs_after != trunk_execs_before:
        failures.append(
            f"hot add_head recompiled the trunk: executable count "
            f"{trunk_execs_before} -> {trunk_execs_after}")
    if got.shape != (3,):
        failures.append(f"hot-added head returned shape {got.shape}")
    for srv in servers.values():
        srv.drain(timeout=60)

    mixed_rps = _median(rps["mixed"])
    part_rps = _median(rps["partitioned"])
    serving = {
        "requests": n_requests, "clients": n_clients,
        "n_heads": len(head_ids),
        "rps_per_round": {k: [round(v, 2) for v in vs]
                          for k, vs in rps.items()},
        "mixed_requests_per_sec": round(mixed_rps, 2),
        "partitioned_requests_per_sec": round(part_rps, 2),
        "mixed_speedup_x": round(mixed_rps / max(part_rps, 1e-9), 2),
        "mixed_batches": mixed_stats["batches"],
        "partitioned_batches": part_stats["batches"],
        "mixed_mean_rows_per_batch": round(
            mixed_stats["batched_rows"] / max(mixed_stats["batches"], 1),
            2),
        "partitioned_mean_rows_per_batch": round(
            part_stats["batched_rows"] / max(part_stats["batches"], 1),
            2),
        "trunk_executables": trunk_execs_after,
    }

    # ---- phase 4: deterministic mixed-batch bit-parity ----------------
    # Fixed short lengths: every row lands in the SAME bucket, so one
    # poll() forms exactly one micro-batch mixing all the heads.
    from proteinbert_tpu import inference

    group = ["".join(rng.choice(alphabet, size=10 + 3 * i))
             for i in range(2 * len(head_ids))]
    gassign = [head_ids[i % len(head_ids)] for i in range(len(group))]
    psrv = Server(params, cfg, max_batch=len(group), max_wait_s=60.0,
                  cache_size=0, warm_kinds=(),
                  batch_classes=(len(group),), registry=registry,
                  heads=head_ids)
    n_trunk0 = psrv.dispatcher.trunk_executable_count
    futures = [psrv.submit(TASK_KIND, s, head_id=h)
               for s, h in zip(group, gassign)]
    psrv.scheduler.poll()  # deterministic single-batch formation
    mixed_out = [f.result(timeout=30) for f in futures]
    # Read AFTER the dispatch: the whole mixed-head batch must have
    # compiled exactly ONE shared trunk executable (n_trunk0 was 0 on
    # the cold, unwarmed server).
    n_trunk_parity = psrv.dispatcher.trunk_executable_count
    if n_trunk0 != 0 or n_trunk_parity != 1:
        failures.append(
            f"parity batch expected exactly one shared trunk executable "
            f"(cold {n_trunk0} -> warm {n_trunk_parity})")
    mixed_batches = psrv.scheduler.stats_counts()[0]  # locked read
    if mixed_batches != 1:
        failures.append(
            f"parity phase expected ONE mixed micro-batch, got "
            f"{mixed_batches}")
    heads_in_batch = len(set(gassign))
    if heads_in_batch < 3:
        failures.append(f"parity batch mixed only {heads_in_batch} heads")
    psrv.abort()

    # BIT-identity gate: mixed-head batch vs PER-HEAD SEQUENTIAL
    # serving at the same (batch_class, bucket) shape — the same
    # executables run, so mixing tenants into one batch must change
    # NOTHING (per-row independence of the trunk forward).
    # max_batch = rows-per-head so each per-head group dispatches full;
    # batch_classes pins the SAME padded class shape the mixed batch
    # ran, so both paths hit the identical executable.
    ssrv = Server(params, cfg,
                  max_batch=len(group) // heads_in_batch,
                  max_wait_s=60.0, cache_size=0, warm_kinds=(),
                  batch_classes=(len(group),), registry=registry,
                  heads=head_ids, partition_heads=True)
    sfutures = [ssrv.submit(TASK_KIND, s, head_id=h)
                for s, h in zip(group, gassign)]
    for _ in range(heads_in_batch):  # one per-head batch per poll
        ssrv.scheduler.poll()
    seq_out = [f.result(timeout=30) for f in sfutures]
    parity_ok = all(np.array_equal(m, s)
                    for m, s in zip(mixed_out, seq_out))
    if not parity_ok:
        failures.append("mixed-head micro-batch is not bit-identical "
                        "to per-head sequential serving")
    seq_batches = ssrv.scheduler.stats_counts()[0]  # locked read
    if seq_batches != heads_in_batch:
        failures.append(
            f"partitioned parity server formed "
            f"{seq_batches} batches, expected "
            f"{heads_in_batch}")
    ssrv.abort()

    # Sanity vs OFFLINE single-row split-apply inference: same math,
    # different batch shape → documented fp32 tolerance (XLA reassoc-
    # iates reductions per shape; measured ~1e-6 — docs/serving.md).
    by_head = {h.head_id: h for h in heads}
    L = psrv.dispatcher.bucket_len(max(len(s) for s in group))
    offline_tol_ok = True
    for i, (s, h) in enumerate(zip(group, gassign)):
        want = heads_apply.predict_task_rows(
            params, model, by_head[h],
            inference._tokenize_masked([s], seq_len)[:, :L])[0]
        if not np.allclose(mixed_out[i], want, rtol=0, atol=1e-5):
            offline_tol_ok = False
    if not offline_tol_ok:
        failures.append("mixed-head serving drifted past the 1e-5 fp32 "
                        "tolerance vs offline split-apply inference")

    # ---- events validate ----------------------------------------------
    tele.close()
    recs = read_events(events_path, strict=True)
    n_reg = sum(1 for r in recs if r["event"] == "head_registered")
    n_ev = sum(1 for r in recs if r["event"] == "head_eval")
    # (the hot-add head was saved via registry.save directly — only the
    # finetune(registry=) path emits head_registered)
    if n_reg != len(tasks):
        failures.append(f"expected {len(tasks)} head_registered "
                        f"events, got {n_reg}")
    # Two eval passes per head: the fp32 harness and the quantized-
    # trunk arm (phase 2b).
    if n_ev != 2 * len(tasks):
        failures.append(f"expected {2 * len(tasks)} head_eval events, "
                        f"got {n_ev}")

    record = {
        "metric": "heads_load",
        "platform": jax.devices()[0].platform,
        "seq_len": seq_len, "model_dim": dim,
        "buckets": list(buckets), "max_batch": max_batch,
        "finetune_s": ft_s,
        "head_ids": head_ids,
        "eval": {h.head_id: eval_results[h.head_id] for h in heads},
        "eval_score_min": round(eval_score_min, 6),
        "eval_quant": {h.head_id: eval_results_quant[h.head_id]
                       for h in heads},
        "eval_score_min_quant": round(eval_score_min_quant, 6),
        "serving": serving,
        "parity": {"rows": len(group), "heads_mixed": heads_in_batch,
                   "bit_identical_vs_sequential": parity_ok,
                   "offline_within_1e-5": offline_tol_ok,
                   "trunk_executables": n_trunk_parity},
        "events": {"head_registered": n_reg, "head_eval": n_ev,
                   "total": len(recs)},
        "failures": failures,
    }
    try:  # mirror onto the shared bench event stream (best-effort)
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="heads_capture",
                platform=record["platform"], seq_len=seq_len,
                n_heads=len(head_ids), n_requests=n_requests,
                mixed_requests_per_sec=serving["mixed_requests_per_sec"],
                partitioned_requests_per_sec=serving[
                    "partitioned_requests_per_sec"],
                mixed_speedup_x=serving["mixed_speedup_x"],
                eval_score_min=record["eval_score_min"],
                eval_score_min_quant=record["eval_score_min_quant"],
                failures=len(failures))
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)
    import shutil

    shutil.rmtree(work, ignore_errors=True)
    print(json.dumps(record))
    if failures:
        for f in failures:
            print(f"HEADS CONTRACT FAILURE: {f}", file=sys.stderr)
        sys.exit(1)


def run_comm():
    """`bench.py --comm`: per-step collective bytes + per-chip state
    bytes, replicated vs ZeRO-1 zero-update, on a CPU-virtual mesh —
    one JSON line, so the memory/comm win is a recorded artifact
    (ISSUE 2 acceptance), CI-measurable without a TPU tunnel.

    Three numbers per mode, all derived from the COMPILED per-device
    program (not from claims): collective bytes by kind from the HLO
    (parallel/zero.collective_bytes_from_hlo), per-chip persistent
    params/opt-state bytes from the sharding rules
    (zero.per_chip_state_bytes — identical for a virtual mesh and the
    real pod shape), and the executable's memory analysis where the
    backend reports one. Knobs: PBT_COMM_MESH="dataxfsdp" (default 4x2,
    matching the 8-device test harness), PBT_COMM_DIM scales the model
    (default 64; plumbing tests use smaller). Numbers are CPU-virtual:
    byte counts are exact properties of the partitioned program, but
    ratios on real ICI/DCN await a tunnel window (PARITY.md note)."""
    import jax

    from proteinbert_tpu.utils.compat import request_cpu_devices

    mesh_spec = os.environ.get("PBT_COMM_MESH", "4x2")
    data_n, fsdp_n = (int(x) for x in mesh_spec.lower().split("x"))
    n_devices = data_n * fsdp_n
    request_cpu_devices(n_devices)
    force_cpu_backend()

    import numpy as np

    from proteinbert_tpu.configs import (
        DataConfig, MeshConfig, ModelConfig, OptimizerConfig, ParallelConfig,
        PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.parallel import batch_sharding, make_mesh
    from proteinbert_tpu.parallel.quant import make_quant_zero_train_step
    from proteinbert_tpu.parallel.sharding import state_sharding
    from proteinbert_tpu.parallel.zero import (
        collective_bytes_from_hlo, collective_wire_bytes_from_hlo,
        grad_reduce_wire_bytes, make_zero_train_step,
        per_chip_state_bytes,
    )
    from proteinbert_tpu.train import create_train_state
    from proteinbert_tpu.train import train_state as ts

    if jax.device_count() < n_devices:
        raise SystemExit(
            f"--comm needs {n_devices} virtual devices, have "
            f"{jax.device_count()} (backend initialized too early?)")

    dim = int(os.environ.get("PBT_COMM_DIM", 64))
    mesh_cfg = MeshConfig(data=data_n, fsdp=fsdp_n)
    model = ModelConfig(local_dim=dim, global_dim=2 * dim, key_dim=16,
                        num_heads=4, num_blocks=2,
                        num_annotations=max(8 * dim, 256), dtype="float32")
    base_cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=128, batch_size=2 * n_devices),
        optimizer=OptimizerConfig(warmup_steps=10),
        mesh=mesh_cfg, train=TrainConfig(max_steps=1))
    mesh = make_mesh(mesh_cfg, jax.devices()[:n_devices])
    abstract = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), base_cfg))
    bsh = batch_sharding(mesh)
    batch_abs = {
        "tokens": jax.ShapeDtypeStruct(
            (base_cfg.data.batch_size, base_cfg.data.seq_len), np.int32,
            sharding=bsh["tokens"]),
        "annotations": jax.ShapeDtypeStruct(
            (base_cfg.data.batch_size, model.num_annotations), np.float32,
            sharding=bsh["annotations"]),
    }

    # Mode table: replicated (no zero), zero (implicit fp32 reduce-
    # scatter), zero_rs_fp32 (the EXPLICIT reduce-scatter at fp32
    # payload — the like-for-like baseline the quantized wire is
    # measured against: identical program, only the payload dtype
    # differs), zero_bf16 / zero_int8 (quantized payloads).
    _GRD = {"zero_bf16": "bf16", "zero_int8": "int8"}

    def analyze(mode):
        zero = mode != "replicated"
        grd = _GRD.get(mode, "fp32")
        cfg = base_cfg.replace(parallel=ParallelConfig(
            zero_update=zero, grad_reduce_dtype=grd))
        sh = state_sharding(mesh, abstract, zero_update=zero)
        st = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, sh)
        if mode == "zero_rs_fp32":
            step = make_quant_zero_train_step(mesh, cfg, payload="fp32")
            lowered = step.lower(st, batch_abs)
        elif zero:
            lowered = make_zero_train_step(mesh, cfg).lower(st, batch_abs)
        else:
            lowered = ts.train_step.lower(st, batch_abs, cfg)
        compiled = lowered.compile()
        hlo = compiled.as_text()
        wire = collective_wire_bytes_from_hlo(hlo, n_devices)
        row = {"mode": mode,
               "collective_bytes": collective_bytes_from_hlo(hlo),
               "wire_bytes": wire,
               "grad_reduce_wire_bytes": grad_reduce_wire_bytes(wire),
               "state_bytes_per_chip": per_chip_state_bytes(
                   mesh, abstract, zero_update=zero)}
        try:  # not every backend reports memory stats
            ma = compiled.memory_analysis()
            row["hbm"] = {
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
            }
        except Exception:
            row["hbm"] = None
        return row

    modes = ("replicated", "zero", "zero_rs_fp32", "zero_bf16",
             "zero_int8")
    rows = [analyze(m) for m in modes]
    by_mode = {r["mode"]: r for r in rows}
    rep, zero = by_mode["replicated"], by_mode["zero"]
    # The quantization ratios compare the SAME explicit reduce-scatter
    # program at int8/bf16 payload vs fp32 payload — wire bytes of the
    # gradient-reduction collectives, counted from compiled HLO
    # (outputs + replica_groups), never inferred from source dtypes.
    fp32_rs = max(by_mode["zero_rs_fp32"]["grad_reduce_wire_bytes"], 1)
    int8_ratio = round(
        by_mode["zero_int8"]["grad_reduce_wire_bytes"] / fp32_rs, 4)
    bf16_ratio = round(
        by_mode["zero_bf16"]["grad_reduce_wire_bytes"] / fp32_rs, 4)
    record = {
        "metric": "zero_update_comm",
        "platform": "cpu-virtual",
        "mesh": {"data": data_n, "fsdp": fsdp_n},
        "model_dim": dim,
        "modes": rows,
        "opt_state_bytes_reduction_x": round(
            rep["state_bytes_per_chip"]["opt_state"]
            / max(zero["state_bytes_per_chip"]["opt_state"], 1), 2),
        "collective_bytes_ratio": round(
            zero["collective_bytes"]["total"]
            / max(rep["collective_bytes"]["total"], 1), 3),
        "int8_grad_wire_ratio": int8_ratio,
        "bf16_grad_wire_ratio": bf16_ratio,
    }
    try:  # mirror onto the shared bench event stream (best-effort)
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(os.path.join(os.path.dirname(LAST_GOOD_PATH),
                                   "bench_events.jsonl"))
        ev.emit("note", source="bench", kind="comm_quant",
                platform=record["platform"], model_dim=dim,
                mesh=record["mesh"],
                int8_grad_wire_ratio=int8_ratio,
                bf16_grad_wire_ratio=bf16_ratio,
                int8_grad_wire_bytes=by_mode["zero_int8"][
                    "grad_reduce_wire_bytes"],
                fp32_grad_wire_bytes=fp32_rs)
        ev.close()
    except Exception as e:
        print(f"bench events stream unavailable: {e}", file=sys.stderr)
    print(json.dumps(record))
    # GATED (ROADMAP item 1 acceptance): the int8 reduce-scatter must
    # move <= 0.30x the fp32 wire bytes. bf16 is reported, not gated
    # (its ~0.5x is arithmetic, but the gate names int8).
    if int8_ratio > 0.30:
        print(f"COMM QUANT FAILURE: int8 grad-reduction wire ratio "
              f"{int8_ratio} > 0.30 vs the fp32 reduce-scatter",
              file=sys.stderr)
        sys.exit(1)


def variant_matches(pat, variant):
    """--only matching: the bare name AND the 'name:seq/batch' shape
    key, so anchored name patterns ('u2st$') and row-targeted ones
    ('remat-convs:1024/512$') both work."""
    name, _, seq, batch = variant
    return bool(pat.search(name) or pat.search(f"{name}:{seq}/{batch}"))


def main():
    # Optional variant filter (regex on the variant name or its
    # 'name:seq/batch' shape key — `bench.py --only 'u[23]'`, or one
    # row via `--only 'remat-convs:1024/512$'`): lets a tunnel-up
    # window be spent on exactly the rows that need refreshing instead
    # of re-running the whole ~25-min sweep. The driver invokes bench.py with no args, so the
    # default (everything) and the emitted JSON contract are unchanged;
    # persist_last_good merges per-shape, so a filtered run can only add
    # or refresh rows, never drop evidence.
    import argparse
    import re

    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None, metavar="REGEX",
                    help="run only variants whose name OR shape key "
                         "'name:seq/batch' matches REGEX (e.g. "
                         "'remat-convs:1024/512$' for one row; name-"
                         "only patterns keep working unchanged)")
    ap.add_argument("--run-index", type=int, default=None, metavar="N",
                    help="internal: run ONE variant of the TPU list "
                         "in-process and print its row as JSON")
    ap.add_argument("--boundary", action="store_true",
                    help="measure train-stream stall per checkpoint "
                         "boundary (sync vs overlapped) on CPU and emit "
                         "one JSON line — the overlap win, CI-measurable "
                         "without a TPU")
    ap.add_argument("--pack", action="store_true",
                    help="measure packed vs unpacked throughput (raw AND "
                         "pad-adjusted effective residues/s, raw AND "
                         "effective MFU) on a realistic length "
                         "distribution and emit one JSON line — "
                         "CI-measurable without a TPU")
    ap.add_argument("--serve", action="store_true",
                    help="sustained-load online serving vs the "
                         "sequential single-request baseline: "
                         "throughput, p50/p99 latency, per-bucket "
                         "bit-parity, queue-overflow rejection, plus a "
                         "ragged-vs-bucketed packed-serving A/B with a "
                         "per-request parity gate — one JSON line, "
                         "CI-measurable without a TPU")
    ap.add_argument("--serve-length-mix", default=None, metavar="SPEC",
                    help="--serve request-length mix: log-normal "
                         "'median=48,sigma=0.9,seed=7' (any subset of "
                         "keys), clamped to the model window — the "
                         "mixed-length workload ragged serving exists "
                         "to speed up; default traffic is identical "
                         "to earlier captures")
    ap.add_argument("--neighbors", action="store_true",
                    help="the ANN serving claim end to end: map a "
                         "corpus into an embedding store, build the "
                         "int8 IVF index, then gate recall@10 >= 0.95 "
                         "vs brute force, index bytes <= 0.30x fp32, "
                         "lookup QPS >= 10x trunk-embed QPS, and "
                         "served-vs-offline top-k parity — one JSON "
                         "line, CPU-measurable")
    ap.add_argument("--heads", action="store_true",
                    help="the multi-tenant head platform end to end: "
                         "finetune → register → serve mixed-head "
                         "traffic vs head-partitioned batching → "
                         "downstream eval; mixed-batch bit-parity and "
                         "flat-trunk-executable contracts gated — one "
                         "JSON line, CI-measurable without a TPU")
    ap.add_argument("--comm", action="store_true",
                    help="compile the train step replicated vs ZeRO-1 "
                         "zero-update on a CPU-virtual mesh and emit one "
                         "JSON line of per-step collective bytes (from "
                         "the HLO) and per-chip state bytes (from the "
                         "sharding rules)")
    cli = ap.parse_args()

    if cli.boundary:
        run_boundary()
        return

    if cli.pack:
        run_pack()
        return

    if cli.serve:
        run_serve(length_mix=cli.serve_length_mix)
        return

    if cli.neighbors:
        run_neighbors()
        return

    if cli.heads:
        run_heads()
        return

    if cli.comm:
        run_comm()
        return

    if cli.run_index is not None:
        # Child mode. The parent already probed the tunnel; skipping the
        # re-probe keeps the child's budget for compile+measure.
        #
        # Self-destruct slightly after the parent's per-variant timeout:
        # if the PARENT is SIGKILLed mid-variant (tpu_watch kills its
        # sweep that way at SWEEP_TIMEOUT), the orphaned child would
        # otherwise sit in a hung remote compile holding the single
        # chip's PJRT client indefinitely. No handler is installed, so
        # SIGALRM's default action terminates the process even while
        # it is blocked inside native tunnel code.
        import signal

        signal.alarm(variant_timeout() + 60)
        print(json.dumps(run_variant(cli.run_index, on_tpu=True)))
        signal.alarm(0)
        return

    on_tpu, reason = probe_tpu()
    if not on_tpu:
        print(f"not benchmarking on TPU — {reason}; forcing CPU",
              file=sys.stderr)
        force_cpu_backend()

    pat = re.compile(cli.only) if cli.only is not None else None

    def select(variant_list, strict=True):
        idx = list(range(len(variant_list)))
        if pat is not None:
            hit = [i for i in idx if variant_matches(pat, variant_list[i])]
            if hit:
                return hit
            if strict:
                raise SystemExit(f"--only {cli.only!r} matches no variant")
            # CPU-fallback list (ADVICE r3): a TPU-targeted filter like
            # --only 'remat-convs-(u|st)' matches none of the 1-variant
            # CPU list; exiting here would break the "always emit the
            # JSON line" invariant — run the fallback list instead.
            print(f"--only {cli.only!r} matches no CPU-fallback variant; "
                  "running the full fallback list", file=sys.stderr)
        return idx

    variants, _ = build_variants(on_tpu)
    # Strict matching only makes sense against the TPU list the filter
    # was written for; on a probe-failed CPU start the line must still
    # be emitted.
    indices = select(variants, strict=on_tpu)

    best = None
    sweep = []  # every variant's numbers, persisted on a TPU run
    platform_seen = None
    if on_tpu:
        # One killable subprocess per variant; the parent NEVER touches
        # the backend, so exactly one PJRT client exists at a time and a
        # hung remote compile is bounded by the per-variant timeout.
        #
        # Whole-sweep wall budget: a cold-cache ~20-variant sweep can run
        # for hours, and a caller that loses patience and kills this
        # process gets NO JSON line (the round-3 parsed=null failure,
        # from the other side). The sweep is ordered by priority and
        # persists per variant, so stopping early loses only the least
        # important re-confirmations; at least one variant always runs.
        try:
            budget = int(os.environ.get("PBT_BENCH_MAX_SECONDS", 3600))
        except ValueError:
            # A malformed knob must not kill the run before its JSON
            # line — that IS the failure this budget exists to prevent.
            print("ignoring malformed PBT_BENCH_MAX_SECONDS; using 3600",
                  file=sys.stderr)
            budget = 3600
        budget = max(budget, 0)  # negatives would cap every sweep at 1
        t_start = time.time()
        attempted = 0
        longest = 0.0
        wait_s = variant_timeout()
        for i in indices:
            name = variants[i][0]
            # Project with the WORST OBSERVED duration once one variant
            # has run (projecting the per-variant timeout would stop
            # after one variant whenever it is >= the budget, starving
            # the rest forever); the timeout bound applies only before
            # any observation exists.
            projected = longest if longest else wait_s
            if (attempted and budget
                    and time.time() - t_start + projected > budget):
                print(f"sweep wall budget ({budget}s) would be exceeded "
                      f"by variant {name} (#{i}); stopping early — "
                      f"{len(sweep)} rows measured, rest keep their "
                      "persisted values", file=sys.stderr)
                break
            # Make the budget a hard bound (ADVICE r4): after the first
            # variant, clamp the child's timeout to the remaining budget
            # so a HUNG variant after fast ones can't overshoot by a
            # full variant_timeout. The first variant keeps the full
            # timeout — "at least one row" beats budget purity — and a
            # 60s floor keeps a near-exhausted budget from burning a
            # child launch on a sub-compile-time window.
            child_wait = wait_s
            if attempted and budget:
                remaining = budget - (time.time() - t_start)
                child_wait = min(wait_s, max(int(remaining), 60))
            attempted += 1
            t_variant = time.time()
            try:
                out = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--run-index", str(i)],
                    stdout=subprocess.PIPE, timeout=child_wait,
                )
            except subprocess.TimeoutExpired:
                longest = max(longest, time.time() - t_variant)
                print(f"variant {name} (#{i}) timed out after "
                      f"{child_wait}s; skipped", file=sys.stderr)
                continue
            longest = max(longest, time.time() - t_variant)
            if out.returncode != 0:
                # OOM/Mosaic rejection/tunnel error — the child's trace
                # already streamed to stderr; the sweep must go on.
                print(f"variant {name} (#{i}) failed "
                      f"(rc {out.returncode}); skipped", file=sys.stderr)
                continue
            try:
                row = json.loads(out.stdout.decode().strip().splitlines()[-1])
            except (ValueError, IndexError):
                print(f"variant {name} (#{i}) emitted no row; skipped",
                      file=sys.stderr)
                continue
            if row.pop("platform", None) != "tpu":
                # The tunnel dropped between the probe and this child's
                # first jax use and its backend fell back — a CPU-
                # measured row in a TPU sweep would fabricate the
                # last-good record (and could poison `best`). Drop it.
                print(f"variant {name} (#{i}) ran on a non-TPU backend; "
                      "row discarded", file=sys.stderr)
                continue
            platform_seen = "tpu"
            sweep.append(row)
            if best is None or row["residues_per_sec"] > best[0]:
                best = (row["residues_per_sec"], row["mfu"], row["variant"],
                        row["seq_len"], row["batch"])
            # Persist after EVERY variant: the tunnel can drop mid-sweep
            # and stall the rest — whatever already ran must survive as
            # last-good data.
            persist_last_good(sweep)
        if best is None:
            # Every child timed out, died, or fell back (tunnel dropped
            # right after the probe said yes). The bench must still emit
            # its line — fall through to the CPU fallback path below,
            # with the --only filter still honored.
            print("all TPU variants failed; falling back to CPU",
                  file=sys.stderr)
            force_cpu_backend()
            on_tpu = False
            variants, _ = build_variants(False)
            indices = select(variants, strict=False)

    if not on_tpu:
        import jax

        for i in indices:
            name = variants[i][0]
            try:
                # Same measurement body as a TPU child (one shared
                # implementation — the rows must stay comparable).
                row = run_variant(i, on_tpu=False)
            except Exception as e:
                print(f"variant {name} failed ({type(e).__name__}); skipped",
                      file=sys.stderr)
                continue
            row.pop("platform", None)
            sweep.append(row)
            if best is None or row["residues_per_sec"] > best[0]:
                best = (row["residues_per_sec"], row["mfu"], row["variant"],
                        row["seq_len"], row["batch"])
        platform_seen = jax.devices()[0].platform

    if best is None:
        raise SystemExit("all bench variants failed")
    record = build_record(best, platform_seen or "unknown")
    if record["platform"] != "tpu":
        # Tunnel-down fallback (VERDICT r3 item 5): promote the last-good
        # TPU evidence to the TOP-LEVEL record — a reader (or the driver)
        # must not see a CPU number as a 40x regression — with explicit
        # staleness provenance (stale + captured_at) and the live CPU
        # measurement demoted to a nested field. The full sweep stays in
        # bench_last_tpu.json; embedding it here is what overflowed the
        # driver's line parser in round 3 (BENCH_r03 parsed=null).
        lg = None
        try:
            with open(LAST_GOOD_PATH) as f:
                lg = json.load(f)
        except (OSError, ValueError):
            pass
        if lg and lg.get("platform") == "tpu":
            live = record
            record = {k: lg[k] for k in
                      ("metric", "value", "unit", "vs_baseline", "platform",
                       "variant", "seq_len", "batch") if k in lg}
            record["stale"] = True
            record["captured_at"] = last_good_captured_at(lg)
            # Age guard (VERDICT r4 weak #5): carry the record's age in
            # the headline and warn loudly when it exceeds the bound, so
            # a long capture gap reads as "unverified", never as a
            # standing 1.42x.
            age = stale_age_hours(record.get("captured_at"))
            if age is not None:
                record["stale_age_hours"] = round(age, 1)
                if age > stale_warn_hours():
                    print(
                        f"WARNING: promoted TPU headline is {age:.0f}h "
                        f"old (> {stale_warn_hours():.0f}h bound); its "
                        "vs_baseline predates recent commits — treat as "
                        "unverified until a fresh capture",
                        file=sys.stderr)
            record["sweep_rows"] = len(lg.get("sweep", []))
            record["live_fallback"] = {
                "platform": live["platform"], "value": live["value"],
                "vs_baseline": live["vs_baseline"]}
    print(json.dumps(record))


if __name__ == "__main__":
    main()
