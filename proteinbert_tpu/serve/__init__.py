"""Online serving subsystem (ISSUE 5 tentpole).

The offline inference surface (proteinbert_tpu/inference.py) is a
blocking batch API: every request pads to the full `cfg.data.seq_len`,
compiles one static shape, and concurrent callers serialize. This
package is the TPU-native online answer — the shape-bucketed,
continuously batched execution model of ragged-paged-attention-style
serving (PAPERS.md), built from five cooperating pieces:

- **queue** (`serve/queue.py`) — thread-safe bounded request queue with
  admission control: bounded depth with OLDEST-FIRST eviction (the
  evicted request's future fails with `QueueFullError` — rejected,
  never silently dropped), per-request deadlines, and a closed state
  that rejects new work during drain;
- **dispatch** (`serve/dispatch.py`) — one pre-warmed jitted executable
  per (bucket_len, batch_class) shape class, reusing the bucket-
  boundary semantics of `data/dataset.make_bucketed_iterator` (buckets
  ascending, last == seq_len) so a 40-residue query pays 64-length
  FLOPs, not 512; served batches shard over the mesh batch dim
  (`parallel/sharding.serve_batch_sharding`);
- **scheduler** (`serve/scheduler.py`) — continuous micro-batching:
  drains the queue under a max-batch/max-wait policy, groups requests
  by (kind, bucket), dispatches the fullest/oldest group. The clock is
  injected, so batch formation is deterministic under a fake clock
  (tests/test_serve.py);
- **cache** (`serve/cache.py`) — content-addressed (sequence-hash
  keyed) LRU result cache with hit/miss/eviction counters,
  short-circuiting repeat queries before they ever enqueue;
- **server** (`serve/server.py`) — the `Server` facade: `embed` /
  `predict_go` / `predict_residues` as sync calls or `submit()`
  futures, graceful `drain()` (in-flight batches finish, queue rejects
  new work) vs `abort()` (pending futures fail, flight-recorder note),
  `serve_*` telemetry on the same obs stream as training runs;
- **http** (`serve/http.py`) — a thin stdlib `http.server` JSON
  endpoint over the same facade (`pbt serve`).

Two dispatch modes (ISSUE 9): the default **bucketed** ladder above,
and **ragged** (`serve_mode="ragged"` / `pbt serve --serve-mode
ragged`) — heterogeneous requests PACK into fixed-shape
(rows, seq_len) rows at bucket-quantized spans via the training-side
packing representation (`data/packing.py`, tokens + segment_ids), so
ONE warm executable per request kind serves every length mix
(`RaggedDispatcher` + `PackedBatchScheduler`), with per-request
outputs matching the bucketed dispatcher's within the documented
jitted ≤1e-5 tolerance (docs/serving.md, "Ragged batching").

Benchmarked by `bench.py --serve` (throughput + latency percentiles vs
the one-request-at-a-time offline baseline); documented in
docs/serving.md.

Above single servers sits the FLEET layer (ISSUE 11, `serve/fleet.py`
/ `pbt fleet`): N replicas behind a `FleetRouter` — /healthz +
SLO-burn health states, idempotent retries with capped backoff and a
fleet-wide retry budget, typed load shedding on top of the 429/503
contract, operator drain/re-admit, a shared content-addressed result
cache, and exactly-once request sealing audited by the fault-injection
drill (`tools/fleet_drill.py`).
"""

from proteinbert_tpu.serve.cache import EmbeddingCache, content_key
from proteinbert_tpu.serve.dispatch import (
    TASK_KIND, BucketDispatcher, RaggedDispatcher,
)
from proteinbert_tpu.serve.errors import (
    DeadlineExceededError,
    QueueFullError,
    SequenceTooLongError,
    ServeError,
    ServerClosedError,
    TrunkMismatchError,
    UnknownHeadError,
)
from proteinbert_tpu.serve.fleet import (
    FaultInjector, FleetRouter, make_fleet_http_server,
)
from proteinbert_tpu.serve.queue import Request, RequestQueue
from proteinbert_tpu.serve.scheduler import (
    MicroBatchScheduler, PackedBatchScheduler,
)
from proteinbert_tpu.serve.server import SERVE_MODES, Server

from proteinbert_tpu.serve.trace import RequestTrace

__all__ = [
    "Server",
    "FleetRouter",
    "FaultInjector",
    "make_fleet_http_server",
    "SERVE_MODES",
    "BucketDispatcher",
    "RaggedDispatcher",
    "MicroBatchScheduler",
    "PackedBatchScheduler",
    "RequestQueue",
    "Request",
    "RequestTrace",
    "EmbeddingCache",
    "content_key",
    "TASK_KIND",
    "ServeError",
    "QueueFullError",
    "DeadlineExceededError",
    "ServerClosedError",
    "SequenceTooLongError",
    "UnknownHeadError",
    "TrunkMismatchError",
]
