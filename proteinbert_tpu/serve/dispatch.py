"""Bucketed shape-class dispatch: one warm executable per shape.

The offline inference path compiles ONE static shape — (batch_size,
seq_len) — so a 40-residue query pays full-seq_len FLOPs. Online
traffic is ragged; the TPU-native answer (the Operator-Fusion inference
and Ragged Paged Attention papers, PAPERS.md) is a small, fixed family
of compiled shapes kept warm, with every request routed to the
cheapest one that fits:

- **length buckets** reuse the semantics of
  `data/dataset.make_bucketed_iterator` (ascending, last == seq_len;
  a row goes to the smallest bucket that fits its tokenized length) —
  the model is shape-parametric in L, so each bucket is just one more
  executable of the same jitted function;
- **batch classes** are a short ladder (powers of two up to
  `max_batch` by default): a micro-batch of r rows is padded up to the
  smallest class ≥ r, bounding both the executable count
  (|buckets| x |classes| per request kind) and the pad waste (< 2x).

`warmup()` compiles every (bucket_len, batch_class) pair up front so
no request ever pays a compile. With a `mesh`, batches are placed
batch-dim-sharded (`parallel/sharding.serve_batch_sharding`) before
dispatch, so a multi-chip server data-parallelizes each micro-batch.

`run_rows` is the OFFLINE entry (`inference.embed(..., bucketed=True)`):
group a whole token matrix by bucket, run each group at its bucket
length, reassemble in input order — with buckets=(seq_len,) the result
is bit-identical to the unbucketed `_batched` path because both feed
the same jitted kernels the same padded shapes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from proteinbert_tpu.configs import PretrainConfig
from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID
from proteinbert_tpu import inference
from proteinbert_tpu.heads import apply as heads_apply
from proteinbert_tpu.heads.registry import LoadedHead, UnknownHeadError
from proteinbert_tpu.serve.errors import CandidateUnfitError, NoCandidateError

KINDS = ("embed", "predict_go", "predict_residues")


def _device_hbm_bytes() -> Optional[int]:
    """The accelerator's per-device memory budget in bytes, when the
    backend reports one (TPU/GPU memory_stats); None when it doesn't
    (CPU) — candidate HBM pricing then only refuses against an
    explicit budget."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — backend-optional API; absence
        # of a budget must never break candidate loading.
        return None
    if isinstance(stats, dict):
        limit = stats.get("bytes_limit")
        if isinstance(limit, int) and limit > 0:
            return limit
    return None

# The dynamic request kind (ISSUE 8): a predict_task request names a
# REGISTERED HEAD instead of a pretraining output. All predict_task
# requests — whatever head they carry — share one warm TRUNK executable
# per (bucket_len, batch_class) ("trunk" entries in `_warm`), plus a
# cheap per-head tail (heads/apply.head_batch) whose executable is
# shared by every head of the same structure. Adding a head NEVER adds
# a trunk compile (the executable-count-stays-flat contract,
# tests/test_heads.py).
TASK_KIND = "predict_task"

# The ANN request kind (ISSUE 17): a `neighbors` request's DEVICE work
# is exactly an embed — the query rides the same warm embed executables
# (bucketed and packed) and only differs after host fetch, when the
# server probes the neighbor index with the returned global embedding.
# Both dispatchers therefore NORMALIZE it to "embed" on entry: same
# jitted fn, same `_warm` key, so serving neighbors adds zero compiles.
NEIGHBORS_KIND = "neighbors"


def resolve_buckets(cfg: PretrainConfig, buckets=None) -> Tuple[int, ...]:
    """Serving bucket boundaries: the explicit argument, else the
    config's training buckets (cfg.data.buckets), else the single
    full-length bucket. Same validity rules as the bucketed iterator:
    ints, strictly ascending, last == seq_len."""
    if buckets is None:
        buckets = cfg.data.buckets or (cfg.data.seq_len,)
    try:
        buckets = tuple(int(b) for b in buckets)
    except (TypeError, ValueError):
        raise ValueError(f"buckets must be ints, got {buckets!r}") from None
    if not buckets or sorted(set(buckets)) != list(buckets):
        raise ValueError(f"buckets must be strictly ascending, got {buckets}")
    if buckets[-1] != cfg.data.seq_len:
        raise ValueError(f"last bucket {buckets[-1]} must equal "
                         f"data.seq_len {cfg.data.seq_len}")
    if buckets[0] < 3:
        raise ValueError(f"smallest bucket {buckets[0]} cannot hold "
                         "<sos> + one residue + <eos>")
    return buckets


def default_batch_classes(max_batch: int, multiple: int = 1) -> Tuple[int, ...]:
    """Ascending power-of-two ladder capped by (and always containing)
    max_batch: 8 → (1, 2, 4, 8); 12 → (1, 2, 4, 8, 12). With
    `multiple` — a mesh's data*fsdp extent — every rung is a multiple
    of it so a served batch splits evenly across the replicas:
    (16, multiple=4) → (4, 8, 16)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if multiple < 1:
        raise ValueError(f"multiple must be >= 1, got {multiple}")
    if max_batch % multiple:
        raise ValueError(
            f"max_batch {max_batch} is not divisible by the mesh's "
            f"data*fsdp extent {multiple} — pick a max_batch the mesh "
            "can split evenly over the batch dim")
    classes = []
    c = multiple
    while c < max_batch:
        classes.append(c)
        c *= 2
    classes.append(max_batch)
    return tuple(classes)


class InFlightBatch:
    """Handle for one asynchronously dispatched micro-batch (ISSUE 19).

    `run_*_async` returns one of these immediately after the jitted
    call is ENQUEUED — JAX dispatch is async, so the device computes
    while the host moves on to form the next batch. Everything that
    blocks (the `np.asarray` host fetch, per-request fan-out, the quant
    parity shadow) lives in `finalize()`, which the scheduler's
    completer thread calls when it is ready to resolve the batch. The
    sync entries (`run_timed`/`run_packed_timed`) are literally
    submit + immediate finalize, so async and sync outputs are
    bit-identical by construction (gated by tools/pipeline_smoke.py
    and the bench `pipeline` phase).
    """

    __slots__ = ("rows", "timings", "_fetch", "_result")

    def __init__(self, rows: int, timings: Dict, fetch):
        self.rows = rows
        self.timings = timings
        self._fetch = fetch
        self._result = None

    def finalize(self):
        """Block for the device result (host fetch + fan-out + parity
        shadow) and return (outputs, timings) — the exact pair the sync
        entry returns. Idempotent: a second call returns the first
        call's result."""
        if self._fetch is not None:
            out = self._fetch()
            self._result = (out, self.timings)
            self._fetch = None
        return self._result


class BucketDispatcher:
    """Routes (kind, tokens, annotations) micro-batches to the warm
    executable of their shape class and returns trimmed host outputs."""

    def __init__(
        self,
        params,
        cfg: PretrainConfig,
        buckets: Optional[Sequence[int]] = None,
        max_batch: int = 8,
        batch_classes: Optional[Sequence[int]] = None,
        mesh=None,
        metrics=None,
        quant: str = "fp32",
        quant_parity_every: int = 0,
    ):
        from proteinbert_tpu.parallel.quant import SERVE_QUANT_MODES

        if quant not in SERVE_QUANT_MODES:
            raise ValueError(f"quant must be one of {SERVE_QUANT_MODES}, "
                             f"got {quant!r}")
        self.params = params
        self.cfg = cfg
        self.buckets = resolve_buckets(cfg, buckets)
        self.max_batch = int(max_batch)
        divisor = 1
        if mesh is not None:
            divisor = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
        if batch_classes is None:
            # Mesh-aware default: every rung divisible by the replica
            # count, so `pbt serve --mesh` works out of the box.
            batch_classes = default_batch_classes(self.max_batch, divisor)
        self.batch_classes = tuple(sorted(int(c) for c in set(batch_classes)))
        if self.batch_classes[-1] < self.max_batch:
            raise ValueError(
                f"largest batch class {self.batch_classes[-1]} cannot hold "
                f"a full micro-batch of {self.max_batch}")
        self.mesh = mesh
        self._shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from proteinbert_tpu.parallel.sharding import serve_batch_sharding

            bad = [c for c in self.batch_classes if c % divisor]
            if bad:
                raise ValueError(
                    f"batch classes {bad} are not divisible by the mesh's "
                    f"data*fsdp extent {divisor} — a served batch shards "
                    "over the batch dim, so every compiled class must "
                    "split evenly across the replicas")
            self._shardings = serve_batch_sharding(mesh)
            # Replicate the trunk over the mesh devices. Orbax-restored
            # params arrive COMMITTED to one device, and a jitted call
            # mixing them with batch-dim-sharded inputs is an
            # "incompatible devices" error — so `pbt serve --mesh` from
            # any real run dir needs the explicit replicated placement
            # (batch-dim data parallelism is the serving layout; fresh
            # uncommitted params, as tests build, were merely lucky).
            self.params = jax.device_put(
                self.params, NamedSharding(mesh, PartitionSpec()))
        # Quantized executable arm (ISSUE 12): with quant != "fp32" the
        # dispatcher quantizes the trunk's weights ONCE at load time
        # (symmetric per-channel int8, parallel/quant.py) and every
        # request runs the quantized executables, which hold int8
        # weights in HBM and dequantize in-executable. The fp32 params
        # are kept resident too — they are the parity-shadow arm
        # (quant_parity_every) and the source of truth for head trunk
        # fingerprints. quant_report records the measured HBM-footprint
        # evidence; parity samples land in quant_parity_max /
        # `serve_quant_parity_max`.
        self.quant = quant
        self.quant_parity_every = int(quant_parity_every)
        # True while warmup() runs its dummy batches: quant parity
        # bookkeeping skips them (see _quant_batch_tick).
        self._warming = False
        self.qparams = None
        self.quant_report: Dict = {}
        self.quant_parity_max: Optional[float] = None
        self._quant_parity_g = (
            metrics.gauge("serve_quant_parity_max")
            if metrics is not None and quant != "fp32" else None)
        self._quant_batches = 0
        if quant != "fp32":
            from proteinbert_tpu.parallel.quant import (
                param_bytes, quantize_params,
            )

            fp32_bytes = param_bytes(self.params)
            qp = quantize_params(self.params)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                qp = jax.device_put(
                    qp, NamedSharding(mesh, PartitionSpec()))
            self.qparams = qp
            q_bytes = param_bytes(self.qparams)
            if self.quant_parity_every <= 0:
                # No parity shadow → the fp32 trunk has no device-side
                # consumer (head fingerprints hash host values), so
                # PARK IT ON HOST: resident HBM holds only the int8
                # weights — the footprint claim, honored, and the
                # headroom a second resident trunk needs. With the
                # shadow on, both trunks stay resident by design
                # (docs/serving.md documents the cost).
                self.params = jax.tree.map(np.asarray, self.params)
            self.quant_report = {
                "mode": quant,
                "weight_bytes_fp32": fp32_bytes,
                "weight_bytes_quant": q_bytes,
                "weight_bytes_ratio": round(q_bytes / max(fp32_bytes, 1),
                                            4),
                "parity_every": self.quant_parity_every,
                "fp32_resident": ("device" if self.quant_parity_every > 0
                                  else "host"),
            }
        # Blue-green candidate arm (ISSUE 20): a SECOND trunk loaded
        # beside the resident one. `cand_*` serve shadow traffic until
        # flip() atomically swaps them in as the resident arm; the
        # outgoing trunk parks on HOST (`parked_*`) for instant
        # rollback. Every batch reads its arm through _arm_snapshot()
        # under this lock, so a flip can never tear a batch across two
        # trunks.
        self._arm_lock = threading.Lock()
        self.cand_params = None  # guarded-by: _arm_lock
        self.cand_qparams = None  # guarded-by: _arm_lock
        self.parked_params = None  # guarded-by: _arm_lock
        self.parked_qparams = None  # guarded-by: _arm_lock
        self.candidate_report: Dict = {}  # guarded-by: _arm_lock
        self._compile_hist = (metrics.histogram("serve_compile_seconds")
                              if metrics is not None else None)
        # Executable-zoo accounting (ISSUE 9 satellite): how many warm
        # executables this dispatcher holds and the cumulative seconds
        # warmup() spent building them — registry gauges so the ragged
        # path's compile-count/HBM reduction is a measured, trajectory-
        # tracked claim. Mirrored in plain attributes for callers with
        # no registry (bench, tests).
        self._exec_g = (metrics.gauge("serve_executable_count")
                        if metrics is not None else None)
        self._warmup_g = (metrics.gauge("serve_warmup_seconds_total")
                          if metrics is not None else None)
        self.warmup_seconds_total = 0.0
        # Warm-shape bookkeeping. Mutated by the scheduler thread per
        # batch and READ (iterated) from client/HTTP threads
        # (warm_head, trunk_executable_count) — iteration during a
        # concurrent add is a RuntimeError in CPython, so both sides
        # take the lock (negligible next to a model call).
        self._warm: set = set()
        self._warm_lock = threading.Lock()
        # Registered heads (ISSUE 8): head_id → LoadedHead with params
        # already on device. Mutated by hot add/remove from client
        # threads while the scheduler serves — guarded; requests carry
        # their OWN head reference from admission time, so a removal
        # only affects new submits (drain semantics, serve/server.py).
        self.heads: Dict[str, LoadedHead] = {}
        self._heads_lock = threading.Lock()
        self.warmup_report: Dict = {"trunk_executables": 0,
                                    "trunk_s": 0.0, "heads": {}}

    # ------------------------------------------------------------ routing

    def bucket_len(self, seq_len_residues: int) -> int:
        """Smallest bucket holding a sequence of this many residues
        (tokenized length = residues + <sos> + <eos>, capped at the
        model window like tokenization caps it)."""
        tok_len = min(seq_len_residues + 2, self.cfg.data.seq_len)
        i = int(np.searchsorted(self.buckets, tok_len))
        return self.buckets[i]

    def batch_class(self, rows: int) -> int:
        """Smallest compiled batch class that fits `rows`."""
        for c in self.batch_classes:
            if c >= rows:
                return c
        raise ValueError(f"{rows} rows exceed the largest batch class "
                         f"{self.batch_classes[-1]}")

    # ------------------------------------------------------ head registry

    @property
    def trunk_executable_count(self) -> int:
        """Warm shared-trunk executables — the number the multi-tenant
        contract says stays FLAT across head add/remove."""
        with self._warm_lock:
            return sum(1 for k in self._warm if k[0] == "trunk")

    @property
    def executable_count(self) -> int:
        """ALL warm trunk-level executables (every kind + the shared
        trunk) — the zoo the ragged dispatcher collapses to O(kinds)."""
        with self._warm_lock:
            return len(self._warm)

    def _note_warm(self, key) -> None:
        """Record one warm executable and keep the registry gauge (and
        therefore /metrics and the bench capture) in step."""
        with self._warm_lock:
            self._warm.add(key)
            n = len(self._warm)
        if self._exec_g is not None:
            self._exec_g.set(n)

    def _note_warmup_seconds(self, seconds: float) -> None:
        self.warmup_seconds_total += seconds
        if self._warmup_g is not None:
            self._warmup_g.set(round(self.warmup_seconds_total, 6))

    def add_head(self, head: LoadedHead, warm: bool = False) -> float:
        """Register a head for predict_task serving: parameters go to
        device once, and with `warm=True` (a live server) the head's
        tail is pre-run against every already-warm trunk shape — the
        PER-HEAD INCREMENTAL warmup cost, returned in seconds and
        recorded in `warmup_report["heads"]`. The trunk is never
        recompiled (asserted by tests/test_heads.py)."""
        if self.mesh is not None:
            # Same committed-params hazard as the trunk (see __init__):
            # registry-loaded head params arrive committed to one
            # device and must be replicated to join mesh-sharded
            # trunk outputs in the jitted tail.
            from jax.sharding import NamedSharding, PartitionSpec

            placed = jax.device_put(
                head.params, NamedSharding(self.mesh, PartitionSpec()))
        else:
            placed = jax.device_put(head.params)
        head = LoadedHead(head_id=head.head_id, name=head.name,
                          task=head.task, params=placed, meta=head.meta)
        with self._heads_lock:
            self.heads[head.head_id] = head
        return self.warm_head(head) if warm else 0.0

    def remove_head(self, head_id: str) -> LoadedHead:
        """Unregister a head; raises UnknownHeadError if absent. New
        submits for it 404 immediately; already-admitted requests hold
        their own reference and complete normally (drain semantics)."""
        with self._heads_lock:
            try:
                return self.heads.pop(head_id)
            except KeyError:
                raise UnknownHeadError(
                    f"no head {head_id!r} is registered on this "
                    "server") from None

    def get_head(self, head_id: str) -> LoadedHead:
        with self._heads_lock:
            try:
                return self.heads[head_id]
            except KeyError:
                raise UnknownHeadError(
                    f"no head {head_id!r} is registered on this server; "
                    f"have {sorted(self.heads)}") from None

    def list_heads(self) -> List[Dict]:
        with self._heads_lock:
            return [{"head_id": h.head_id, "name": h.name,
                     "kind": h.task.kind,
                     "num_outputs": h.task.num_outputs}
                    for h in self.heads.values()]

    def _dummy_batch(self, L: int, cls: int):
        tokens = np.full((cls, L), PAD_ID, np.int32)
        tokens[:, 0] = SOS_ID
        tokens[:, 1] = EOS_ID
        ann = np.zeros((cls, self.cfg.model.num_annotations), np.float32)
        return tokens, ann

    def warm_head(self, head: LoadedHead) -> float:
        """Compile one head's tail for every already-warm trunk shape;
        returns the incremental seconds. The tail is warmed on ZERO
        dummies of the trunk-output shapes (local (cls, L, C) / global
        (cls, G) in the compute dtype, pad_mask (cls, L) bool) — the
        identical tail executable, with NO trunk execution at all, so a
        control-plane hot add cannot spike the data plane's tail
        latency. The trunk never compiles here:
        `trunk_executable_count` is flat across this call."""
        with self._warm_lock:
            shapes = sorted({(k[1], k[2]) for k in self._warm
                             if k[0] == "trunk"})
        dtype = jnp.dtype(self.cfg.model.dtype)
        total = 0.0
        for L, cls in shapes:
            local = jnp.zeros((cls, L, self.cfg.model.local_dim), dtype)
            global_ = jnp.zeros((cls, self.cfg.model.global_dim), dtype)
            pad_mask = jnp.zeros((cls, L), bool)
            t0 = time.perf_counter()
            jax.block_until_ready(heads_apply.head_batch(
                head.params, local, global_, pad_mask, head.task.kind))
            total += time.perf_counter() - t0
        self.warmup_report["heads"][head.head_id] = round(total, 6)
        return total

    # ----------------------------------------------------------- execution

    def _fn(self, kind: str, quantized: Optional[bool] = None):
        """The jitted entry for one request kind — the quantized arm's
        (parallel/quant.py) when this dispatcher serves quantized,
        unless `quantized=False` asks for the fp32 shadow (parity
        sampling)."""
        if quantized is None:
            quantized = self.quant != "fp32"
        if quantized:
            from proteinbert_tpu.parallel.quant import quant_entry

            return quant_entry(kind, act=self.quant == "int8_act")
        if kind == "embed":
            return inference._encode_batch
        if kind == "predict_go":
            return inference._go_probs_batch
        if kind == "predict_residues":
            return inference._residue_probs_batch
        raise ValueError(f"unknown request kind {kind!r}; have {KINDS}")

    def _run_params(self, quantized: Optional[bool] = None):
        if quantized is None:
            quantized = self.quant != "fp32"
        return self.qparams if quantized else self.params

    def _trunk_fn(self, quantized: Optional[bool] = None):
        """The shared predict_task trunk entry — quantized arm when
        configured (head TAILS always run fp32 on the trunk's outputs:
        they are tiny, and per-head quantization would multiply
        artifacts; docs/serving.md)."""
        if quantized is None:
            quantized = self.quant != "fp32"
        if quantized:
            from proteinbert_tpu.parallel.quant import _q_trunk_batch

            return _q_trunk_batch
        return heads_apply.trunk_batch

    # ------------------------------------------------ blue-green arms

    def _replicate(self, tree):
        """Device placement for a trunk-sized tree: replicated over the
        mesh when one exists (the same committed-params hazard as
        __init__), handed to jit as-is otherwise."""
        if self.mesh is None:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(tree, NamedSharding(self.mesh,
                                                  PartitionSpec()))

    def _arm_snapshot(self, arm: str = "resident"):
        """One atomic read of (serving params, fp32 reference params)
        for an executable arm — THE flip-atomicity point (ISSUE 20).
        Every batch takes both trees in a single lock hold, so a
        concurrent flip() can never hand a batch the old serving arm
        with the new parity reference (or vice versa); batches already
        submitted keep the references they captured and finish on the
        trunk they started on."""
        with self._arm_lock:
            if arm == "resident":
                params, qp = self.params, self.qparams
            elif arm == "candidate":
                params, qp = self.cand_params, self.cand_qparams
                if params is None:
                    raise NoCandidateError(
                        "no candidate trunk is loaded on this replica "
                        "(load one with Server.load_candidate / "
                        "POST /v1/rollout/load)")
            else:
                raise ValueError(f"unknown executable arm {arm!r}; "
                                 "have ('resident', 'candidate')")
        return (qp if self.quant != "fp32" else params), params

    def load_candidate(self, params,
                       hbm_budget_bytes: Optional[int] = None) -> Dict:
        """Load a candidate trunk beside the resident one (ISSUE 20).

        The candidate must be STRUCTURALLY IDENTICAL to the resident
        trunk (same tree, shapes, dtypes) — that is what lets it ride
        the resident arm's compiled executables, which are keyed on
        shapes, not on which params they run. Under quant serving the
        candidate is quantized exactly like the resident arm (and its
        fp32 source parks on host when the resident fp32 does).

        HBM pricing: the device-resident bytes of BOTH arms are summed
        and checked against `hbm_budget_bytes` (explicit argument, else
        the backend's reported per-device limit, else unenforced) —
        `CandidateUnfitError` is the typed refusal when two trunks
        don't fit; the int8 arm's ~0.27x resident bytes are the
        headroom the second trunk rides in. Returns the candidate
        report (also kept for candidate_status())."""
        from proteinbert_tpu.parallel.quant import (
            param_bytes, quantize_params,
        )

        res_leaves = jax.tree.leaves(self.params)
        cand_leaves = jax.tree.leaves(params)
        if (jax.tree.structure(params) != jax.tree.structure(self.params)
                or any(a.shape != b.shape or a.dtype != b.dtype
                       for a, b in zip(res_leaves, cand_leaves))):
            raise ValueError(
                "candidate trunk does not match the resident trunk's "
                "parameter structure/shapes/dtypes — only a "
                "structurally identical trunk can ride the warm "
                "executables (shape-keyed compile cache)")
        cand_q = None
        if self.quant != "fp32":
            cand_q = self._replicate(quantize_params(params))
            if self.quant_parity_every <= 0:
                # Mirror the resident arm: no parity shadow → the
                # fp32 source parks on host, HBM holds int8 only.
                cand_store = jax.tree.map(np.asarray, params)
            else:
                cand_store = self._replicate(params)
            cand_dev = param_bytes(cand_q)
            if self.quant_parity_every > 0:
                cand_dev += param_bytes(cand_store)
            res_dev = param_bytes(self.qparams)
            if self.quant_parity_every > 0:
                res_dev += param_bytes(self.params)
        else:
            cand_store = self._replicate(params)
            cand_dev = param_bytes(cand_store)
            res_dev = param_bytes(self.params)
        budget = (hbm_budget_bytes if hbm_budget_bytes is not None
                  else _device_hbm_bytes())
        if budget is not None and res_dev + cand_dev > budget:
            raise CandidateUnfitError(
                f"candidate trunk needs {cand_dev} device bytes beside "
                f"the resident arm's {res_dev} ({res_dev + cand_dev} "
                f"total > HBM budget {budget}) — two fp32 trunks don't "
                "fit; serve --quant int8 (~0.27x resident bytes) to "
                "buy the headroom, or raise the budget")
        report = {
            "quant": self.quant,
            "weight_bytes_resident": int(res_dev),
            "weight_bytes_candidate": int(cand_dev),
            "hbm_budget_bytes": budget,
        }
        with self._arm_lock:
            self.cand_params = cand_store
            self.cand_qparams = cand_q
            self.candidate_report = dict(report)
        return report

    def warm_candidate(self) -> float:
        """Pre-run the candidate arm over every already-warm trunk-level
        shape. The executables are keyed on shapes/dtypes, not on the
        params they run, so the candidate boots THROUGH the compile
        cache — this pass proves that (zero new compiles; `_warm` and
        the executable gauge stay flat) and faults in the candidate's
        device placement before any shadow traffic arrives. Returns
        wall seconds."""
        with self._warm_lock:
            keys = sorted(self._warm)
        run_params, _ = self._arm_snapshot("candidate")
        t0 = time.perf_counter()
        self._warming = True
        try:
            for kind, L, cls in keys:
                tokens, ann = self._dummy_batch(L, cls)
                tb, ab = self._place(tokens, ann)
                fn = (self._trunk_fn() if kind == "trunk"
                      else self._fn(kind))
                jax.block_until_ready(
                    fn(run_params, tb, ab, self.cfg.model))
        finally:
            self._warming = False
        return time.perf_counter() - t0

    def flip(self) -> float:
        """Atomic promotion: the candidate becomes the resident arm in
        one lock hold — batches already submitted keep the params they
        captured (zero dropped, zero torn), batches submitted after
        this return see only the new trunk. The outgoing trunk parks on
        HOST (so HBM never holds three trunks) for instant rollback().
        Returns wall seconds (dominated by the device→host park
        fetch, which runs before the swap, outside the lock)."""
        t0 = time.perf_counter()
        with self._arm_lock:
            if self.cand_params is None:
                raise NoCandidateError(
                    "flip asked with no candidate trunk loaded")
            old_p, old_q = self.params, self.qparams
        # Park the outgoing arm on host BEFORE taking the swap lock:
        # in-flight batches read it concurrently (read-only), and the
        # swap itself stays O(pointer).
        parked = jax.tree.map(np.asarray, old_p)
        parked_q = (jax.tree.map(np.asarray, old_q)
                    if old_q is not None else None)
        with self._arm_lock:
            if self.cand_params is None:
                raise NoCandidateError(
                    "candidate trunk vanished mid-flip (concurrent "
                    "flip/unload)")
            self.params = self.cand_params
            self.qparams = self.cand_qparams
            self.cand_params = None
            self.cand_qparams = None
            self.parked_params = parked
            self.parked_qparams = parked_q
        return time.perf_counter() - t0

    def rollback(self) -> float:
        """Instant rollback: the parked trunk returns as the resident
        arm — bit-identical numerics, because the parked arrays are
        exact host copies of the pre-flip weights feeding the exact
        same executables — and the demoted trunk moves back to the
        candidate slot (still warm, so a fixed re-promotion does not
        reload). Raises NoCandidateError when nothing is parked."""
        t0 = time.perf_counter()
        with self._arm_lock:
            if self.parked_params is None:
                raise NoCandidateError(
                    "rollback asked with no parked trunk")
            demoted_p, demoted_q = self.params, self.qparams
            self.params = self._replicate(self.parked_params)
            self.qparams = (self._replicate(self.parked_qparams)
                            if self.parked_qparams is not None else None)
            self.cand_params = demoted_p
            self.cand_qparams = demoted_q
            self.parked_params = None
            self.parked_qparams = None
        return time.perf_counter() - t0

    def unload_candidate(self) -> bool:
        """Drop the candidate arm (rollout abort / gate refusal); the
        resident arm is untouched. Returns whether one was loaded."""
        with self._arm_lock:
            had = self.cand_params is not None
            self.cand_params = None
            self.cand_qparams = None
            self.candidate_report = {}
        return had

    def candidate_status(self) -> Dict:
        """Arm occupancy + the candidate report, one atomic read."""
        with self._arm_lock:
            return {"loaded": self.cand_params is not None,
                    "parked": self.parked_params is not None,
                    **self.candidate_report}

    def run_candidate(self, kind: str, tokens: np.ndarray,
                      annotations: Optional[np.ndarray] = None,
                      heads: Optional[Sequence[LoadedHead]] = None):
        """Run one micro-batch on the CANDIDATE arm, synchronously —
        the shadow-mirror entry (ISSUE 20). Identical prep/padding to
        `run` on the same warm executables (shape-keyed, so the
        candidate rides the resident arm's compiles), but nothing here
        touches the quant parity cadence or any live-path accounting."""
        result, _ = self.run_timed_async(
            kind, tokens, annotations, timed=False, heads=heads,
            arm="candidate").finalize()
        return result

    @staticmethod
    def _parity_max(a, b) -> float:
        """Max abs elementwise deviation between two same-structure
        outputs (dicts/arrays/lists of arrays) on host; boolean leaves
        (masks) are excluded — identical by construction, and their
        arithmetic difference is meaningless."""
        worst = 0.0
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            xa, ya = np.asarray(x), np.asarray(y)
            if xa.dtype == np.bool_ or ya.dtype == np.bool_:
                continue
            if xa.size:
                worst = max(worst, float(np.max(np.abs(
                    xa.astype(np.float32) - ya.astype(np.float32)))))
        return worst

    def _quant_batch_tick(self, timings: Dict) -> bool:
        """Per-batch quant bookkeeping shared by every dispatch path:
        stamp the arm onto the timings (UNCONDITIONALLY — the
        absent-means-fp32 event contract must hold on untimed batches
        too; the schedulers merge these fields from a timed=False
        run), advance the batch counter, and decide whether THIS batch
        runs the fp32 parity shadow. Warmup dummy batches are excluded
        entirely: they must neither consume the parity cadence nor
        count all-PAD compiles as LIVE parity samples."""
        if self.quant == "fp32" or self._warming:
            return False
        timings["quant"] = self.quant
        self._quant_batches += 1
        return (self.quant_parity_every > 0
                and (self._quant_batches - 1)
                % self.quant_parity_every == 0)

    def _shadow_parity(self, out, ref_thunk,
                       timings: Dict) -> None:
        """Run the fp32 shadow (`ref_thunk`), record the worst
        per-request deviation against `out` — the one implementation
        every (bucketed|ragged) x (kind|heads) path shares."""
        worst = self._parity_max(out, ref_thunk())
        self.quant_parity_max = max(self.quant_parity_max or 0.0, worst)
        self.quant_report["parity_max"] = round(self.quant_parity_max, 9)
        self.quant_report["parity_samples"] = (
            self.quant_report.get("parity_samples", 0) + 1)
        if self._quant_parity_g is not None:
            self._quant_parity_g.set(round(self.quant_parity_max, 9))
        timings["quant_parity_max"] = round(worst, 9)

    def _place(self, tokens: np.ndarray, annotations: np.ndarray):
        if self._shardings is None:
            return jnp.asarray(tokens), jnp.asarray(annotations)
        return (jax.device_put(tokens, self._shardings["tokens"]),
                jax.device_put(annotations, self._shardings["annotations"]))

    def run(self, kind: str, tokens: np.ndarray,
            annotations: Optional[np.ndarray] = None,
            heads: Optional[Sequence[LoadedHead]] = None):
        """Run one micro-batch: tokens (r, L) with L a bucket length,
        annotations (r, A) or None. Rows are padded up to the batch
        class, outputs come back trimmed to r on host.

        Returns {"global", "local_mean"} for "embed", (r, A) probs for
        "predict_go", (r, L, V) probs for "predict_residues". For
        "predict_task", `heads` carries row i's LoadedHead and the
        return is a list of r per-row float32 head outputs (shapes
        differ between heads of different task kinds).
        """
        result, _ = self.run_timed(kind, tokens, annotations,
                                   timed=False, heads=heads)
        return result

    def run_timed(self, kind: str, tokens: np.ndarray,
                  annotations: Optional[np.ndarray] = None,
                  timed: bool = True,
                  heads: Optional[Sequence[LoadedHead]] = None):
        """`run()` that also returns stage attribution for request
        traces: {"prep_s": pad + device placement, "device_s": model
        call through host fetch (the compile lands here on a cold
        shape), "finalize_s": the host-fetch share of device_s,
        "pad_fraction": padding share of the (batch_class, L) grid the
        executable actually ran — row padding up to the class plus
        token padding within rows}. Implemented as submit + immediate
        finalize of the async entry, so sync and pipelined dispatch
        share one code path (and therefore bit-identical outputs)."""
        return self.run_timed_async(kind, tokens, annotations,
                                    timed=timed, heads=heads).finalize()

    def run_timed_async(self, kind: str, tokens: np.ndarray,
                        annotations: Optional[np.ndarray] = None,
                        timed: bool = True,
                        heads: Optional[Sequence[LoadedHead]] = None,
                        arm: str = "resident") -> InFlightBatch:
        """Submit one micro-batch and return an `InFlightBatch` as soon
        as the jitted call is enqueued (ISSUE 19). Validation, padding,
        device placement and the model call happen here on the calling
        (scheduler) thread; the blocking host fetch, head tails and the
        parity shadow run in the handle's `finalize()`. `arm` selects
        the trunk (ISSUE 20): "resident" is the live arm, "candidate"
        the blue-green shadow arm — both trees are read atomically via
        `_arm_snapshot`, so a concurrent flip never tears a batch."""
        if kind == NEIGHBORS_KIND:
            kind = "embed"  # identical device work, shared executable
        rows, L = tokens.shape
        if L not in self.buckets:
            raise ValueError(f"tokens length {L} is not one of the "
                             f"buckets {self.buckets}")
        if (kind == TASK_KIND) != (heads is not None):
            raise ValueError(
                f"kind {kind!r} and heads={'set' if heads is not None else 'None'} "
                "do not agree: predict_task batches carry per-row heads, "
                "pretrain kinds never do")
        timings: Dict[str, float] = {}
        t0 = time.perf_counter() if timed else 0.0
        annotations = inference.check_annotations(annotations, rows, self.cfg)
        cls = self.batch_class(rows)
        if timed:
            real = int((tokens != PAD_ID).sum())
            timings["pad_fraction"] = round(1.0 - real / (cls * L), 6)
        if rows < cls:
            tokens = np.pad(tokens, ((0, cls - rows), (0, 0)))
            annotations = np.pad(annotations, ((0, cls - rows), (0, 0)))
        tb, ab = self._place(tokens, annotations)
        t1 = time.perf_counter()
        if timed:
            timings["prep_s"] = round(t1 - t0, 9)
        run_params, ref_params = self._arm_snapshot(arm)
        parity_due = (arm == "resident"
                      and self._quant_batch_tick(timings))
        if heads is not None:
            # Multi-tenant path: ONE shared trunk executable for the
            # whole (possibly mixed-head) batch, then each distinct
            # head's cheap tail over the full batch — every row keeps
            # its own head's output (heads/apply.py). The tails ride
            # in the fetch closure: they are tiny, and the trunk — the
            # device work worth overlapping — is already in flight.
            trunk_out = self._trunk_fn()(run_params, tb, ab,
                                         self.cfg.model)
            self._note_warm(("trunk", L, cls))

            def fetch():
                out = heads_apply.apply_heads(trunk_out, heads)
                if parity_due:
                    self._shadow_parity(
                        out,
                        lambda: heads_apply.apply_heads(
                            heads_apply.trunk_batch(ref_params, tb, ab,
                                                    self.cfg.model),
                            heads),
                        timings)
                return out
        else:
            fn = self._fn(kind)
            res = fn(run_params, tb, ab, self.cfg.model)
            self._note_warm((kind, L, cls))

            def fetch():
                out = jax.tree.map(lambda a: np.asarray(a)[:rows], res)
                if parity_due:
                    self._shadow_parity(
                        out,
                        lambda: jax.tree.map(
                            lambda a: np.asarray(a)[:rows],
                            self._fn(kind, quantized=False)(
                                ref_params, tb, ab, self.cfg.model)),
                        timings)
                return out

        def finalize_fetch():
            tf = time.perf_counter()
            out = fetch()
            if timed:
                now = time.perf_counter()
                timings["device_s"] = round(now - t1, 9)
                timings["finalize_s"] = round(now - tf, 9)
            return out

        return InFlightBatch(rows, timings, finalize_fetch)

    def warmup(self, kinds: Sequence[str] = ("embed",)) -> int:
        """Pre-compile every (bucket_len, batch_class) executable for the
        given kinds so no live request pays a compile; returns how many
        shape classes were warmed. Cost is |kinds| x |buckets| x
        |classes| compiles — keep `kinds` to what the deployment
        serves (the others compile lazily on first use).

        The predict_task family warms automatically whenever heads are
        registered (or "predict_task" is named in `kinds`): the SHARED
        trunk compiles once per (bucket, class) — counted in the return
        value and `warmup_report["trunk_executables"]` — and every
        registered head's tail is pre-run with its per-head incremental
        cost recorded in `warmup_report["heads"]`. Heads added LATER to
        a live server never recompile the trunk (`add_head(warm=True)`
        pays only the tail).

        Wall seconds spent here accumulate into the
        `serve_warmup_seconds_total` gauge (`warmup_seconds_total`
        attribute) and every warm shape lands in
        `serve_executable_count` — the executable-zoo accounting
        (ISSUE 9 satellite) the ragged dispatcher's O(kinds) claim is
        measured against."""
        t_warm = time.perf_counter()
        n = 0
        kinds = tuple(kinds)
        self._warming = True
        try:
            for kind in kinds:
                if kind == TASK_KIND:
                    continue
                if kind not in KINDS:
                    raise ValueError(f"unknown request kind {kind!r}; "
                                     f"have {KINDS + (TASK_KIND,)}")
                for L in self.buckets:
                    for cls in self.batch_classes:
                        if (kind, L, cls) in self._warm:
                            continue
                        dummy, _ = self._dummy_batch(L, cls)
                        if self._compile_hist is not None:
                            t0 = time.perf_counter()
                            self.run(kind, dummy)
                            self._compile_hist.observe(
                                time.perf_counter() - t0)
                        else:
                            self.run(kind, dummy)
                        n += 1
            if TASK_KIND in kinds or self.heads:
                n += self._warmup_task()
        finally:
            self._warming = False
        self._note_warmup_seconds(time.perf_counter() - t_warm)
        return n

    def _warmup_task(self) -> int:
        """Warm the shared trunk once per (bucket, class) and every
        registered head's tail at each shape; returns NEW trunk
        executables warmed. Per-head seconds land in
        `warmup_report["heads"]` — on a warm trunk they are the cost of
        compiling one tiny matmul tail (and near-zero for a second head
        of the same structure, which shares the tail executable)."""
        report = self.warmup_report
        with self._heads_lock:
            heads = list(self.heads.values())
        n = 0
        for L in self.buckets:
            for cls in self.batch_classes:
                tokens, ann = self._dummy_batch(L, cls)
                tb, ab = self._place(tokens, ann)
                with self._warm_lock:
                    new = ("trunk", L, cls) not in self._warm
                t0 = time.perf_counter()
                trunk_out = self._trunk_fn()(self._run_params(), tb, ab,
                                             self.cfg.model)
                jax.block_until_ready(trunk_out)
                dt = time.perf_counter() - t0
                if new:
                    self._note_warm(("trunk", L, cls))
                    report["trunk_executables"] += 1
                    report["trunk_s"] = round(report["trunk_s"] + dt, 6)
                    if self._compile_hist is not None:
                        self._compile_hist.observe(dt)
                    n += 1
                for head in heads:
                    t0 = time.perf_counter()
                    jax.block_until_ready(heads_apply.head_batch(
                        head.params, trunk_out["local"],
                        trunk_out["global"], trunk_out["pad_mask"],
                        head.task.kind))
                    report["heads"][head.head_id] = round(
                        report["heads"].get(head.head_id, 0.0)
                        + time.perf_counter() - t0, 6)
        return n

    # ------------------------------------------------- offline batch path

    def run_rows(self, kind: str, tokens: np.ndarray,
                 annotations: Optional[np.ndarray], batch_size: int):
        """Offline whole-matrix entry: group (N, seq_len) rows by
        bucket, run each group at its bucket length in input-order
        chunks of `batch_size`, reassemble results by original row
        index. `predict_residues` probability tails beyond a row's
        bucket are zero-filled back to seq_len (pad positions)."""
        n = tokens.shape[0]
        annotations = inference.check_annotations(annotations, n, self.cfg)
        lengths = (tokens != PAD_ID).sum(axis=1)
        bucket_of = np.searchsorted(self.buckets, lengths)
        out: Dict[str, np.ndarray] = {}
        flat: Optional[np.ndarray] = None
        for b, L in enumerate(self.buckets):
            idx = np.flatnonzero(bucket_of == b)
            for lo in range(0, len(idx), batch_size):
                sel = idx[lo : lo + batch_size]
                res = self.run(kind, tokens[sel][:, :L], annotations[sel])
                if kind == "embed":
                    for k, v in res.items():
                        if k not in out:
                            out[k] = np.zeros((n,) + v.shape[1:], v.dtype)
                        out[k][sel] = v
                elif kind == "predict_go":
                    if flat is None:
                        flat = np.zeros((n, res.shape[1]), res.dtype)
                    flat[sel] = res
                else:  # predict_residues: zero-fill the pad tail
                    if flat is None:
                        flat = np.zeros(
                            (n, self.cfg.data.seq_len, res.shape[2]),
                            res.dtype)
                    flat[sel, :L] = res
        return out if kind == "embed" else flat


class RaggedDispatcher(BucketDispatcher):
    """Ragged PACKED dispatch (ISSUE 9 tentpole): ONE warm executable
    per request kind at the fixed shape (rows_per_batch, seq_len),
    consuming the training-side packed representation {tokens,
    segment_ids, annotations} (data/packing.py) instead of a
    (bucket_len, batch_class) ladder.

    Requests are packed at BUCKET-QUANTIZED spans: a request's span is
    its `bucket_len` (same ladder as the bucketed dispatcher), its
    tokens `[<sos> seq <eos> <pad>...]` fill the span, and segment_ids
    cover the WHOLE span. That quantization is what makes ragged-mode
    outputs match the bucketed dispatcher's on identical traffic
    (within the documented jitted ≤1e-5 tolerance, PR 7 precedent):

    - the boundary-masked conv (`kernels/fused_block._segment_conv`)
      zeroes taps outside the span, which is EXACTLY the zero halo a
      'SAME'-padded conv sees at a (cls, bucket_len) array's edges —
      and in-span <pad> positions contribute their <pad> embeddings to
      nearby taps just as they do inside a bucketed row;
    - attention/pooling exclude in-span <pad> positions via the real-
      token mask, exactly as the bucketed path's pad_mask does.

    Unlike the bucketed ladder, the bucket set here costs NO
    executables — it is purely a span-quantization rule (the compiled
    shape is always (rows_per_batch, seq_len)), so a deployment that
    prefers density over bucketed-parity can run a much denser ladder
    for free (docs/serving.md, ragged batching).

    Executable count: O(request kinds) + one shared packed trunk for
    predict_task + per-head-structure tails, versus the bucketed
    |buckets| x |classes| x kinds zoo — tracked by the same
    `serve_executable_count` gauge.
    """

    def __init__(
        self,
        params,
        cfg: PretrainConfig,
        buckets: Optional[Sequence[int]] = None,
        rows_per_batch: int = 4,
        max_segments: int = 8,
        mesh=None,
        metrics=None,
        quant: str = "fp32",
        quant_parity_every: int = 0,
    ):
        if rows_per_batch < 1:
            raise ValueError(f"rows_per_batch must be >= 1, "
                             f"got {rows_per_batch}")
        if max_segments < 1:
            raise ValueError(f"max_segments must be >= 1, "
                             f"got {max_segments}")
        if quant == "int8_act":
            raise ValueError(
                "quant='int8_act' is a bucketed-arm option: the packed "
                "executables have no activation fake-quant variant "
                "(use quant='int8' for weight-only quantized ragged "
                "serving — docs/serving.md)")
        # Mesh support (ISSUE 11 satellite, PR 8 residual): packed rows
        # shard over the joint ('data','fsdp') batch axis exactly like
        # bucketed micro-batches (serve_batch_sharding — segment_ids
        # shard like the tokens they annotate). The single batch class
        # (rows_per_batch,) must split evenly across the replicas; the
        # parent ctor enforces that and builds self._shardings.
        super().__init__(params, cfg, buckets=buckets,
                         max_batch=rows_per_batch,
                         batch_classes=(rows_per_batch,), mesh=mesh,
                         metrics=metrics, quant=quant,
                         quant_parity_every=quant_parity_every)
        self.rows_per_batch = int(rows_per_batch)
        self.max_segments = int(max_segments)

    # ----------------------------------------------------------- execution

    def _place_packed(self, tokens: np.ndarray, segment_ids: np.ndarray,
                      annotations: np.ndarray):
        """Host packed batch → device arrays, batch-dim-sharded over the
        mesh when one was passed (serve_batch_sharding)."""
        if self._shardings is None:
            return (jnp.asarray(tokens), jnp.asarray(segment_ids),
                    jnp.asarray(annotations))
        return (jax.device_put(tokens, self._shardings["tokens"]),
                jax.device_put(segment_ids, self._shardings["segment_ids"]),
                jax.device_put(annotations, self._shardings["annotations"]))

    def _packed_fn(self, kind: str, quantized: Optional[bool] = None):
        if quantized is None:
            quantized = self.quant != "fp32"
        if quantized:
            from proteinbert_tpu.parallel.quant import quant_packed_entry

            return quant_packed_entry(kind)
        if kind == "embed":
            return inference._packed_encode_batch
        if kind == "predict_go":
            return inference._packed_go_probs_batch
        if kind == "predict_residues":
            return inference._packed_residue_probs_batch
        raise ValueError(f"unknown request kind {kind!r}; have {KINDS}")

    def _packed_trunk_fn(self, quantized: Optional[bool] = None):
        if quantized is None:
            quantized = self.quant != "fp32"
        if quantized:
            from proteinbert_tpu.parallel.quant import (
                _q_packed_trunk_batch,
            )

            return _q_packed_trunk_batch
        return heads_apply.packed_trunk_batch

    def run_timed(self, *args, **kwargs):
        raise NotImplementedError(
            "RaggedDispatcher consumes packed batches only — use "
            "run_packed()/run_packed_timed() "
            "(serve/scheduler.PackedBatchScheduler builds them)")

    def run_timed_async(self, *args, **kwargs):
        raise NotImplementedError(
            "RaggedDispatcher consumes packed batches only — use "
            "run_packed_timed_async() "
            "(serve/scheduler.PackedBatchScheduler builds them)")

    def run_packed(self, kind: str, tokens: np.ndarray,
                   segment_ids: np.ndarray, annotations: np.ndarray,
                   riders: Sequence[Tuple[int, int, int, int]],
                   heads=None) -> List:
        outs, _ = self.run_packed_timed(kind, tokens, segment_ids,
                                        annotations, riders, heads=heads,
                                        timed=False)
        return outs

    def run_packed_timed(self, kind: str, tokens: np.ndarray,
                         segment_ids: np.ndarray, annotations: np.ndarray,
                         riders: Sequence[Tuple[int, int, int, int]],
                         heads=None, timed: bool = True):
        """Run one packed batch synchronously — submit + immediate
        finalize of `run_packed_timed_async`, so sync and pipelined
        dispatch share one code path (bit-identical outputs)."""
        return self.run_packed_timed_async(
            kind, tokens, segment_ids, annotations, riders, heads=heads,
            timed=timed).finalize()

    def run_packed_timed_async(self, kind: str, tokens: np.ndarray,
                               segment_ids: np.ndarray,
                               annotations: np.ndarray,
                               riders: Sequence[Tuple[int, int, int, int]],
                               heads=None, timed: bool = True,
                               arm: str = "resident") -> InFlightBatch:
        """Submit one packed batch through the kind's single warm
        executable; the returned `InFlightBatch.finalize()` fans
        per-segment outputs back out after the host fetch (ISSUE 19).

        tokens/segment_ids are (rows_per_batch, seq_len), annotations
        (rows_per_batch, max_segments, A). `riders` carries one
        (row, segment_index, start, span) per request, row-major, with
        segment_index 0-based; for `predict_task`, `heads` is the
        aligned per-rider LoadedHead list. Returns (per-rider outputs
        aligned with `riders`, timings) — each output has the SAME
        shape the bucketed dispatcher returns for that request:
        {"global" (G,), "local_mean" (C,)} / (A,) probs /
        (span, V) probs / the rider's head output.
        """
        if kind == NEIGHBORS_KIND:
            kind = "embed"  # identical device work, shared executable
        R, L = tokens.shape
        if (R, L) != (self.rows_per_batch, self.cfg.data.seq_len):
            raise ValueError(
                f"packed tokens shape {(R, L)} != the compiled "
                f"({self.rows_per_batch}, {self.cfg.data.seq_len})")
        if (kind == TASK_KIND) != (heads is not None):
            raise ValueError(
                f"kind {kind!r} and "
                f"heads={'set' if heads is not None else 'None'} do not "
                "agree: predict_task batches carry per-rider heads, "
                "pretrain kinds never do")
        timings: Dict[str, float] = {}
        t0 = time.perf_counter() if timed else 0.0
        if timed:
            real = int((tokens != PAD_ID).sum())
            timings["pad_fraction"] = round(1.0 - real / (R * L), 6)
            timings["segments"] = len(riders)
            timings["segments_per_row"] = round(len(riders) / R, 4)
        tb, sb, ab = self._place_packed(tokens, segment_ids, annotations)
        t1 = time.perf_counter()
        if timed:
            timings["prep_s"] = round(t1 - t0, 9)
        run_params, ref_params = self._arm_snapshot(arm)
        parity_due = (arm == "resident"
                      and self._quant_batch_tick(timings))

        def fan_out(host):
            fanned = []
            for row, seg, start, span in riders:
                if kind == "embed":
                    fanned.append(
                        {"global": host["global"][row, seg],
                         "local_mean": host["local_mean"][row, seg]})
                elif kind == "predict_go":
                    fanned.append(host[row, seg])
                else:  # predict_residues: the span lines up with the
                    # bucketed (bucket_len, V) output
                    fanned.append(host[row, start:start + span])
            return fanned

        if heads is not None:
            trunk_out = self._packed_trunk_fn()(
                run_params, tb, sb, ab, self.cfg.model)
            self._note_warm(("trunk", L, R))

            def fetch():
                outs = heads_apply.apply_heads_packed(
                    trunk_out,
                    [(h,) + tuple(r) for h, r in zip(heads, riders)])
                if parity_due:
                    self._shadow_parity(
                        outs,
                        lambda: heads_apply.apply_heads_packed(
                            heads_apply.packed_trunk_batch(
                                ref_params, tb, sb, ab, self.cfg.model),
                            [(h,) + tuple(r)
                             for h, r in zip(heads, riders)]),
                        timings)
                return outs
        else:
            res = self._packed_fn(kind)(run_params, tb, sb, ab,
                                        self.cfg.model)
            self._note_warm((kind, L, R))

            def fetch():
                outs = fan_out(jax.tree.map(np.asarray, res))
                if parity_due:
                    self._shadow_parity(
                        outs,
                        lambda: fan_out(jax.tree.map(
                            np.asarray,
                            self._packed_fn(kind, quantized=False)(
                                ref_params, tb, sb, ab,
                                self.cfg.model))),
                        timings)
                return outs

        def finalize_fetch():
            tf = time.perf_counter()
            outs = fetch()
            if timed:
                now = time.perf_counter()
                timings["device_s"] = round(now - t1, 9)
                timings["finalize_s"] = round(now - tf, 9)
            return outs

        return InFlightBatch(len(riders), timings, finalize_fetch)

    # ------------------------------------------------------------- warmup

    def _dummy_packed(self):
        """One syntactically valid packed batch (a minimal-span segment
        per row) — content is irrelevant to the compile."""
        R, L = self.rows_per_batch, self.cfg.data.seq_len
        span = self.buckets[0]
        tokens = np.full((R, L), PAD_ID, np.int32)
        tokens[:, 0] = SOS_ID
        tokens[:, 1] = EOS_ID
        seg = np.zeros((R, L), np.int32)
        seg[:, :span] = 1
        ann = np.zeros((R, self.max_segments,
                        self.cfg.model.num_annotations), np.float32)
        riders = [(r, 0, 0, span) for r in range(R)]
        return tokens, seg, ann, riders

    def warm_candidate(self) -> float:
        """Pre-run the candidate arm over the warm PACKED executables —
        same zero-new-compiles contract as the bucketed override (the
        packed fns are shape-keyed too). Returns wall seconds."""
        with self._warm_lock:
            keys = sorted(self._warm)
        run_params, _ = self._arm_snapshot("candidate")
        tokens, seg, ann, _riders = self._dummy_packed()
        tb, sb, ab = self._place_packed(tokens, seg, ann)
        t0 = time.perf_counter()
        self._warming = True
        try:
            for kind, _L, _R in keys:
                fn = (self._packed_trunk_fn() if kind == "trunk"
                      else self._packed_fn(kind))
                jax.block_until_ready(
                    fn(run_params, tb, sb, ab, self.cfg.model))
        finally:
            self._warming = False
        return time.perf_counter() - t0

    def run_candidate(self, *args, **kwargs):
        raise NotImplementedError(
            "RaggedDispatcher consumes packed batches only — use "
            "run_packed_candidate() (serve/server.shadow_submit builds "
            "the single-rider packed batch)")

    def run_packed_candidate(self, kind: str, tokens: np.ndarray,
                             segment_ids: np.ndarray,
                             annotations: np.ndarray,
                             riders: Sequence[Tuple[int, int, int, int]],
                             heads=None) -> List:
        """`run_packed` on the CANDIDATE arm — the ragged shadow-mirror
        entry (see the bucketed `run_candidate`)."""
        outs, _ = self.run_packed_timed_async(
            kind, tokens, segment_ids, annotations, riders, heads=heads,
            timed=False, arm="candidate").finalize()
        return outs

    def warmup(self, kinds: Sequence[str] = ("embed",)) -> int:
        """Pre-compile the ONE packed executable per kind (plus the
        shared packed trunk + per-head tails when heads are in play);
        returns how many were warmed. Compare with the bucketed
        dispatcher's |kinds| x |buckets| x |classes| — this is the
        executable-zoo collapse the `serve_executable_count` gauge
        measures."""
        t_warm = time.perf_counter()
        n = 0
        kinds = tuple(kinds)
        R, L = self.rows_per_batch, self.cfg.data.seq_len
        tokens, seg, ann, riders = self._dummy_packed()
        self._warming = True
        try:
            for kind in kinds:
                if kind == TASK_KIND:
                    continue
                if kind not in KINDS:
                    raise ValueError(f"unknown request kind {kind!r}; "
                                     f"have {KINDS + (TASK_KIND,)}")
                if (kind, L, R) in self._warm:
                    continue
                if self._compile_hist is not None:
                    t0 = time.perf_counter()
                    self.run_packed(kind, tokens, seg, ann, riders)
                    self._compile_hist.observe(time.perf_counter() - t0)
                else:
                    self.run_packed(kind, tokens, seg, ann, riders)
                n += 1
            if TASK_KIND in kinds or self.heads:
                n += self._warmup_task()
        finally:
            self._warming = False
        self._note_warmup_seconds(time.perf_counter() - t_warm)
        return n

    def _warmup_task(self) -> int:
        """Warm the shared PACKED trunk (once — one shape total) and
        every registered head's packed tail; returns new trunk
        executables (0 or 1)."""
        report = self.warmup_report
        with self._heads_lock:
            heads = list(self.heads.values())
        R, L = self.rows_per_batch, self.cfg.data.seq_len
        tokens, seg, ann, _ = self._dummy_packed()
        tb, sb, ab = self._place_packed(tokens, seg, ann)
        with self._warm_lock:
            new = ("trunk", L, R) not in self._warm
        t0 = time.perf_counter()
        trunk_out = self._packed_trunk_fn()(self._run_params(), tb, sb,
                                            ab, self.cfg.model)
        jax.block_until_ready(trunk_out)
        dt = time.perf_counter() - t0
        n = 0
        if new:
            self._note_warm(("trunk", L, R))
            report["trunk_executables"] += 1
            report["trunk_s"] = round(report["trunk_s"] + dt, 6)
            if self._compile_hist is not None:
                self._compile_hist.observe(dt)
            n = 1
        for head in heads:
            t0 = time.perf_counter()
            jax.block_until_ready(heads_apply.packed_head_batch(
                head.params, trunk_out["local"], trunk_out["global"],
                trunk_out["seg_mask"], head.task.kind))
            report["heads"][head.head_id] = round(
                report["heads"].get(head.head_id, 0.0)
                + time.perf_counter() - t0, 6)
        return n

    def warm_head(self, head: LoadedHead) -> float:
        """Compile one head's PACKED tail against the (single) packed
        trunk shape on zero dummies — no trunk execution, the same
        control-plane/data-plane separation as the bucketed
        `warm_head`. The trunk never compiles here."""
        with self._warm_lock:
            has_trunk = any(k[0] == "trunk" for k in self._warm)
        if not has_trunk:
            self.warmup_report["heads"][head.head_id] = 0.0
            return 0.0
        dtype = jnp.dtype(self.cfg.model.dtype)
        R, L, S = (self.rows_per_batch, self.cfg.data.seq_len,
                   self.max_segments)
        local = jnp.zeros((R, L, self.cfg.model.local_dim), dtype)
        global_ = jnp.zeros((R, S, self.cfg.model.global_dim), dtype)
        seg_mask = jnp.zeros((R, S, L), bool)
        t0 = time.perf_counter()
        jax.block_until_ready(heads_apply.packed_head_batch(
            head.params, local, global_, seg_mask, head.task.kind))
        total = time.perf_counter() - t0
        self.warmup_report["heads"][head.head_id] = round(total, 6)
        return total
