"""Bucketed shape-class dispatch: one warm executable per shape.

The offline inference path compiles ONE static shape — (batch_size,
seq_len) — so a 40-residue query pays full-seq_len FLOPs. Online
traffic is ragged; the TPU-native answer (the Operator-Fusion inference
and Ragged Paged Attention papers, PAPERS.md) is a small, fixed family
of compiled shapes kept warm, with every request routed to the
cheapest one that fits:

- **length buckets** reuse the semantics of
  `data/dataset.make_bucketed_iterator` (ascending, last == seq_len;
  a row goes to the smallest bucket that fits its tokenized length) —
  the model is shape-parametric in L, so each bucket is just one more
  executable of the same jitted function;
- **batch classes** are a short ladder (powers of two up to
  `max_batch` by default): a micro-batch of r rows is padded up to the
  smallest class ≥ r, bounding both the executable count
  (|buckets| x |classes| per request kind) and the pad waste (< 2x).

`warmup()` compiles every (bucket_len, batch_class) pair up front so
no request ever pays a compile. With a `mesh`, batches are placed
batch-dim-sharded (`parallel/sharding.serve_batch_sharding`) before
dispatch, so a multi-chip server data-parallelizes each micro-batch.

`run_rows` is the OFFLINE entry (`inference.embed(..., bucketed=True)`):
group a whole token matrix by bucket, run each group at its bucket
length, reassemble in input order — with buckets=(seq_len,) the result
is bit-identical to the unbucketed `_batched` path because both feed
the same jitted kernels the same padded shapes.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from proteinbert_tpu.configs import PretrainConfig
from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID
from proteinbert_tpu import inference

KINDS = ("embed", "predict_go", "predict_residues")


def resolve_buckets(cfg: PretrainConfig, buckets=None) -> Tuple[int, ...]:
    """Serving bucket boundaries: the explicit argument, else the
    config's training buckets (cfg.data.buckets), else the single
    full-length bucket. Same validity rules as the bucketed iterator:
    ints, strictly ascending, last == seq_len."""
    if buckets is None:
        buckets = cfg.data.buckets or (cfg.data.seq_len,)
    try:
        buckets = tuple(int(b) for b in buckets)
    except (TypeError, ValueError):
        raise ValueError(f"buckets must be ints, got {buckets!r}") from None
    if not buckets or sorted(set(buckets)) != list(buckets):
        raise ValueError(f"buckets must be strictly ascending, got {buckets}")
    if buckets[-1] != cfg.data.seq_len:
        raise ValueError(f"last bucket {buckets[-1]} must equal "
                         f"data.seq_len {cfg.data.seq_len}")
    if buckets[0] < 3:
        raise ValueError(f"smallest bucket {buckets[0]} cannot hold "
                         "<sos> + one residue + <eos>")
    return buckets


def default_batch_classes(max_batch: int, multiple: int = 1) -> Tuple[int, ...]:
    """Ascending power-of-two ladder capped by (and always containing)
    max_batch: 8 → (1, 2, 4, 8); 12 → (1, 2, 4, 8, 12). With
    `multiple` — a mesh's data*fsdp extent — every rung is a multiple
    of it so a served batch splits evenly across the replicas:
    (16, multiple=4) → (4, 8, 16)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if multiple < 1:
        raise ValueError(f"multiple must be >= 1, got {multiple}")
    if max_batch % multiple:
        raise ValueError(
            f"max_batch {max_batch} is not divisible by the mesh's "
            f"data*fsdp extent {multiple} — pick a max_batch the mesh "
            "can split evenly over the batch dim")
    classes = []
    c = multiple
    while c < max_batch:
        classes.append(c)
        c *= 2
    classes.append(max_batch)
    return tuple(classes)


class BucketDispatcher:
    """Routes (kind, tokens, annotations) micro-batches to the warm
    executable of their shape class and returns trimmed host outputs."""

    def __init__(
        self,
        params,
        cfg: PretrainConfig,
        buckets: Optional[Sequence[int]] = None,
        max_batch: int = 8,
        batch_classes: Optional[Sequence[int]] = None,
        mesh=None,
        metrics=None,
    ):
        self.params = params
        self.cfg = cfg
        self.buckets = resolve_buckets(cfg, buckets)
        self.max_batch = int(max_batch)
        divisor = 1
        if mesh is not None:
            divisor = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
        if batch_classes is None:
            # Mesh-aware default: every rung divisible by the replica
            # count, so `pbt serve --mesh` works out of the box.
            batch_classes = default_batch_classes(self.max_batch, divisor)
        self.batch_classes = tuple(sorted(int(c) for c in set(batch_classes)))
        if self.batch_classes[-1] < self.max_batch:
            raise ValueError(
                f"largest batch class {self.batch_classes[-1]} cannot hold "
                f"a full micro-batch of {self.max_batch}")
        self.mesh = mesh
        self._shardings = None
        if mesh is not None:
            from proteinbert_tpu.parallel.sharding import serve_batch_sharding

            bad = [c for c in self.batch_classes if c % divisor]
            if bad:
                raise ValueError(
                    f"batch classes {bad} are not divisible by the mesh's "
                    f"data*fsdp extent {divisor} — a served batch shards "
                    "over the batch dim, so every compiled class must "
                    "split evenly across the replicas")
            self._shardings = serve_batch_sharding(mesh)
        self._compile_hist = (metrics.histogram("serve_compile_seconds")
                              if metrics is not None else None)
        self._warm: set = set()

    # ------------------------------------------------------------ routing

    def bucket_len(self, seq_len_residues: int) -> int:
        """Smallest bucket holding a sequence of this many residues
        (tokenized length = residues + <sos> + <eos>, capped at the
        model window like tokenization caps it)."""
        tok_len = min(seq_len_residues + 2, self.cfg.data.seq_len)
        i = int(np.searchsorted(self.buckets, tok_len))
        return self.buckets[i]

    def batch_class(self, rows: int) -> int:
        """Smallest compiled batch class that fits `rows`."""
        for c in self.batch_classes:
            if c >= rows:
                return c
        raise ValueError(f"{rows} rows exceed the largest batch class "
                         f"{self.batch_classes[-1]}")

    # ----------------------------------------------------------- execution

    def _fn(self, kind: str):
        if kind == "embed":
            return inference._encode_batch
        if kind == "predict_go":
            return inference._go_probs_batch
        if kind == "predict_residues":
            return inference._residue_probs_batch
        raise ValueError(f"unknown request kind {kind!r}; have {KINDS}")

    def _place(self, tokens: np.ndarray, annotations: np.ndarray):
        if self._shardings is None:
            return jnp.asarray(tokens), jnp.asarray(annotations)
        return (jax.device_put(tokens, self._shardings["tokens"]),
                jax.device_put(annotations, self._shardings["annotations"]))

    def run(self, kind: str, tokens: np.ndarray,
            annotations: Optional[np.ndarray] = None):
        """Run one micro-batch: tokens (r, L) with L a bucket length,
        annotations (r, A) or None. Rows are padded up to the batch
        class, outputs come back trimmed to r on host.

        Returns {"global", "local_mean"} for "embed", (r, A) probs for
        "predict_go", (r, L, V) probs for "predict_residues".
        """
        result, _ = self.run_timed(kind, tokens, annotations,
                                   timed=False)
        return result

    def run_timed(self, kind: str, tokens: np.ndarray,
                  annotations: Optional[np.ndarray] = None,
                  timed: bool = True):
        """`run()` that also returns stage attribution for request
        traces: {"prep_s": pad + device placement, "device_s": model
        call through host fetch (the compile lands here on a cold
        shape), "pad_fraction": padding share of the (batch_class, L)
        grid the executable actually ran — row padding up to the class
        plus token padding within rows}."""
        rows, L = tokens.shape
        if L not in self.buckets:
            raise ValueError(f"tokens length {L} is not one of the "
                             f"buckets {self.buckets}")
        timings: Dict[str, float] = {}
        t0 = time.perf_counter() if timed else 0.0
        annotations = inference.check_annotations(annotations, rows, self.cfg)
        cls = self.batch_class(rows)
        if timed:
            real = int((tokens != PAD_ID).sum())
            timings["pad_fraction"] = round(1.0 - real / (cls * L), 6)
        if rows < cls:
            tokens = np.pad(tokens, ((0, cls - rows), (0, 0)))
            annotations = np.pad(annotations, ((0, cls - rows), (0, 0)))
        fn = self._fn(kind)
        tb, ab = self._place(tokens, annotations)
        if timed:
            t1 = time.perf_counter()
            timings["prep_s"] = round(t1 - t0, 9)
        res = fn(self.params, tb, ab, self.cfg.model)
        self._warm.add((kind, L, cls))
        out = jax.tree.map(lambda a: np.asarray(a)[:rows], res)
        if timed:
            timings["device_s"] = round(time.perf_counter() - t1, 9)
        return out, timings

    def warmup(self, kinds: Sequence[str] = ("embed",)) -> int:
        """Pre-compile every (bucket_len, batch_class) executable for the
        given kinds so no live request pays a compile; returns how many
        shape classes were warmed. Cost is |kinds| x |buckets| x
        |classes| compiles — keep `kinds` to what the deployment
        serves (the others compile lazily on first use)."""
        n = 0
        for kind in kinds:
            if kind not in KINDS:
                raise ValueError(f"unknown request kind {kind!r}; "
                                 f"have {KINDS}")
            for L in self.buckets:
                for cls in self.batch_classes:
                    if (kind, L, cls) in self._warm:
                        continue
                    dummy = np.full((cls, L), PAD_ID, np.int32)
                    dummy[:, 0] = SOS_ID
                    dummy[:, 1] = EOS_ID
                    if self._compile_hist is not None:
                        t0 = time.perf_counter()
                        self.run(kind, dummy)
                        self._compile_hist.observe(time.perf_counter() - t0)
                    else:
                        self.run(kind, dummy)
                    n += 1
        return n

    # ------------------------------------------------- offline batch path

    def run_rows(self, kind: str, tokens: np.ndarray,
                 annotations: Optional[np.ndarray], batch_size: int):
        """Offline whole-matrix entry: group (N, seq_len) rows by
        bucket, run each group at its bucket length in input-order
        chunks of `batch_size`, reassemble results by original row
        index. `predict_residues` probability tails beyond a row's
        bucket are zero-filled back to seq_len (pad positions)."""
        n = tokens.shape[0]
        annotations = inference.check_annotations(annotations, n, self.cfg)
        lengths = (tokens != PAD_ID).sum(axis=1)
        bucket_of = np.searchsorted(self.buckets, lengths)
        out: Dict[str, np.ndarray] = {}
        flat: Optional[np.ndarray] = None
        for b, L in enumerate(self.buckets):
            idx = np.flatnonzero(bucket_of == b)
            for lo in range(0, len(idx), batch_size):
                sel = idx[lo : lo + batch_size]
                res = self.run(kind, tokens[sel][:, :L], annotations[sel])
                if kind == "embed":
                    for k, v in res.items():
                        if k not in out:
                            out[k] = np.zeros((n,) + v.shape[1:], v.dtype)
                        out[k][sel] = v
                elif kind == "predict_go":
                    if flat is None:
                        flat = np.zeros((n, res.shape[1]), res.dtype)
                    flat[sel] = res
                else:  # predict_residues: zero-fill the pad tail
                    if flat is None:
                        flat = np.zeros(
                            (n, self.cfg.data.seq_len, res.shape[2]),
                            res.dtype)
                    flat[sel, :L] = res
        return out if kind == "embed" else flat
