"""Per-request trace context for the serving path (ISSUE 6 tentpole).

A `RequestTrace` rides on a `Request` from `Server.submit()` through
queue → scheduler → dispatcher → cache → response, collecting one clock
mark per stage boundary. All marks come from the SERVER's injected
clock (`time.monotonic` in production, a fake clock in tests), so a
trace's stage durations are deterministic under `poll(now=)` and the
stage decomposition is exact by construction: stages are CONTIGUOUS
intervals between consecutive marks, so they always sum to the
end-to-end latency (the acceptance property `bench.py --serve` checks
on live traffic).

Stage names, in request order:

| stage        | interval                               | covers |
|--------------|----------------------------------------|--------|
| `submit`     | submit() entry → queue push            | admission, tokenize, cache lookup |
| `queue`      | queue push → scheduler ingest          | waiting for the scheduler to wake |
| `batch_form` | ingest → popped into a batch           | waiting for max_batch / max_wait |
| `dispatch`   | popped → model call                    | stacking, padding, device_put (+compile on a cold shape) |
| `execute`    | model call → outputs on host           | device execute + host fetch |
| `lookup`     | outputs on host → ANN answer           | neighbor-index probe (ISSUE 17; `neighbors` requests only) |
| `finalize`   | outputs on host → future resolved      | cache insert, result shaping |

The `lookup` stage exists only on `/v1/neighbors` requests (the
embed-leg stages before it are unchanged); when present it is inserted
between `execute` and `finalize`, so the stage set still tiles the
end-to-end interval by construction — `pbt diagnose --serve` splits
neighbor latency into embed leg (everything before `lookup`) and
lookup leg on exactly that property.

A request that exits early (cache hit, eviction, rejection, abort)
simply has fewer marks; its last present stage absorbs the remainder.

Cost contract: a trace is ~10 float slots plus one clock read per
stage boundary — cheap enough that EVERY request carries one whenever
telemetry is enabled (errors/rejections must trace even when sampled
out). Emission (the `serve_request` event + Perfetto spans) happens
only for sampled or non-`ok` requests. With the NULL telemetry facade
no trace is created at all and every touchpoint is a `None` check.

Stdlib-only (no jax, no numpy): importable anywhere obs is.
"""

from __future__ import annotations

import math
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

# Spans shorter than this are dropped from the Perfetto export (not
# from the event's stages dict): zero-width slices only clutter the UI.
_MIN_SPAN_S = 1e-7

STAGES = ("submit", "queue", "batch_form", "dispatch", "execute",
          "lookup", "finalize")


def stride_sampled(seq: int, rate: float) -> bool:
    """Deterministic stride sampling: True for floor(seq*rate) ticks —
    exactly `rate` of consecutive sequence numbers, no RNG state."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return math.floor(seq * rate) != math.floor((seq - 1) * rate)


class RequestTrace:
    """Stage-mark accumulator for one served request."""

    __slots__ = (
        "request_id", "kind", "sampled", "wall0",
        "t_submit", "t_enqueued", "t_ingested", "t_popped",
        "t_run0", "t_run1", "t_lookup", "t_done",
        "bucket_len", "batch_class", "rows", "pad_fraction",
        "prep_s", "device_s", "cache", "outcome", "error", "head_id",
        "segments", "segments_per_row", "mode", "quant",
        "trace_id", "parent", "replica_id",
    )

    def __init__(self, request_id: str, kind: str, now: float,
                 sampled: bool = True, wall: Optional[float] = None):
        self.request_id = request_id
        self.kind = kind
        self.sampled = sampled
        # Wall-clock anchor for Perfetto (monotonic marks are offsets
        # from t_submit); taken once so a fake clock stays fake.
        self.wall0 = time.time() if wall is None else wall
        self.t_submit = now
        self.t_enqueued: Optional[float] = None
        self.t_ingested: Optional[float] = None
        self.t_popped: Optional[float] = None
        self.t_run0: Optional[float] = None
        self.t_run1: Optional[float] = None
        self.t_lookup: Optional[float] = None
        self.t_done: Optional[float] = None
        self.bucket_len: Optional[int] = None
        self.batch_class: Optional[int] = None
        self.rows: Optional[int] = None
        self.pad_fraction: Optional[float] = None
        self.prep_s: Optional[float] = None
        self.device_s: Optional[float] = None
        self.cache: str = "off"          # off | miss | hit
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        self.head_id: Optional[str] = None  # predict_task tenant id —
                                            # per-head latency/error
                                            # attribution in
                                            # `pbt diagnose --serve`
        # Ragged packed serving (ISSUE 9): how many requests (segments)
        # shared the rider's packed batch, the batch's mean occupancy,
        # and which dispatch mode ran it. None on the bucketed path.
        self.segments: Optional[int] = None
        self.segments_per_row: Optional[float] = None
        self.mode: Optional[str] = None
        # Quantized executable arm (ISSUE 12): "int8"/"int8_act" when
        # a quantized executable served this request, None on fp32.
        self.quant: Optional[str] = None
        # Fleet-scope causal context (ISSUE 18): `trace_id` is the
        # router-minted id this request joined via the X-PBT-Trace
        # header (None = self-rooted, standalone server), `parent` the
        # enclosing fleet request's id (== trace_id in the current
        # two-level router→replica topology), `replica_id` the serving
        # process's --replica-id identity. All ride the serve_request
        # event so the fleet collector can join cross-process records
        # without inferring identity from ports.
        self.trace_id: Optional[str] = None
        self.parent: Optional[str] = None
        self.replica_id: Optional[str] = None

    def join(self, trace_id: Optional[str],
             replica_id: Optional[str] = None) -> None:
        """Adopt a propagated fleet-scope trace context (no-ops on
        None): after joining, public_id() answers with the FLEET id —
        the X-PBT-Request-Id value clients see end-to-end."""
        if trace_id:
            self.trace_id = trace_id
            self.parent = trace_id
        if replica_id:
            self.replica_id = replica_id

    def public_id(self) -> str:
        """The id this request answers to externally: the fleet-scope
        trace id when joined, the local request id when self-rooted."""
        return self.trace_id or self.request_id

    # ------------------------------------------------------------ marks

    def mark_enqueued(self, now: float) -> None:
        self.t_enqueued = now

    def mark_ingested(self, now: float) -> None:
        self.t_ingested = now

    def mark_popped(self, now: float) -> None:
        self.t_popped = now

    def mark_run(self, t0: float, t1: float) -> None:
        self.t_run0 = t0
        self.t_run1 = t1

    def mark_lookup(self, now: float) -> None:
        """End of the neighbor-index probe (ISSUE 17). Setting it
        splits the interval after `execute` into `lookup` (ANN) and
        `finalize` (cache insert / result shaping); without it the
        stage set is unchanged."""
        self.t_lookup = now

    def mark_batch(self, bucket_len: int, batch_class: int, rows: int,
                   pad_fraction: Optional[float] = None,
                   prep_s: Optional[float] = None,
                   device_s: Optional[float] = None,
                   segments: Optional[int] = None,
                   segments_per_row: Optional[float] = None,
                   mode: Optional[str] = None) -> None:
        """Batch-level context, stamped onto every rider of the batch
        (same executable, same padded grid — the attribution is shared
        by construction). On the ragged path `bucket_len` is the
        rider's SPAN (its bucket-quantized length inside the packed
        row), `batch_class` the executable's fixed row count, and
        `segments`/`segments_per_row`/`mode` describe the packing."""
        self.bucket_len = bucket_len
        self.batch_class = batch_class
        self.rows = rows
        self.pad_fraction = pad_fraction
        self.prep_s = prep_s
        self.device_s = device_s
        self.segments = segments
        self.segments_per_row = segments_per_row
        self.mode = mode

    # ---------------------------------------------------------- finish

    @property
    def finished(self) -> bool:
        return self.outcome is not None

    def finish(self, outcome: str, now: float,
               error: Optional[BaseException] = None) -> bool:
        """Seal the trace; False if it was already sealed (a request
        must reach exactly one terminal outcome — double-finish would
        mean orphaned/duplicated spans)."""
        if self.outcome is not None:
            return False
        self.outcome = outcome
        self.t_done = now
        if error is not None:
            self.error = f"{type(error).__name__}: {error}"
        return True

    # ------------------------------------------------------- derived

    def _chain(self) -> Tuple[List[Tuple[str, float]], float]:
        """(present marks clamped MONOTONIC, end). Marks come from two
        threads' reads of the same clock (a scheduler poll() takes its
        `now` once, so a request enqueued mid-poll can carry
        t_enqueued > t_ingested by a few ms): clamping each mark to its
        predecessor — and the end to the last mark — keeps the
        stages-tile-e2e invariant exact instead of intermittently off
        by the thread-interleave gap."""
        marks = [("submit", self.t_submit), ("queue", self.t_enqueued),
                 ("batch_form", self.t_ingested),
                 ("dispatch", self.t_popped), ("execute", self.t_run0)]
        if self.t_lookup is not None:
            # Neighbor request: the interval after the device run
            # splits into the ANN probe and the true finalize tail.
            marks += [("lookup", self.t_run1),
                      ("finalize", self.t_lookup)]
        else:
            marks += [("finalize", self.t_run1)]
        present: List[Tuple[str, float]] = []
        prev = None
        for name, t in marks:
            if t is None:
                continue
            if prev is not None and t < prev:
                t = prev
            present.append((name, t))
            prev = t
        end = self.t_done if self.t_done is not None else self.t_submit
        if prev is not None:
            end = max(end, prev)
        return present, end

    def _segments(self) -> List[Tuple[str, float, float]]:
        """Contiguous (stage, start, end) intervals from the present
        marks. Each stage ends at the NEXT present mark (finally at
        the trace end), so the intervals tile [t_submit, end] exactly."""
        present, end = self._chain()
        segments = []
        for i, (name, t0) in enumerate(present):
            t1 = present[i + 1][1] if i + 1 < len(present) else end
            segments.append((name, t0, max(t0, t1)))
        return segments

    def stages(self) -> Dict[str, float]:
        return {name: round(t1 - t0, 9)
                for name, t0, t1 in self._segments()}

    def e2e_s(self) -> float:
        _, end = self._chain()
        return max(0.0, end - self.t_submit)

    def event_fields(self, stages: Optional[Dict[str, float]] = None,
                     ) -> Dict[str, Any]:
        """Payload for the `serve_request` event (schema: obs/events).
        Pass `stages` when the caller already derived them (the seal
        path) to avoid re-walking the mark chain per request."""
        fields: Dict[str, Any] = {
            "request_id": self.request_id,
            "kind": self.kind,
            "outcome": self.outcome or "ok",
            "stages": self.stages() if stages is None else stages,
            "e2e_s": round(self.e2e_s(), 9),
            "cache": self.cache,
            "sampled": self.sampled,
        }
        for name in ("bucket_len", "batch_class", "rows", "pad_fraction",
                     "prep_s", "device_s", "error", "head_id",
                     "segments", "segments_per_row", "mode", "quant",
                     "trace_id", "parent", "replica_id"):
            v = getattr(self, name)
            if v is not None:
                fields[name] = v
        return fields

    def export_spans(self, collector) -> None:
        """Replay the trace into a SpanCollector as one parent span
        (`serve.request`) plus one child per stage, on a per-request
        synthetic lane (tid = crc32 of the id) so concurrent requests
        do not nest into each other."""
        tid = zlib.crc32(self.request_id.encode()) & 0x7FFFFFFF
        base_args = {"request_id": self.request_id, "kind": self.kind,
                     "outcome": self.outcome or "ok"}
        if self.head_id is not None:
            base_args["head_id"] = self.head_id
        if self.bucket_len is not None:
            base_args["bucket_len"] = self.bucket_len
        if self.batch_class is not None:
            base_args["batch_class"] = self.batch_class
        if self.error is not None:
            base_args["error"] = self.error
        if self.trace_id is not None:
            base_args["trace_id"] = self.trace_id
        collector.add("serve.request", self.wall0, self.e2e_s(),
                      depth=0, tid=tid, **base_args)
        for name, t0, t1 in self._segments():
            if t1 - t0 < _MIN_SPAN_S:
                continue
            collector.add(f"serve.{name}", self.wall0 + (t0 - self.t_submit),
                          t1 - t0, depth=1, tid=tid,
                          request_id=self.request_id,
                          outcome=self.outcome or "ok")
