"""Self-healing serve fleet: a tiny router in front of N replicas.

One `pbt serve` process is a single point of failure — a crash, a bad
host, or a draining deploy takes the whole endpoint down. This module
turns "a server" into "a service" (ROADMAP item 2): N serve replicas
(each an ordinary `pbt serve` HTTP endpoint, in-process or subprocess)
behind a `FleetRouter` that

- **health-checks** every replica via its existing `/healthz`
  (liveness + the SLO burn rates PR 6 put in `stats()["slo"]`): a
  replica whose checks fail `fail_threshold` times in a row goes
  `dead`; one whose worst burn rate exceeds `degrade_burn` goes
  `degraded` (kept as a last resort, never preferred); a dead replica
  that answers `readmit_threshold` consecutive checks is re-admitted.
  A torn health response (unparseable JSON — a replica dying
  mid-write) counts as a failure, never as health.
- **retries idempotent requests** — every `/v1/*` inference POST is a
  pure function of its body — on a dead/degraded replica: connection
  failures and 503s (a replica draining/closing) retry on the next
  replica with capped exponential backoff, bounded by BOTH a
  per-request `max_retries` and a fleet-wide retry BUDGET
  (`floor + ratio·accepted`), so a brown-out cannot amplify traffic
  into a retry storm.
- **sheds load on top of the existing 429/504 contract** instead of
  queue-collapsing: a replica's 429 (queue_full) and 504 (deadline)
  are typed backpressure and pass through UNRETRIED — re-driving them
  would amplify exactly the load that caused them — and when no
  admitting replica exists the router answers its own typed 503
  (`no_capacity`, Retry-After) rather than queueing.
- **drains and re-admits replicas** without dropping accepted work:
  `drain` only stops NEW routing — requests already forwarded finish
  on the replica (its own drain semantics guarantee that), and
  `admit` restores routing.
- **shares a content-addressed result cache** (`serve/cache.py` keyed
  exactly like the replica-local caches) so a failover does not re-pay
  warm embeddings: a repeat of any previously answered request is
  served router-side even while the replica that computed it is dead.

Fleet-scope causal tracing (ISSUE 18): the router's request id IS the
fleet `trace_id` — it rides every replica attempt as an `X-PBT-Trace`
header (the replica's RequestTrace joins it), every retry/hedge emits
a sibling `fleet_attempt` record (attempt index, target replica,
outcome, backoff wait), and `FleetCollector` merges router + replica
event files into one seq-ordered stream `pbt diagnose --fleet`
reconstructs causal chains from. `fleet_metrics()` (GET
/fleet/metrics) is the aggregation plane: replica registries scraped
via /metrics.json and merged — counters summed, gauges labeled by
replica, quantile windows merged over raw values.

Exactly-once sealing: every request the router ACCEPTS terminates in
exactly one `FLEET_REQUEST_OUTCOMES` outcome (ok / cache_hit /
retried_ok / shed / failed), counted in `fleet_requests_total{outcome=}`
and emitted as a `fleet_request` event — the fleet-level funnel the
drill harness (`tools/fleet_drill.py`) audits against the per-replica
PR 6 trace funnel. `FaultInjector` hooks let the drill kill replicas
mid-request, inject latency spikes, and tear health responses without
patching router internals.

Stdlib-only transport (http.server + urllib), same as serve/http.py.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

from proteinbert_tpu.serve.cache import EmbeddingCache, content_key

logger = logging.getLogger(__name__)

# Inference routes the router forwards (and caches). All are idempotent:
# the response is a pure function of the request body.
ROUTE_KINDS = {
    "/v1/embed": "embed",
    "/v1/predict_go": "predict_go",
    "/v1/predict_residues": "predict_residues",
    "/v1/predict_task": "predict_task",
    "/v1/neighbors": "neighbors",
}

# 503 = the replica is closing/draining (ServerClosedError) — the work
# never started, safe and right to retry elsewhere. 429/504 are typed
# backpressure/QoS rejections: retrying would amplify the very load
# that caused them (shed, pass through).
RETRYABLE_STATUSES = frozenset({503})
SHED_STATUSES = frozenset({429, 504})

_MAX_BODY = 32 * 1024 * 1024


class FaultInjector:
    """Drill/test hooks threaded through the router: per-replica
    injected forward latency, simulated connection kills, and torn
    health responses. Thread-safe; every default is 'no fault', so a
    router built without one pays a None check only."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latency: Dict[str, float] = {}  # guarded-by: _lock
        self._dead: set = set()               # guarded-by: _lock
        self._torn_health: set = set()        # guarded-by: _lock
        self._health_latency: Dict[str, float] = {}  # guarded-by: _lock

    def set_latency(self, replica: str, seconds: float) -> None:
        with self._lock:
            if seconds > 0:
                self._latency[replica] = float(seconds)
            else:
                self._latency.pop(replica, None)

    def kill(self, replica: str) -> None:
        """Simulate a dead replica: every forward to it raises a
        connection error at the router (the real-kill path — actually
        closing the replica's socket — is the drill's job)."""
        with self._lock:
            self._dead.add(replica)

    def revive(self, replica: str) -> None:
        with self._lock:
            self._dead.discard(replica)

    def tear_health(self, replica: str, torn: bool = True) -> None:
        with self._lock:
            if torn:
                self._torn_health.add(replica)
            else:
                self._torn_health.discard(replica)

    def set_health_latency(self, replica: str, seconds: float) -> None:
        """Grey failure: the replica answers health checks, just
        SLOWLY. Distinct from tear_health (hard failure) — the drill
        uses this to prove the health loop never starves behind one
        slow replica (fleet_health_scrape_seconds bounds it)."""
        with self._lock:
            if seconds > 0:
                self._health_latency[replica] = float(seconds)
            else:
                self._health_latency.pop(replica, None)

    def forward_latency(self, replica: str) -> float:
        with self._lock:
            return self._latency.get(replica, 0.0)

    def health_latency(self, replica: str) -> float:
        with self._lock:
            return self._health_latency.get(replica, 0.0)

    def is_dead(self, replica: str) -> bool:
        with self._lock:
            return replica in self._dead

    def health_is_torn(self, replica: str) -> bool:
        with self._lock:
            return replica in self._torn_health


class Replica:
    """Router-side view of one serve replica (state guarded by the
    router's lock)."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.state = "up"  # optimistic until the first health tick
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        self.inflight = 0
        self.burn_rate = 0.0
        self.requests_total = 0
        self.failures_total = 0
        self.last_health: Optional[Dict[str, Any]] = None
        # Trunk arm identity (ISSUE 20), learned from /healthz: the
        # resident trunk's fingerprint + quant mode, and the candidate
        # fingerprint while a rollout is shadowing. None until the
        # first successful health check.
        self.trunk_fp: Optional[str] = None
        self.quant: Optional[str] = None
        self.candidate_fp: Optional[str] = None

    def routable(self) -> bool:
        return self.state in ("up", "degraded")

    def status(self) -> Dict[str, Any]:
        return {"name": self.name, "url": self.url, "state": self.state,
                "inflight": self.inflight,
                "consecutive_failures": self.consecutive_failures,
                "burn_rate": round(self.burn_rate, 4),
                "requests_total": self.requests_total,
                "failures_total": self.failures_total,
                "trunk_fingerprint": self.trunk_fp,
                "quant": self.quant,
                "candidate_fingerprint": self.candidate_fp}


class FleetRouter:
    """Route, retry, shed, heal — see module docstring."""

    def __init__(
        self,
        replicas: Sequence,
        *,
        telemetry=None,
        clock=time.monotonic,
        sleep=time.sleep,
        health_interval_s: float = 0.5,
        health_timeout_s: float = 2.0,
        fail_threshold: int = 3,
        readmit_threshold: int = 2,
        degrade_burn: float = 1.0,
        max_retries: int = 2,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        retry_budget_ratio: float = 0.2,
        retry_budget_floor: int = 8,
        request_timeout_s: float = 30.0,
        cache_size: int = 2048,
        fault_injector: Optional[FaultInjector] = None,
        index_digest: Optional[str] = None,
        propagate_trace: bool = True,
        flight_paths: Optional[Dict[str, str]] = None,
    ):
        from proteinbert_tpu.obs import as_telemetry

        self.replicas: List[Replica] = []
        for i, r in enumerate(replicas):
            if isinstance(r, Replica):
                self.replicas.append(r)
            elif isinstance(r, str):
                self.replicas.append(Replica(f"r{i}", r))
            else:
                name, url = r
                self.replicas.append(Replica(name, url))
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        self.tele = as_telemetry(telemetry)
        self.clock = clock
        self._sleep = sleep
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.fail_threshold = fail_threshold
        self.readmit_threshold = readmit_threshold
        self.degrade_burn = degrade_burn
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.retry_budget_ratio = retry_budget_ratio
        self.retry_budget_floor = retry_budget_floor
        self.request_timeout_s = request_timeout_s
        self.injector = fault_injector
        # Identity of the neighbor index the replicas serve (ISSUE 17,
        # `index_identity(index_dir)`): it scopes cached /v1/neighbors
        # responses to the exact index contents. Without it the router
        # cannot prove two replicas hold the same index, so neighbor
        # responses are simply not cached (forwarding still works).
        self.index_digest = index_digest
        # Fleet-scope causal tracing (ISSUE 18): when on, the router's
        # request id travels to every replica attempt as X-PBT-Trace
        # (the replica's RequestTrace joins it) and each attempt emits
        # a fleet_attempt sibling record. Off is the bench A/B arm —
        # the overhead gate measures on-vs-off.
        self.propagate_trace = bool(propagate_trace)
        # Where each replica's flight-recorder ring will dump on crash
        # (replica name -> flight_<pid>.json path): surfaced on the
        # fleet_replica death event so a dead replica's last-N trail is
        # findable before its tmpdir vanishes.
        self.flight_paths = dict(flight_paths or {})
        self.cache = EmbeddingCache(cache_size, metrics=self.tele.metrics)
        self._lock = threading.Lock()
        self._rr = itertools.count()
        # Exactly-once seal accounting: accepted == sealed at drain is
        # the router-level invariant the drill asserts. Declared in the
        # `pbt check` lock-discipline registry: any unlocked touch of
        # these fails the tier-1 gate (docs/analysis.md).
        self.accepted_total = 0           # guarded-by: _lock
        self.sealed_total = 0             # guarded-by: _lock
        self.retries_spent = 0            # guarded-by: _lock
        self.outcomes: Dict[str, int] = {}  # guarded-by: _lock
        metrics = self.tele.metrics
        from proteinbert_tpu.obs.events import FLEET_REQUEST_OUTCOMES

        self._outcome_c = {o: metrics.counter("fleet_requests_total",
                                              outcome=o)
                           for o in FLEET_REQUEST_OUTCOMES}
        self._retry_c = metrics.counter("fleet_retries_total")
        self._shed_c = metrics.counter("fleet_shed_total")
        self._up_g = {r.name: metrics.gauge("fleet_replica_up",
                                            replica=r.name)
                      for r in self.replicas}
        self._admitting_g = metrics.gauge("fleet_replicas_admitting")
        # 1.0 while routable replicas disagree on the resident trunk
        # fingerprint (mid-flip, or a flip that half-landed) — the
        # health sweep flags that fleet as degraded (ISSUE 20).
        self._fp_mixed_g = metrics.gauge("fleet_fingerprint_mixed")
        self._fleet_state = "coherent"    # guarded-by: _lock
        # Health-loop scrape latency per replica (the previously
        # unmeasured half of the health plane): one slow replica shows
        # up HERE, and the drill asserts the loop still visits every
        # other replica each sweep (no starvation).
        self._scrape_h = {r.name: metrics.histogram(
            "fleet_health_scrape_seconds", replica=r.name)
            for r in self.replicas}
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._ended = False               # guarded-by: _lock
        self._req_ids = itertools.count(1)
        self._id_prefix = f"f{os.getpid():x}-"
        # Optional FleetCollector (attach_collector): the merged-stream
        # funnel the CLI/drill drain into one fleet JSONL.
        self.collector = None
        # Optional RolloutController (attach_rollout): owns shadow
        # mirroring + gated promotion; the router only calls its
        # mirror() hook from the sealed 200 path (ISSUE 20).
        self.rollout = None

    def attach_collector(self, collector: "FleetCollector") -> None:
        """Wire the event funnel: the router itself never tails files
        mid-flight (the merge is post-hoc), it just owns the handle so
        drain-time callers find router + replicas in one place."""
        self.collector = collector

    def attach_rollout(self, controller) -> None:
        """Wire a rollout controller's shadow mirror into the routed
        path. The hook fires AFTER the live response is sealed, so a
        slow/broken candidate can never hold a user request hostage."""
        self.rollout = controller

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "FleetRouter":
        self.tele.emit("fleet_start", pid=os.getpid(), config={
            "replicas": {r.name: r.url for r in self.replicas},
            "health_interval_s": self.health_interval_s,
            "fail_threshold": self.fail_threshold,
            "readmit_threshold": self.readmit_threshold,
            "degrade_burn": self.degrade_burn,
            "max_retries": self.max_retries,
            "backoff_base_s": self.backoff_base_s,
            "backoff_cap_s": self.backoff_cap_s,
            "retry_budget_ratio": self.retry_budget_ratio,
            "retry_budget_floor": self.retry_budget_floor,
            "cache_size": self.cache.capacity,
        })
        self._gauge_admitting()
        if self.health_interval_s > 0:
            self._health_thread = threading.Thread(
                target=self._health_loop, name="fleet-health", daemon=True)
            self._health_thread.start()
        return self

    def drain(self) -> None:
        """Stop the health loop and emit the terminal record. The HTTP
        front end is the caller's to shut down (CLI/drill order:
        httpd.shutdown() → router.drain()), so no new request can race
        the terminal stats."""
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
        # The ended latch is lock-guarded (a concurrent double-drain
        # must emit exactly one terminal record); the emit itself runs
        # OUTSIDE the lock because stats() re-acquires it.
        with self._lock:
            if self._ended:
                return
            self._ended = True
        self.tele.emit("fleet_end", outcome="drained",
                       stats=self.stats())

    # -------------------------------------------------------- health loop

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self.health_tick()
            except Exception:  # noqa: BLE001 — a dead health loop is a
                # SILENT router regression (states frozen, crashed
                # replicas kept in rotation); log and keep ticking.
                logger.exception("fleet health tick failed; retrying "
                                 "next interval")

    def health_tick(self) -> None:
        """One health sweep over all replicas (public so tests and the
        drill can drive it deterministically without the thread)."""
        for rep in self.replicas:
            t0 = self.clock()
            payload = self._fetch_health(rep)
            self._scrape_h[rep.name].observe(max(0.0, self.clock() - t0))
            self._apply_health(rep, payload)
        self._gauge_admitting()
        self._sweep_fingerprints()

    def _sweep_fingerprints(self) -> None:
        """Flag a mixed-fingerprint fleet (ISSUE 20): routable replicas
        disagreeing on the resident trunk means a flip half-landed (or
        is mid-flight). Emits `rollout_fleet` on every state change and
        keeps the fleet_fingerprint_mixed gauge current."""
        with self._lock:
            fps = {r.trunk_fp for r in self.replicas
                   if r.routable() and r.trunk_fp}
            state = "degraded" if len(fps) > 1 else "coherent"
            changed = state != self._fleet_state
            self._fleet_state = state
        self._fp_mixed_g.set(1.0 if state == "degraded" else 0.0)
        if changed:
            self.tele.emit("rollout_fleet", state=state,
                           fingerprints=len(fps))

    def fingerprint_status(self) -> Dict[str, Any]:
        """Per-replica trunk identity + the fleet coherence verdict."""
        with self._lock:
            return {
                "fleet_state": self._fleet_state,
                "fingerprints": {r.name: r.trunk_fp for r in self.replicas},
                "candidates": {r.name: r.candidate_fp
                               for r in self.replicas
                               if r.candidate_fp},
            }

    def _fetch_health(self, rep: Replica) -> Optional[Dict[str, Any]]:
        if self.injector is not None:
            # Grey failure first: a slow replica is slow whether or not
            # it eventually answers/tears — the scrape histogram must
            # see the stall either way.
            lat = self.injector.health_latency(rep.name)
            if lat > 0:
                self._sleep(lat)
            if (self.injector.health_is_torn(rep.name)
                    or self.injector.is_dead(rep.name)):
                return None
        try:
            with urllib.request.urlopen(rep.url + "/healthz",
                                        timeout=self.health_timeout_s) as r:
                raw = r.read()
            payload = json.loads(raw)
            if not isinstance(payload, dict) or not payload.get("ok"):
                return None  # torn/garbled body == failed check
            return payload
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def _apply_health(self, rep: Replica,
                      payload: Optional[Dict[str, Any]]) -> None:
        with self._lock:
            if payload is None:
                rep.consecutive_successes = 0
                rep.consecutive_failures += 1
                if (rep.state not in ("dead", "draining")
                        and rep.consecutive_failures >= self.fail_threshold):
                    self._transition(rep, "dead",
                                     reason="health_checks_failed")
                return
            rep.last_health = payload
            rep.consecutive_failures = 0
            rep.consecutive_successes += 1
            # Trunk arm identity (ISSUE 20): /healthz carries the
            # resident fingerprint + quant at top level and the
            # candidate fingerprint under stats.rollout. Same defensive
            # posture as the burn parse — absent fields leave the
            # previous value (an old-version replica is not "mixed",
            # it is unknown).
            fp = payload.get("trunk_fingerprint")
            if isinstance(fp, str) and fp:
                rep.trunk_fp = fp
            quant = payload.get("quant")
            if isinstance(quant, str) and quant:
                rep.quant = quant
            rollout = (payload.get("stats") or {}).get("rollout")
            if isinstance(rollout, dict):
                cand = rollout.get("candidate_fingerprint")
                rep.candidate_fp = cand if isinstance(cand, str) else None
            # Defensive parse: a replica of a different version (or a
            # garbled body that still parsed) must degrade to "no burn
            # signal", never crash the health pass.
            slo = ((payload.get("stats") or {}).get("slo")) or {}
            burns = [0.0]
            if isinstance(slo, dict):
                for s in slo.values():
                    if isinstance(s, dict):
                        try:
                            burns.append(float(s.get("burn_rate") or 0.0))
                        except (TypeError, ValueError):
                            pass
            rep.burn_rate = max(burns)
            if rep.state == "draining":
                return  # operator intent wins over health
            if rep.state == "dead":
                if rep.consecutive_successes >= self.readmit_threshold:
                    self._transition(rep, "up", event_state="admitted",
                                     reason="health_recovered")
                return
            if rep.burn_rate > self.degrade_burn:
                if rep.state != "degraded":
                    self._transition(rep, "degraded", reason="slo_burn")
            elif rep.state != "up":
                self._transition(rep, "up", reason="burn_recovered")

    def _transition(self, rep: Replica, state: str,
                    event_state: Optional[str] = None,
                    reason: str = "") -> None:
        """Lock held by callers. `event_state` lets a dead→up recovery
        report as 'admitted' while storing the routable 'up'."""
        rep.state = state
        self._up_g[rep.name].set(1.0 if rep.routable() else 0.0)
        fields = {}
        if state == "dead":
            # Point the death record at the replica's flight-recorder
            # dump (when the fleet knows where it will land): the
            # last-N forensic ring outlives the replica even though its
            # tmpdir will not (pbt fleet copies it out).
            flight = self.flight_paths.get(rep.name)
            if flight is not None:
                fields["flight"] = flight
        self.tele.emit("fleet_replica", replica=rep.name,
                       state=event_state or state, url=rep.url,
                       reason=reason,
                       consecutive_failures=rep.consecutive_failures,
                       burn_rate=round(rep.burn_rate, 4), **fields)

    def _gauge_admitting(self) -> None:
        with self._lock:
            n = sum(1 for r in self.replicas if r.routable())
        self._admitting_g.set(n)

    # ------------------------------------------------------ control plane

    def _by_name(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica {name!r}; have "
                       f"{[r.name for r in self.replicas]}")

    def drain_replica(self, name: str) -> None:
        """Operator drain: stop routing NEW work to `name`. Requests
        already forwarded keep running on the replica (its own drain
        semantics seal them); nothing accepted is dropped."""
        rep = self._by_name(name)
        with self._lock:
            if rep.state != "draining":
                self._transition(rep, "draining", reason="operator")
        self._gauge_admitting()

    def admit_replica(self, name: str) -> None:
        """Re-admit a drained (or dead) replica into the rotation."""
        rep = self._by_name(name)
        with self._lock:
            rep.consecutive_failures = 0
            rep.consecutive_successes = 0
            self._transition(rep, "up", event_state="admitted",
                             reason="operator")
        self._gauge_admitting()

    def replica_status(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.status() for r in self.replicas]

    # ----------------------------------------------------------- routing

    def _pick(self, exclude: set) -> Optional[Replica]:
        """Least-inflight admitting replica, 'up' preferred over
        'degraded', round-robin tiebreak. None when nothing routable."""
        with self._lock:
            for states in (("up",), ("degraded",)):
                cands = [r for r in self.replicas
                         if r.state in states and r.name not in exclude]
                if cands:
                    k = next(self._rr)
                    cands.sort(key=lambda r: (r.inflight, r.name))
                    low = cands[0].inflight
                    lowest = [r for r in cands if r.inflight == low]
                    return lowest[k % len(lowest)]
        return None

    def _has_candidate(self, tried: set) -> bool:
        with self._lock:
            return any(r.routable() and r.name not in tried
                       for r in self.replicas)

    def _try_spend_retry(self, retries_so_far: int) -> bool:
        """Atomically check the per-request cap AND the fleet-wide
        budget and, when allowed, spend one retry. Check-and-spend is
        ONE lock hold: a separate check would let K concurrent
        brown-out requests all observe headroom and collectively
        overshoot the budget by K-1 — during exactly the storm the
        budget exists to bound."""
        if retries_so_far >= self.max_retries:
            return False
        with self._lock:
            allowed = (self.retry_budget_floor
                       + self.retry_budget_ratio * self.accepted_total)
            if self.retries_spent >= allowed:
                return False
            self.retries_spent += 1
        self._retry_c.inc()
        return True

    def _forward(self, rep: Replica, path: str, raw_body: bytes,
                 trace_id: Optional[str] = None) -> Tuple[int, bytes]:
        """One upstream POST; raises ConnectionError-family on transport
        failure, returns (status, body) otherwise (4xx/5xx included).
        `trace_id` rides as X-PBT-Trace — the propagated fleet context
        the replica's RequestTrace joins (ISSUE 18)."""
        if self.injector is not None:
            lat = self.injector.forward_latency(rep.name)
            if lat > 0:
                self._sleep(lat)
            if self.injector.is_dead(rep.name):
                raise ConnectionError(
                    f"injected kill of replica {rep.name}")
        headers = {"Content-Type": "application/json"}
        if trace_id is not None:
            headers["X-PBT-Trace"] = trace_id
        req = urllib.request.Request(
            rep.url + path, data=raw_body, headers=headers, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            # Non-2xx WITH a response: the replica answered — a typed
            # rejection or error, not a transport failure.
            return e.code, e.read()
        # urllib.error.URLError / OSError / timeout propagate: transport
        # failure, the retry path's business.

    def shadow_forward(self, name: str, path: str, raw_body: bytes,
                       trace_id: str) -> Tuple[int, bytes]:
        """Mirror one request to `name`'s CANDIDATE arm (ISSUE 20).

        Deliberately outside every live-path ledger: no inflight or
        health bookkeeping, no cache read/write, no retry, no seal —
        a shadow is an observation, not a request. The X-PBT-Shadow
        header routes it through Server.shadow_submit on the replica;
        the trace_id ties the shadow record to its live sibling.
        Transport failures return (0, b"") rather than raising: the
        controller scores them as shadow failures."""
        rep = self._by_name(name)
        if self.injector is not None:
            if self.injector.is_dead(rep.name):
                return 0, b""
        headers = {"Content-Type": "application/json",
                   "X-PBT-Shadow": "1",
                   "X-PBT-Trace": trace_id}
        req = urllib.request.Request(
            rep.url + path, data=raw_body, headers=headers, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except (urllib.error.URLError, OSError):
            return 0, b""

    def control_forward(self, name: str, path: str,
                        body: Optional[Dict[str, Any]] = None
                        ) -> Tuple[int, bytes]:
        """POST one rollout control verb (/v1/rollout/*) to a replica.
        Control traffic never retries and never touches the request
        ledgers — a failed flip must surface, not be papered over.
        Transport failure returns (0, b"")."""
        rep = self._by_name(name)
        if self.injector is not None and self.injector.is_dead(rep.name):
            return 0, b""
        raw = json.dumps(body or {}).encode()
        req = urllib.request.Request(
            rep.url + path, data=raw,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s * 2) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except (urllib.error.URLError, OSError):
            return 0, b""

    def _cache_key(self, kind: str, body: Any) -> Optional[str]:
        """Content address of one inference request (None = uncacheable
        body — the replica will 400 it). Excludes deadline_ms (QoS, not
        content); includes head_id and top_k (they change the result)."""
        if self.cache.capacity == 0 or not isinstance(body, dict):
            return None
        seq = body.get("seq")
        if not isinstance(seq, str) or not seq:
            return None
        ann = body.get("annotations")
        scope = kind
        if kind == "neighbors":
            # Cacheable only when the router knows WHICH index the
            # fleet serves — the digest + requested k scope the key
            # exactly like the replica-side cache does.
            if self.index_digest is None:
                return None
            scope += f":{self.index_digest[:16]}"
            if body.get("k") is not None:
                scope += f":k{body['k']}"
        if body.get("head_id") is not None:
            scope += f":{body['head_id']}"
        if body.get("top_k") is not None:
            scope += f":top{body['top_k']}"
        try:
            return content_key(scope, seq, ann)
        except (TypeError, ValueError):
            return None

    def route(self, path: str, raw_body: bytes) -> Tuple[int, bytes,
                                                         Dict[str, str]]:
        """Route one accepted inference request; returns (status, body,
        extra headers). EVERY call seals exactly once — the try/finally
        backstop turns an unexpected escape into a sealed `failed`
        rather than a lost request."""
        kind = ROUTE_KINDS[path]
        rid = f"{self._id_prefix}{next(self._req_ids):x}"
        with self._lock:
            self.accepted_total += 1
        sealed = {"done": False}

        def seal(outcome: str, status: int, replica: Optional[str],
                 retries: int) -> None:
            if sealed["done"]:
                return
            sealed["done"] = True
            with self._lock:
                self.sealed_total += 1
                self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            self._outcome_c[outcome].inc()
            if outcome == "shed":
                self._shed_c.inc()
            # trace_id IS the router's request id (one id names the
            # request end-to-end); replica_id mirrors `replica` under
            # the uniform join key every fleet event carries.
            self.tele.emit("fleet_request", outcome=outcome, path=path,
                           replica=replica, retries=retries,
                           status=status, request_id=rid,
                           trace_id=rid, replica_id=replica)

        try:
            return self._route_sealed(kind, path, raw_body, rid, seal)
        finally:
            if not sealed["done"]:  # belt-and-braces: never lose one
                seal("failed", 500, None, 0)

    def _route_sealed(self, kind: str, path: str, raw_body: bytes,
                      rid: str, seal) -> Tuple[int, bytes, Dict[str, str]]:
        # X-PBT-Request-Id answers with the FLEET id on every response
        # the router composes itself (shed/cache_hit/failed) — the same
        # id the replica's propagated trace answers with on a forwarded
        # 200, so clients read one header regardless of who replied.
        headers = {"X-PBT-Fleet-Request-Id": rid,
                   "X-PBT-Request-Id": rid}

        def attempt(replica: str, outcome: str,
                    status: Optional[int] = None,
                    backoff_s: Optional[float] = None) -> None:
            """One sibling attempt record under this trace (ISSUE 18):
            `retries` at emit time IS the 0-based attempt index, so
            attempts on record == retries spent + 1 — the accounting
            invariant tests/test_fleet_trace.py audits."""
            if not self.propagate_trace:
                return
            fields: Dict[str, Any] = {}
            if status is not None:
                fields["status"] = status
            if backoff_s is not None:
                fields["backoff_s"] = round(backoff_s, 6)
            self.tele.emit("fleet_attempt", trace_id=rid,
                           attempt=retries, replica=replica,
                           outcome=outcome, path=path, **fields)
        try:
            body = json.loads(raw_body) if raw_body else None
        except ValueError:
            body = None
        key = self._cache_key(kind, body)
        if key is not None:
            hit = self.cache.get(key)
            if hit is not None:
                seal("cache_hit", 200, None, 0)
                headers["X-PBT-Fleet-Cache"] = "hit"
                return 200, hit, headers

        retries = 0
        tried: set = set()
        transport_failed_any = False
        while True:
            rep = self._pick(tried)
            if rep is None:
                if transport_failed_any:
                    # A candidate existed when the retry was spent but
                    # died before the pick: this is an outage reaching
                    # the client, not load shedding — label it so.
                    seal("failed", 502, None, retries)
                    return 502, json.dumps(
                        {"error": "every admitting replica became "
                                  "unreachable",
                         "type": "replica_unavailable"}).encode(), headers
                # Nothing admitting at arrival: typed shed, never a hang.
                seal("shed", 503, None, retries)
                headers["Retry-After"] = "1"
                return 503, json.dumps(
                    {"error": "no admitting replica in the fleet",
                     "type": "no_capacity"}).encode(), headers
            with self._lock:
                rep.inflight += 1
                rep.requests_total += 1
            try:
                status, resp = self._forward(
                    rep, path, raw_body,
                    rid if self.propagate_trace else None)
                transport_failure = False
            except (urllib.error.URLError, OSError) as e:
                status, resp = 502, json.dumps(
                    {"error": f"replica {rep.name} unreachable: {e}",
                     "type": "replica_unavailable"}).encode()
                transport_failure = True
            finally:
                with self._lock:
                    rep.inflight -= 1

            if transport_failure or status in RETRYABLE_STATUSES:
                transport_failed_any = transport_failed_any \
                    or transport_failure
                with self._lock:
                    rep.failures_total += 1
                    if transport_failure:
                        rep.consecutive_failures += 1
                        if (rep.state not in ("dead", "draining")
                                and rep.consecutive_failures
                                >= self.fail_threshold):
                            self._transition(rep, "dead",
                                             reason="forward_failed")
                tried.add(rep.name)
                failed_how = ("transport_failed" if transport_failure
                              else "retryable")
                # Spend a retry only when an untried candidate exists —
                # a token burned on a guaranteed no_capacity would
                # deplete the budget without buying a dispatch.
                if self._has_candidate(tried) \
                        and self._try_spend_retry(retries):
                    wait = min(self.backoff_cap_s,
                               self.backoff_base_s * (2 ** retries))
                    # The backoff rides on the attempt a retry FOLLOWED:
                    # the causal chain reads attempt(failed, waited W) →
                    # attempt(next replica).
                    attempt(rep.name, failed_how, status=status,
                            backoff_s=wait)
                    self._sleep(wait)
                    retries += 1
                    continue
                attempt(rep.name, failed_how, status=status)
                # Budget/cap/candidates exhausted: a replica 503 stays
                # a typed shed; a transport failure surfaces as 502.
                outcome = "failed" if transport_failure else "shed"
                seal(outcome, status, rep.name, retries)
                return status, resp, headers

            headers["X-PBT-Fleet-Replica"] = rep.name
            if status in SHED_STATUSES:
                attempt(rep.name, "shed", status=status)
                seal("shed", status, rep.name, retries)
                return status, resp, headers
            if status == 200:
                if key is not None:
                    self.cache.put(key, resp)
                attempt(rep.name, "ok", status=status)
                seal("retried_ok" if retries else "ok", status,
                     rep.name, retries)
                # Shadow mirror (ISSUE 20): AFTER the live request is
                # sealed — mirroring can never delay or fail a user
                # response. The controller samples/enqueues; a full
                # queue drops the mirror, never blocks here.
                ctl = self.rollout
                if ctl is not None:
                    try:
                        ctl.mirror(path, raw_body, rid, resp, rep.name)
                    except Exception:  # noqa: BLE001 — shadow plane
                        # must never break the live path.
                        logger.exception("rollout mirror hook failed")
                return status, resp, headers
            # Replica answered with a non-retryable error (400/404/500):
            # pass it through, sealed as failed.
            attempt(rep.name, "failed", status=status)
            seal("failed", status, rep.name, retries)
            return status, resp, headers

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "accepted": self.accepted_total,
                "sealed": self.sealed_total,
                "outcomes": dict(self.outcomes),
                "retries_spent": self.retries_spent,
                "fleet_state": self._fleet_state,
                "replicas": [r.status() for r in self.replicas],
            }
        out["cache"] = self.cache.stats()
        ctl = self.rollout
        if ctl is not None:
            out["rollout"] = ctl.status()
        return out

    # ------------------------------------------------------ rollout verbs

    def start_rollout(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        """Build a RolloutController from an operator spec and start it
        (shadow phase). One rollout at a time: a live controller in a
        non-terminal state refuses a second start."""
        from proteinbert_tpu.rollout import RolloutController
        ctl = self.rollout
        if ctl is not None and not ctl.terminal():
            raise RuntimeError(
                f"a rollout is already {ctl.state}; abort it first "
                "(pbt rollout abort)")
        ctl = RolloutController(self, telemetry=self.tele, **spec)
        self.attach_rollout(ctl)
        return ctl.start()

    def rollout_status(self) -> Dict[str, Any]:
        ctl = self.rollout
        out = {"rollout": None if ctl is None else ctl.status()}
        out.update(self.fingerprint_status())
        return out

    def promote_rollout(self) -> Dict[str, Any]:
        ctl = self.rollout
        if ctl is None:
            raise RuntimeError("no rollout in progress")
        return ctl.promote()

    def abort_rollout(self) -> Dict[str, Any]:
        ctl = self.rollout
        if ctl is None:
            raise RuntimeError("no rollout in progress")
        return ctl.abort()

    # -------------------------------------------------- aggregation plane

    def fleet_metrics(self) -> Dict[str, Any]:
        """Scrape every replica's /metrics.json and merge into ONE
        fleet view (the MLPerf aggregate-then-gate shape — ROADMAP 4's
        autoscaler signal): counters SUMMED across replicas, gauges
        kept per-replica (a mean of queue depths hides the hot one) by
        re-labeling each key with `replica=`, histograms merged
        (count/sum added, min/max combined), and quantile windows
        merged over the CONCATENATED raw values — a fleet p99 is not
        any function of per-replica p99s. Unreachable replicas are
        skipped and listed under `missing` (a partial fleet view that
        says so beats a hang)."""
        from proteinbert_tpu.obs.metrics import nearest_rank

        counters: Dict[str, float] = {}
        gauges: Dict[str, Any] = {}
        histograms: Dict[str, Dict[str, Any]] = {}
        window_vals: Dict[str, List[float]] = {}
        scraped: List[str] = []
        missing: List[str] = []
        for rep in self.replicas:
            try:
                with urllib.request.urlopen(
                        rep.url + "/metrics.json",
                        timeout=self.health_timeout_s) as r:
                    payload = json.loads(r.read())
                if not isinstance(payload, dict):
                    raise ValueError("non-dict metrics payload")
            except (urllib.error.URLError, OSError, ValueError):
                missing.append(rep.name)
                continue
            scraped.append(rep.name)
            snap = payload.get("snapshot") or {}
            for k, v in (snap.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    counters[k] = counters.get(k, 0.0) + float(v)
            for k, v in (snap.get("gauges") or {}).items():
                gauges[_label_replica(k, rep.name)] = v
            for k, h in (snap.get("histograms") or {}).items():
                if not isinstance(h, dict) or not h.get("count"):
                    continue
                m = histograms.get(k)
                if m is None:
                    m = histograms[k] = {"count": 0, "sum": 0.0,
                                         "min": None, "max": None}
                m["count"] += int(h["count"])
                m["sum"] += float(h.get("sum") or 0.0)
                for side, pick in (("min", min), ("max", max)):
                    v = h.get(side)
                    if isinstance(v, (int, float)):
                        m[side] = (float(v) if m[side] is None
                                   else pick(m[side], float(v)))
            for k, vals in (payload.get("windows") or {}).items():
                if isinstance(vals, list):
                    window_vals.setdefault(k, []).extend(
                        float(v) for v in vals
                        if isinstance(v, (int, float)))
        windows = {}
        for k, vals in window_vals.items():
            vals.sort()
            windows[k] = {
                "n": len(vals),
                "p50_s": (round(nearest_rank(vals, 0.50), 6)
                          if vals else None),
                "p99_s": (round(nearest_rank(vals, 0.99), 6)
                          if vals else None),
                "mean_s": (round(sum(vals) / len(vals), 6)
                           if vals else None),
            }
        return {"replicas": scraped, "missing": missing,
                "counters": counters, "gauges": gauges,
                "histograms": histograms, "windows": windows}


def _label_replica(key: str, replica: str) -> str:
    """Append `replica="..."` to a registry key (`name` or
    `name{l="v"}`) — how fleet_metrics keeps per-replica gauges apart
    without inventing a second key syntax."""
    name, sep, rest = key.partition("{")
    if not sep:
        return f'{name}{{replica="{replica}"}}'
    inner = rest[:-1]
    inner = f'{inner},replica="{replica}"' if inner \
        else f'replica="{replica}"'
    return f"{name}{{{inner}}}"


class FleetCollector:
    """The fleet event funnel (ISSUE 18): tails the router's and every
    replica's event JSONL into ONE merged, seq-ordered stream keyed by
    `trace_id` — the stream `pbt diagnose --fleet` reconstructs causal
    chains from.

    Reuses `obs/events.read_events` in tolerant mode, so a replica
    SIGKILLed mid-write contributes everything up to its torn final
    line (the drill's core scenario). Each record is stamped with its
    source (`src`, `src_seq`) and a `replica_id` default (existing
    stamps win — a fleet_request's serving-replica id is never
    overwritten), then the merged stream is re-sequenced 0..N-1 so it
    passes the same monotonic-seq validation as any single stream.
    Ordering is (t, src, src_seq): wall-clock first, with per-source
    emission order as the tiebreak inside one timestamp."""

    def __init__(self, sources: Optional[Dict[str, str]] = None):
        # name -> JSONL path; insertion order is irrelevant (merge
        # sorts), uniqueness is not: one stream per name.
        self.sources: Dict[str, str] = dict(sources or {})

    def add_source(self, name: str, path: str) -> None:
        self.sources[name] = path

    def collect(self) -> List[Dict[str, Any]]:
        from proteinbert_tpu.obs.events import read_events

        merged: List[Dict[str, Any]] = []
        for name in sorted(self.sources):
            path = self.sources[name]
            if not os.path.exists(path):
                continue
            for rec in read_events(path, strict=False):
                rec = dict(rec)
                rec["src"] = name
                rec["src_seq"] = rec.get("seq", 0)
                rec.setdefault("replica_id", name)
                merged.append(rec)
        merged.sort(key=lambda r: (r.get("t", 0.0), r["src"],
                                   r["src_seq"]))
        for i, rec in enumerate(merged):
            rec["seq"] = i
        return merged

    @staticmethod
    def seal_violations(records) -> Dict[str, int]:
        """trace_id -> fleet_request seal count, for every trace sealed
        != exactly once in the merged stream (empty == the exactly-once
        invariant holds fleet-wide)."""
        counts: Dict[str, int] = {}
        for rec in records:
            if rec.get("event") == "fleet_request":
                tid = rec.get("trace_id") or rec.get("request_id")
                if tid:
                    counts[tid] = counts.get(tid, 0) + 1
        return {tid: n for tid, n in counts.items() if n != 1}

    def write(self, out_path: str) -> int:
        """Collect + write the merged stream as JSONL; returns the
        record count. Plain sequential write (no append contention —
        the merge is a post-hoc pass, not a live tail)."""
        records = self.collect()
        with open(out_path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return len(records)


# ------------------------------------------------------------ HTTP front

def make_fleet_handler(router: FleetRouter):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Socket read timeout: bounds how long an idle keep-alive
        # connection holds its handler thread, which in turn bounds how
        # long server_close() blocks joining handlers at drain (the
        # front runs NON-daemon threads so in-flight requests seal
        # BEFORE fleet_end — make_fleet_http_server).
        timeout = 10

        def log_message(self, fmt, *args):  # telemetry covers it
            pass

        def _reply(self, status: int, payload,
                   extra: Optional[Dict[str, str]] = None) -> None:
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode())
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            if not 0 <= length <= _MAX_BODY:
                raise ValueError(f"bad Content-Length {length}")
            return self.rfile.read(length)

        def do_GET(self):
            if self.path == "/healthz":
                reps = router.replica_status()
                ok = any(r["state"] in ("up", "degraded") for r in reps)
                self._reply(200 if ok else 503,
                            {"ok": ok, "role": "fleet-router",
                             "fleet_state": router.fingerprint_status()[
                                 "fleet_state"],
                             "replicas": reps})
            elif self.path == "/fleet/status":
                self._reply(200, {"replicas": router.replica_status(),
                                  "stats": router.stats()})
            elif self.path == "/rollout/status":
                self._reply(200, router.rollout_status())
            elif self.path == "/fleet/metrics":
                # The fleet-wide merged registry view (counters summed,
                # gauges per-replica, windows percentile-merged) — the
                # autoscaler/SLO-burn scrape point (ISSUE 18).
                self._reply(200, router.fleet_metrics())
            elif self.path == "/metrics":
                text = router.tele.metrics.prometheus_text() \
                    if getattr(router.tele, "metrics", None) is not None \
                    else ""
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no such route {self.path}"})

        def _control(self, raw: bytes, drain: bool) -> None:
            try:
                body = json.loads(raw)
            except ValueError as e:
                self._reply(400, {"error": f"bad request: {e}",
                                  "type": "bad_request"})
                return
            name = body.get("replica") if isinstance(body, dict) else None
            if not isinstance(name, str):
                self._reply(400, {"error": "'replica' must be a string",
                                  "type": "bad_request"})
                return
            try:
                if drain:
                    router.drain_replica(name)
                else:
                    router.admit_replica(name)
            except KeyError as e:
                self._reply(404, {"error": str(e),
                                  "type": "unknown_replica"})
            else:
                self._reply(200, {"ok": True,
                                  "replicas": router.replica_status()})

        def _rollout_control(self, verb: str, raw: bytes) -> None:
            """POST /rollout/start|promote|abort (ISSUE 20). Typed
            errors: a spec problem is a 400, an illegal phase (double
            start, promote with no rollout) a 409, anything else 500."""
            try:
                if verb == "start":
                    spec = json.loads(raw) if raw else {}
                    if not isinstance(spec, dict):
                        raise ValueError("rollout spec must be an object")
                    out = router.start_rollout(spec)
                elif verb == "promote":
                    out = router.promote_rollout()
                elif verb == "abort":
                    out = router.abort_rollout()
                else:
                    self._reply(404, {"error": f"no such rollout verb "
                                               f"{verb!r}"})
                    return
            except (TypeError, ValueError, KeyError) as e:
                self._reply(400, {"error": str(e), "type": "bad_request"})
            except RuntimeError as e:
                self._reply(409, {"error": str(e),
                                  "type": "rollout_conflict"})
            except Exception as e:  # noqa: BLE001 — typed 500 beats a
                # torn keep-alive connection.
                self._reply(500, {"error": f"{type(e).__name__}: {e}",
                                  "type": "internal"})
            else:
                self._reply(200, {"ok": True, **out})

        def do_POST(self):
            # Read the body BEFORE any reply: this handler speaks
            # HTTP/1.1 keep-alive, and answering an unknown route or a
            # bad request while the body bytes sit unread on the socket
            # desyncs the connection — the NEXT request would be parsed
            # starting at the leftover bytes.
            try:
                raw = self._read_body()
            except ValueError as e:
                self.close_connection = True  # body left unread
                self._reply(400, {"error": f"bad request: {e}",
                                  "type": "bad_request"})
                return
            if self.path == "/fleet/drain":
                self._control(raw, drain=True)
                return
            if self.path == "/fleet/admit":
                self._control(raw, drain=False)
                return
            if self.path.startswith("/rollout/"):
                self._rollout_control(self.path[len("/rollout/"):], raw)
                return
            if self.path not in ROUTE_KINDS:
                self._reply(404, {"error": f"no such route {self.path}"})
                return
            status, body, extra = router.route(self.path, raw)
            self._reply(status, body, extra)

    return Handler


def make_fleet_http_server(router: FleetRouter, host: str = "127.0.0.1",
                           port: int = 0) -> ThreadingHTTPServer:
    """Bind the router's HTTP front (port 0 = ephemeral; read
    `.server_address[1]`); callers run `.serve_forever()` and own
    shutdown ordering (httpd.shutdown() + server_close() BEFORE
    router.drain()).

    Handler threads are NON-daemon with block_on_close: server_close()
    joins every in-flight handler, so a request mid-route() seals
    BEFORE router.drain() emits the terminal fleet_end stats — daemon
    threads (the single-replica endpoint's choice) would let a seal
    land after the terminal record and make accepted != sealed flicker
    at shutdown. The Handler's socket timeout bounds the join: an idle
    keep-alive connection releases its thread within `timeout` s."""
    httpd = ThreadingHTTPServer((host, port), make_fleet_handler(router))
    httpd.daemon_threads = False
    httpd.block_on_close = True
    return httpd
