"""Continuous micro-batching scheduler.

One daemon thread drains the request queue under a two-knob policy —
the standard continuous-batching contract:

- **max_batch**: a (kind, bucket) group that reaches `max_batch`
  queued rows dispatches immediately (throughput bound);
- **max_wait_s**: otherwise, a group dispatches when its OLDEST member
  has waited `max_wait_s` (latency bound — p99 queueing delay is
  bounded by max_wait + one batch time, the property bench.py --serve
  measures).

Requests group by (kind, bucket_len): only same-kind, same-bucket rows
can share a compiled executable. Within a group, FIFO order is
preserved end-to-end — the batch a request rides in is a deterministic
function of arrival order and the clock, which is why every formation
test in tests/test_serve.py runs single-threaded against `poll(now=)`
with a fake clock instead of sleeping.

A dispatch failure (OOM, a bug in a jitted fn) fails THAT batch's
futures and keeps the scheduler alive for later batches; the error is
also recorded as a `note` on the telemetry stream.

Observability (ISSUE 6): every request's QUEUE WAIT (push → popped for
dispatch) lands in the `serve_queue_wait_seconds` histogram plus a
local mirror for `Server.stats()` — cheap, and recorded even when
request tracing is sampled out. Requests that carry a `RequestTrace`
additionally get per-stage clock marks (ingest / pop / execute) and a
terminal `complete_observer` callback (outcome ∈ ok/error/expired) the
Server uses to seal the trace, emit the `serve_request` event, and
feed the SLO evaluator. All marks use the injected clock.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from proteinbert_tpu.obs.metrics import Histogram
from proteinbert_tpu.serve.errors import DeadlineExceededError
from proteinbert_tpu.serve.queue import Request, RequestQueue

logger = logging.getLogger(__name__)

GroupKey = Tuple[str, int]  # (kind, bucket_len)


class _ReadyBatch:
    """An already-resolved result wearing the in-flight handle shape —
    the fallback for stub dispatchers with no `run_*_async` entry
    (their blocking call already happened on the scheduler thread)."""

    def __init__(self, result, timings):
        self._result = (result, timings)

    def finalize(self):
        return self._result


class _FailedBatch:
    """A submit-time dispatch failure carried through the in-flight
    window so the ONE finalize path handles every batch outcome; the
    original traceback rides on the exception object."""

    def __init__(self, exc: BaseException):
        self._exc = exc

    def finalize(self):
        raise self._exc


class MicroBatchScheduler:
    def __init__(
        self,
        queue: RequestQueue,
        dispatcher,
        finalize: Callable[[Request, object], None],
        max_batch: int = 8,
        max_wait_s: float = 0.01,
        clock=time.monotonic,
        partition_heads: bool = False,
        telemetry=None,
        latency_observer: Optional[Callable[[float], None]] = None,
        expire_observer: Optional[Callable[[Request], None]] = None,
        complete_observer: Optional[
            Callable[[Request, str, float, Optional[BaseException],
                      Optional[dict]], None]] = None,
        replica_id: Optional[str] = None,
        pipeline_depth: int = 2,
    ):
        from proteinbert_tpu.obs import as_telemetry

        self.queue = queue
        self.dispatcher = dispatcher
        self.finalize = finalize
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.clock = clock
        # Fleet identity (ISSUE 18): stamped onto every serve_batch
        # event so the fleet's merged stream can attribute batches to
        # replicas without inferring identity from ports/paths.
        self.replica_id = replica_id
        self._replica_fields = (
            {"replica_id": replica_id} if replica_id else {})
        # Multi-tenant grouping (ISSUE 8): requests group by
        # (kind, bucket) ONLY — all predict_task requests share the
        # kind "predict_task", so one micro-batch MIXES heads through
        # the shared trunk executable. partition_heads=True appends the
        # head id to the group key instead (per-head batches) — the
        # baseline `bench.py --heads` measures the mixed win against.
        self.partition_heads = bool(partition_heads)
        self.tele = as_telemetry(telemetry)
        self._latency = latency_observer or (lambda s: None)
        # Called per deadline-expired request (scheduler thread): the
        # Server counts these under rejected{reason=deadline} so
        # /metrics, stats(), and --max-requests accounting see them.
        self._on_expire = expire_observer or (lambda req: None)
        # Called once per terminal request the scheduler decides
        # (outcome "ok" | "error" | "expired", with the clock's now, the
        # error if any, and batch context) — the trace/SLO hook.
        self._on_complete = complete_observer or (
            lambda req, outcome, now, err, ctx: None)
        # Guarded by _pending_lock (declared below): normally
        # scheduler-thread-private, but fail_pending (abort with a
        # still-live thread stuck in a long jitted call) and
        # pending_rows (bench quiesce poll) touch it from other
        # threads.
        self._pending: "collections.OrderedDict[GroupKey, collections.deque]" \
            = collections.OrderedDict()      # guarded-by: _pending_lock
        self._pending_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        # Dispatch counters: written by the scheduler thread, read by
        # Server.stats() from client/HTTP threads — lock-guarded (the
        # unlocked stats()-path read ISSUE 15's lock rule was built to
        # catch) and read through stats_counts().
        self.batches_total = 0               # guarded-by: _pending_lock
        self.rows_total = 0                  # guarded-by: _pending_lock
        self.expired_total = 0               # guarded-by: _pending_lock
        self._occupancy_g = self.tele.metrics.gauge("serve_batch_occupancy")
        self._rows_h = self.tele.metrics.histogram("serve_batch_rows")
        self._batch_h = self.tele.metrics.histogram("serve_batch_seconds")
        self._qwait_h = self.tele.metrics.histogram(
            "serve_queue_wait_seconds")
        # Live mirror for Server.stats(): the registry instrument is a
        # shared no-op under NULL telemetry, but stats() must report
        # real queue-wait numbers regardless (same rule as the
        # Server's rejection-count mirrors).
        self.queue_wait = Histogram()
        # Timed dispatch (run_timed: prep/device split + pad scan) costs
        # an O(rows*L) token scan per batch, so it runs only when
        # something consumes the result: a sampled rider in the batch,
        # or this flag (the Server sets it when SLO attribution needs
        # pad_fraction for every request).
        self.time_batches = False
        # Pipelined dispatch (ISSUE 19): a bounded window of submitted-
        # but-unfinalized batches between SUBMIT (the jitted call is
        # enqueued — JAX dispatch is async, so the device starts
        # immediately) and FINALIZE (blocking host fetch, per-request
        # fan-out, future sealing). With a completer thread (started by
        # start() when pipeline_depth > 1) the scheduler forms and
        # submits batch N+1 while batch N computes; without one
        # (single-threaded poll() tests, or depth 1) every submit
        # finalizes synchronously — exactly the pre-pipeline behavior,
        # which is what keeps fake-clock formation tests deterministic.
        # The Condition below doubles as the mutex for every field
        # annotated with it ('lock' in its name keeps the
        # lock-discipline rule reading `with self._inflight_lock:`
        # regions as held).
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight_lock = threading.Condition()
        self._inflight = collections.deque()  # guarded-by: _inflight_lock
        self.inflight_max = 0                 # guarded-by: _inflight_lock
        self.finalize_seconds_total = 0.0     # guarded-by: _inflight_lock
        self.overlap_seconds_total = 0.0      # guarded-by: _inflight_lock
        self._completer: Optional[threading.Thread] = None
        self._completer_stop = threading.Event()
        self._inflight_g = self.tele.metrics.gauge("serve_inflight_batches")
        self._overlap_g = self.tele.metrics.gauge("serve_overlap_ratio")
        self._finalize_h = self.tele.metrics.histogram(
            "serve_finalize_seconds")

    # -------------------------------------------------------- formation

    def pending_rows(self) -> int:
        with self._pending_lock:
            return sum(len(d) for d in self._pending.values())

    def stats_counts(self) -> Tuple[int, int, int]:
        """(batches_total, rows_total, expired_total) under the lock —
        the one coherent read for Server.stats()/bench, so a stats
        scrape mid-dispatch can never see a half-updated pair."""
        with self._pending_lock:
            return (self.batches_total, self.rows_total,
                    self.expired_total)

    def _ingest(self, now: float) -> None:
        items = self.queue.pop_all()
        if not items:
            return
        with self._pending_lock:
            for req in items:
                if req.trace is not None:
                    req.trace.mark_ingested(now)
                kind = req.kind
                if self.partition_heads and req.head is not None:
                    kind = f"{kind}:{req.head.head_id}"
                key = (kind, req.bucket_len)
                group = self._pending.get(key)
                if group is None:
                    group = self._pending[key] = collections.deque()
                group.append(req)

    def _observe_wait(self, req: Request, now: float) -> None:
        wait = max(0.0, now - req.enqueued_at)
        self._qwait_h.observe(wait)
        self.queue_wait.observe(wait)

    def _expire_pending(self, now: float) -> None:
        expired: List[Request] = []
        with self._pending_lock:
            for key in list(self._pending):
                group = self._pending[key]
                keep = collections.deque()
                for req in group:
                    if req.deadline is not None and now >= req.deadline:
                        expired.append(req)
                    else:
                        keep.append(req)
                if keep:
                    self._pending[key] = keep
                else:
                    del self._pending[key]
        if not expired:
            return
        # Depth at rejection time: what is still ahead of a new arrival
        # (queued + formed-but-undispatched), AFTER dropping the
        # expired rows themselves.
        depth = self.pending_rows() + len(self.queue)
        with self._pending_lock:
            self.expired_total += len(expired)
        for req in expired:
            self._observe_wait(req, now)
            req.future.set_exception(DeadlineExceededError(
                f"deadline passed after "
                f"{now - req.enqueued_at:.3f}s waiting for a batch"))
            self.tele.emit("serve_reject", reason="deadline",
                           kind=req.kind, queue_depth=depth)
            self._on_expire(req)
            self._on_complete(req, "expired", now, None, None)

    def _select_group(self, now: float) -> Optional[GroupKey]:
        """Dispatch decision: a full group first (fullest wins, ties to
        the oldest head), else the group whose head has waited past
        max_wait_s (oldest head wins), else — when draining — the
        oldest head outright."""
        with self._pending_lock:
            full = [(len(g), -g[0].enqueued_at, k)
                    for k, g in self._pending.items()
                    if len(g) >= self.max_batch]
            if full:
                return max(full)[2]
            overdue = [(g[0].enqueued_at, k)
                       for k, g in self._pending.items()
                       if now - g[0].enqueued_at >= self.max_wait_s]
            if overdue:
                return min(overdue)[1]
            if self.queue.closed and self._pending:
                return min((g[0].enqueued_at, k)
                           for k, g in self._pending.items())[1]
            return None

    # --------------------------------------------------------- dispatch

    def _dispatch(self, key: GroupKey, now: float) -> int:
        # Under partition_heads the group key's kind carries a
        # ":<head_id>" suffix; the dispatcher and events see the base
        # kind (per-row heads travel on the requests themselves).
        kind, bucket_len = key[0].split(":", 1)[0], key[1]
        with self._pending_lock:
            group = self._pending.get(key)
            if not group:  # raced an abort's fail_pending
                return 0
            batch: List[Request] = [group.popleft()
                                    for _ in range(min(self.max_batch,
                                                       len(group)))]
            if not group:
                del self._pending[key]
        cls = self.dispatcher.batch_class(len(batch))
        tracing = False
        timed = self.time_batches
        for req in batch:
            self._observe_wait(req, now)
            if req.trace is not None:
                tracing = True
                if req.trace.sampled:
                    timed = True
                req.trace.mark_popped(now)
        tokens = np.stack([r.tokens for r in batch])
        num_ann = self.dispatcher.cfg.model.num_annotations
        annotations = np.stack([
            r.annotations if r.annotations is not None
            else np.zeros(num_ann, np.float32)
            for r in batch])
        ctx = {"rows": len(batch), "batch_class": cls,
               "bucket_len": bucket_len}
        # predict_task rows carry their own LoadedHead (resolved at
        # admission): pass them through so the dispatcher runs the
        # shared trunk once and each head's cheap tail per group.
        heads = ([r.head for r in batch]
                 if batch[0].head is not None else None)
        extra = {"heads": heads} if heads is not None else {}
        if heads is not None:
            ctx["heads"] = sorted({h.head_id for h in heads})
        self._wait_for_slot()
        t0 = time.perf_counter()
        run0 = self.clock()
        try:
            # run_timed_async (BucketDispatcher) returns an in-flight
            # handle as soon as the jitted call is enqueued — the
            # blocking host fetch moves to _finalize_batch. run_timed /
            # plain run() keep stub dispatchers working (their result
            # rides the window in a _ReadyBatch). Untimed batches still
            # go through timed=False rather than run(): the quantized
            # arm stamps its `quant`/`quant_parity_max` event fields
            # unconditionally (absent-means-fp32 must hold on untimed
            # batches too), and timed=False skips only the O(rows*L)
            # pad scan.
            run_async = getattr(self.dispatcher, "run_timed_async", None)
            run_timed = getattr(self.dispatcher, "run_timed", None)
            if run_async is not None:
                handle = run_async(kind, tokens, annotations,
                                   timed=bool(tracing and timed), **extra)
            elif run_timed is not None:
                result, timings = run_timed(kind, tokens, annotations,
                                            timed=bool(tracing and timed),
                                            **extra)
                handle = _ReadyBatch(result, timings)
            else:
                handle = _ReadyBatch(
                    self.dispatcher.run(kind, tokens, annotations,
                                        **extra), {})
        except Exception as e:  # submit failed; finalize path fails it
            handle = _FailedBatch(e)
        self._enqueue_inflight({
            "mode": "bucketed", "batch": batch, "handle": handle,
            "ctx": ctx, "kind": kind, "bucket_len": bucket_len,
            "cls": cls, "run0": run0, "t0": t0})
        return len(batch)

    def _finalize_batch(self, entry: Dict) -> None:
        """Resolve one in-flight micro-batch: blocking host fetch,
        per-request finalize/fan-out, trace marks, counters, the
        serve_batch event and the terminal complete callback. Runs on
        the completer thread when one is live, else inline right after
        submit. Trace stages: `execute` is submit → fetch-complete
        (run0 → run1) and `finalize` is fetch-complete → sealed, so
        per-request stages still tile [submit, done]."""
        batch: List[Request] = entry["batch"]
        ctx, run0 = entry["ctx"], entry["run0"]
        kind, bucket_len, cls = (entry["kind"], entry["bucket_len"],
                                 entry["cls"])
        tf0 = time.perf_counter()
        try:
            result, timings = entry["handle"].finalize()
        except Exception as e:  # fail THIS batch, keep serving
            logger.exception("batch dispatch failed (%s, L=%d, rows=%d)",
                             kind, bucket_len, len(batch))
            self.tele.emit("note", source="serve", error=str(e),
                           kind=kind, bucket_len=bucket_len)
            fail_t = self.clock()
            for req in batch:
                if req.trace is not None:
                    req.trace.mark_run(run0, fail_t)
                    req.trace.mark_batch(
                        bucket_len, cls, len(batch),
                        pad_fraction=ctx.get("pad_fraction"))
                if not req.future.done():
                    req.future.set_exception(e)
                self._on_complete(req, "error", fail_t, e, ctx)
            return
        ctx.update(timings)
        dt = time.perf_counter() - entry["t0"]
        run1 = self.clock()
        self._batch_h.observe(dt)
        self._finalize_h.observe(time.perf_counter() - tf0)
        done_t = self.clock()
        for i, req in enumerate(batch):
            if isinstance(result, dict):
                row = {k: v[i] for k, v in result.items()}
            else:
                row = result[i]
            outcome, err = "ok", None
            try:
                self.finalize(req, row)
            except Exception as e:
                outcome, err = "error", e
                if not req.future.done():
                    req.future.set_exception(e)
            self._latency(done_t - req.enqueued_at)
            if req.trace is not None:
                req.trace.mark_run(run0, run1)
                req.trace.mark_batch(
                    bucket_len, cls, len(batch),
                    pad_fraction=ctx.get("pad_fraction"),
                    prep_s=ctx.get("prep_s"),
                    device_s=ctx.get("device_s"))
            self._on_complete(req, outcome, self.clock(), err, ctx)
        with self._pending_lock:
            self.batches_total += 1
            self.rows_total += len(batch)
        self._occupancy_g.set(len(batch) / cls)
        self._rows_h.observe(len(batch))
        # Quant fields ride only when the arm set them: the documented
        # contract is absent-means-fp32, not null (obs/events.py).
        quant_fields = {k: ctx[k] for k in ("quant", "quant_parity_max")
                        if ctx.get(k) is not None}
        self.tele.emit("serve_batch", kind=kind, bucket_len=bucket_len,
                       rows=len(batch), batch_class=cls,
                       batch_seconds=round(dt, 6),
                       pad_fraction=ctx.get("pad_fraction"),
                       heads=ctx.get("heads"), **quant_fields,
                       **self._replica_fields)

    # ------------------------------------------------- in-flight window

    def _wait_for_slot(self) -> None:
        """Backpressure: block until the in-flight window has room.
        Only meaningful with a live completer (the sync path never
        leaves an entry behind); bounded wait steps keep an abort's
        stop() from wedging a full-window scheduler."""
        if self._completer is None:
            return
        with self._inflight_lock:
            while (len(self._inflight) >= self.pipeline_depth
                   and not self._stopped.is_set()):
                self._inflight_lock.wait(0.05)

    def _enqueue_inflight(self, entry: Dict) -> None:
        with self._inflight_lock:
            self._inflight.append(entry)
            n = len(self._inflight)
            if n > self.inflight_max:
                self.inflight_max = n
            self._inflight_lock.notify_all()
        self._inflight_g.set(n)
        if self._completer is None:
            self._drain_inflight()

    def _drain_inflight(self) -> None:
        """Finalize every windowed batch on the CALLING thread — the
        sync path (no completer), and the epilogue that resolves
        still-in-flight work when run_forever exits without one."""
        while True:
            with self._inflight_lock:
                if not self._inflight:
                    return
                entry = self._inflight.popleft()
                n = len(self._inflight)
                self._inflight_lock.notify_all()
            self._inflight_g.set(n)
            self._observe_finalize(entry, overlapped=n > 0)

    def _inflight_idle(self) -> bool:
        with self._inflight_lock:
            return not self._inflight

    def _observe_finalize(self, entry: Dict, overlapped: bool) -> None:
        """_finalize_batch plus the dispatch/finalize overlap
        accounting: finalize wall-seconds spent while ANOTHER batch was
        in the window are overlapped — the device had work the whole
        time the host was fetching/sealing."""
        t0 = time.perf_counter()
        self._finalize_batch(entry)
        fsec = time.perf_counter() - t0
        with self._inflight_lock:
            overlapped = overlapped or bool(self._inflight)
            self.finalize_seconds_total += fsec
            if overlapped:
                self.overlap_seconds_total += fsec
            total = self.finalize_seconds_total
            overlap = self.overlap_seconds_total
        if total > 0:
            self._overlap_g.set(round(overlap / total, 6))

    def _complete_forever(self) -> None:
        """Completer-thread loop: pop the oldest in-flight batch,
        finalize it, repeat — exiting only once run_forever has signaled
        stop AND the window is empty, so drain/abort both resolve every
        already-submitted batch exactly once."""
        while True:
            with self._inflight_lock:
                if not self._inflight:
                    if self._completer_stop.is_set():
                        return
                    self._inflight_lock.wait(0.05)
                    continue
                entry = self._inflight.popleft()
                n = len(self._inflight)
                self._inflight_lock.notify_all()
            self._inflight_g.set(n)
            self._observe_finalize(entry, overlapped=n > 0)

    def pipeline_stats(self) -> Dict:
        """One coherent read of the pipeline counters (Server.stats(),
        bench, tools/pipeline_smoke.py)."""
        with self._inflight_lock:
            total = self.finalize_seconds_total
            overlap = self.overlap_seconds_total
            return {
                "depth": self.pipeline_depth,
                "inflight_max": self.inflight_max,
                "finalize_seconds_total": round(total, 6),
                "overlap_seconds_total": round(overlap, 6),
                "overlap_ratio": (round(overlap / total, 6)
                                  if total > 0 else 0.0),
            }

    def poll(self, now: Optional[float] = None) -> int:
        """One scheduling step: ingest, expire, dispatch AT MOST one
        micro-batch. Returns rows dispatched (0 = idle). Deterministic
        given queue contents and `now` — the fake-clock test entry."""
        if now is None:
            now = self.clock()
        self._ingest(now)
        self._expire_pending(now)
        key = self._select_group(now)
        if key is None:
            return 0
        return self._dispatch(key, now)

    # ---------------------------------------------------------- threading

    def run_forever(self) -> None:
        # Idle parking: wake at least every max_wait/2 so an under-full
        # group's max-wait trigger fires on time even with no new pushes.
        park = max(min(self.max_wait_s / 2, 0.05), 0.001)
        try:
            while not self._stopped.is_set():
                if self.poll():
                    continue
                # Drained only when the QUEUE is empty too: a push can
                # land between poll()'s ingest and a close(), and
                # exiting then would strand that request's future
                # forever. After close() no new pushes are admitted, so
                # empty-at-observation is final. The in-flight window
                # must be idle too — a submitted batch's futures are
                # still unsealed until the completer resolves it.
                if (self.queue.closed and not self._pending
                        and len(self.queue) == 0
                        and self._inflight_idle()):
                    return
                self.queue.wait(timeout=park)
        finally:
            # Drain/abort epilogue: every batch already SUBMITTED is on
            # device and its futures must seal exactly once — signal
            # the completer to exit once the window empties and wait
            # for it (or resolve the window inline when there is
            # none). Only after this does join() return, so
            # Server.abort's fail_pending can never race a live
            # finalize.
            self._completer_stop.set()
            with self._inflight_lock:
                self._inflight_lock.notify_all()
            if self._completer is not None:
                self._completer.join()
            else:
                self._drain_inflight()

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        if self.pipeline_depth > 1:
            self._completer = threading.Thread(
                target=self._complete_forever,
                name="pbt-serve-completer", daemon=True)
            self._completer.start()
        self._thread = threading.Thread(target=self.run_forever,
                                        name="pbt-serve-scheduler",
                                        daemon=True)
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the drain to finish; True when the thread is gone."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self) -> None:
        """Hard stop (abort path): the loop exits at the next check;
        pending futures are the Server's to fail."""
        self._stopped.set()
        self.queue.close()

    def fail_pending(self, exc: Exception) -> List[Request]:
        """Abort path: fail every not-yet-dispatched request; returns
        the requests that were failed (the Server seals their traces).
        Safe against a scheduler thread that outlived its join timeout
        (a long jitted call): extraction holds the pending lock, so the
        thread either sees an empty map or had already popped its
        batch."""
        with self._pending_lock:
            reqs = [req for group in self._pending.values()
                    for req in group]
            self._pending.clear()
        failed = []
        for req in reqs:
            if not req.future.done():
                req.future.set_exception(exc)
                failed.append(req)
        return failed


class PackedBatchScheduler(MicroBatchScheduler):
    """RAGGED packed batch formation (ISSUE 9 tentpole).

    Replaces the (kind, bucket) grouping with PACKING: admission places
    each request into an open packed row for its KIND via the same
    first-fit residual-capacity rule as `data/packing.PackPlanner`
    (`data/packing.OnlinePacker`), at the request's bucket-quantized
    span. One dispatch runs `rows_per_batch` rows through the kind's
    single fixed-shape executable (`serve/dispatch.RaggedDispatcher`)
    — so every length mix shares one compiled shape, and a batch
    carries up to rows_per_batch x max_segments requests.

    Dispatch policy (the same two-knob contract as the bucketed
    scheduler, per KIND):

    - a kind with MORE than `rows_per_batch` open rows dispatches the
      oldest `rows_per_batch` immediately (throughput bound — the
      extra row is the open frontier, so the popped rows have already
      been topped off by first-fit);
    - otherwise a kind dispatches when the oldest request in ANY of
      its open rows has waited `max_wait_s` (latency bound), padding
      the executable's row count with empty rows;
    - when the queue is closed (drain), remaining rows flush oldest
      kind first.

    Deadlines: expiry sweeps open rows every poll (an expired request
    is REMOVED from its row — its span stays dead space, costing
    capacity, never correctness) and re-checks at dispatch pop, so an
    expired request never resolves with a result.

    Single-threaded against `poll(now=)` the formation is a
    deterministic function of arrival order and the clock, exactly
    like the bucketed scheduler (tests/test_serve_ragged.py).
    """

    def __init__(
        self,
        queue: RequestQueue,
        dispatcher,
        finalize: Callable[[Request, object], None],
        rows_per_batch: int = 4,
        max_wait_s: float = 0.01,
        clock=time.monotonic,
        max_segments: int = 8,
        telemetry=None,
        latency_observer: Optional[Callable[[float], None]] = None,
        expire_observer: Optional[Callable[[Request], None]] = None,
        complete_observer=None,
        replica_id: Optional[str] = None,
        pipeline_depth: int = 2,
    ):
        super().__init__(
            queue, dispatcher, finalize, max_batch=rows_per_batch,
            max_wait_s=max_wait_s, clock=clock, partition_heads=False,
            telemetry=telemetry, latency_observer=latency_observer,
            expire_observer=expire_observer,
            complete_observer=complete_observer, replica_id=replica_id,
            pipeline_depth=pipeline_depth)
        # Lazy import: data/packing pulls the dataset module, which the
        # pure-logic scheduler tests (stub dispatchers) need not load.
        from proteinbert_tpu.data.packing import OnlinePacker

        self._packer_cls = OnlinePacker
        self.rows_per_batch = int(rows_per_batch)
        self.max_segments = int(max_segments)
        self.seq_len = int(dispatcher.cfg.data.seq_len)
        # kind -> OnlinePacker of open rows (payloads are Requests).
        # Same contract as the base class's _pending map.
        self._packers: "collections.OrderedDict[str, object]" = \
            collections.OrderedDict()        # guarded-by: _pending_lock

    # -------------------------------------------------------- formation

    def pending_rows(self) -> int:
        """Pending REQUESTS (the quiesce-poll unit, matching the base
        class's per-request semantics — not physical packed rows)."""
        with self._pending_lock:
            return sum(p.total_items() for p in self._packers.values())

    def _ingest(self, now: float) -> None:
        items = self.queue.pop_all()
        if not items:
            return
        with self._pending_lock:
            for req in items:
                if req.trace is not None:
                    req.trace.mark_ingested(now)
                packer = self._packers.get(req.kind)
                if packer is None:
                    packer = self._packers[req.kind] = self._packer_cls(
                        self.seq_len, self.max_segments)
                packer.place(req, req.bucket_len)

    def _expire_requests(self, expired: List[Request], now: float) -> None:
        if not expired:
            return
        depth = self.pending_rows() + len(self.queue)
        with self._pending_lock:
            self.expired_total += len(expired)
        for req in expired:
            self._observe_wait(req, now)
            req.future.set_exception(DeadlineExceededError(
                f"deadline passed after "
                f"{now - req.enqueued_at:.3f}s waiting for a batch"))
            self.tele.emit("serve_reject", reason="deadline",
                           kind=req.kind, queue_depth=depth)
            self._on_expire(req)
            self._on_complete(req, "expired", now, None, None)

    def _expire_pending(self, now: float) -> None:
        expired: List[Request] = []
        with self._pending_lock:
            for kind in list(self._packers):
                packer = self._packers[kind]
                expired.extend(packer.expire(
                    lambda r: r.deadline is not None and now >= r.deadline))
                if len(packer) == 0:
                    del self._packers[kind]
        self._expire_requests(expired, now)

    def _select_group(self, now: float):
        """Dispatch decision per KIND: a kind holding MORE than
        rows_per_batch open rows first (most rows wins, ties to the
        oldest head) — the extra row is the open frontier, so the
        popped oldest rows have already been topped off by first-fit
        instead of shipping a barely-started newest row — else the kind
        whose oldest row-head request waited past max_wait_s, else —
        draining — the oldest head outright."""
        def oldest(packer) -> float:
            return min(r.enqueued_at for r in packer.row_heads())

        with self._pending_lock:
            candidates = [(k, p) for k, p in self._packers.items()
                          if len(p)]
            full = [(len(p), -oldest(p), k) for k, p in candidates
                    if len(p) > self.rows_per_batch]
            if full:
                return max(full)[2]
            overdue = [(oldest(p), k) for k, p in candidates
                       if now - oldest(p) >= self.max_wait_s]
            if overdue:
                return min(overdue)[1]
            if self.queue.closed and candidates:
                return min((oldest(p), k) for k, p in candidates)[1]
            return None

    # --------------------------------------------------------- dispatch

    def _dispatch(self, key, now: float) -> int:
        kind = key
        R, L, S = self.rows_per_batch, self.seq_len, self.max_segments
        with self._pending_lock:
            packer = self._packers.get(kind)
            if packer is None or len(packer) == 0:  # raced fail_pending
                return 0
            rows = packer.pop_rows(R)
            if len(packer) == 0:
                del self._packers[kind]
        num_ann = self.dispatcher.cfg.model.num_annotations
        tokens = np.zeros((R, L), np.int32)
        segment_ids = np.zeros((R, L), np.int32)
        annotations = np.zeros((R, S, num_ann), np.float32)
        riders: List[Tuple[Request, int, int, int, int]] = []
        expired: List[Request] = []
        tracing = False
        timed = self.time_batches
        for r, row in enumerate(rows):
            for s, (req, start, span) in enumerate(row):
                if req.deadline is not None and now >= req.deadline:
                    expired.append(req)  # raced in since the last sweep
                    continue
                tokens[r, start:start + span] = req.tokens
                segment_ids[r, start:start + span] = s + 1
                if req.annotations is not None:
                    annotations[r, s] = req.annotations
                riders.append((req, r, s, start, span))
                self._observe_wait(req, now)
                if req.trace is not None:
                    tracing = True
                    if req.trace.sampled:
                        timed = True
                    req.trace.mark_popped(now)
        self._expire_requests(expired, now)
        if not riders:
            return len(expired)
        batch = [r[0] for r in riders]
        geom = [(r, s, start, span) for (_, r, s, start, span) in riders]
        heads = ([req.head for req in batch]
                 if batch[0].head is not None else None)
        n_riders = len(riders)
        ctx = {"rows": R, "batch_class": R, "bucket_len": L,
               "segments": n_riders,
               "segments_per_row": round(n_riders / R, 4),
               "mode": "ragged"}
        if heads is not None:
            ctx["heads"] = sorted({h.head_id for h in heads})
        self._wait_for_slot()
        t0 = time.perf_counter()
        run0 = self.clock()
        try:
            # Same rule as the bucketed scheduler: untimed batches run
            # timed=False so the quantized arm's unconditionally-
            # stamped event fields still reach the ctx; the async entry
            # moves the host fetch + fan-out into _finalize_batch.
            run_async = getattr(self.dispatcher,
                                "run_packed_timed_async", None)
            if run_async is not None:
                handle = run_async(kind, tokens, segment_ids,
                                   annotations, geom, heads=heads,
                                   timed=bool(tracing and timed))
            else:
                outs, timings = self.dispatcher.run_packed_timed(
                    kind, tokens, segment_ids, annotations, geom,
                    heads=heads, timed=bool(tracing and timed))
                handle = _ReadyBatch(outs, timings)
        except Exception as e:  # submit failed; finalize path fails it
            handle = _FailedBatch(e)
        self._enqueue_inflight({
            "mode": "ragged", "riders": riders, "handle": handle,
            "ctx": ctx, "kind": kind, "n_riders": n_riders,
            "run0": run0, "t0": t0})
        return n_riders

    def _finalize_batch(self, entry: Dict) -> None:
        """Packed-batch finalize: host fetch + per-rider fan-out via
        the in-flight handle, then the same marks/counters/event shape
        the pre-pipeline dispatch produced (mode="ragged")."""
        riders = entry["riders"]
        ctx, run0 = entry["ctx"], entry["run0"]
        kind, n_riders = entry["kind"], entry["n_riders"]
        R, L, S = self.rows_per_batch, self.seq_len, self.max_segments
        tf0 = time.perf_counter()
        try:
            outs, timings = entry["handle"].finalize()
        except Exception as e:  # fail THIS batch, keep serving
            logger.exception("packed batch dispatch failed "
                             "(%s, rows=%d, segments=%d)",
                             kind, R, n_riders)
            self.tele.emit("note", source="serve", error=str(e),
                           kind=kind, bucket_len=L, mode="ragged")
            fail_t = self.clock()
            for req, _, _, _, span in riders:
                if req.trace is not None:
                    req.trace.mark_run(run0, fail_t)
                    req.trace.mark_batch(
                        span, R, R,
                        pad_fraction=ctx.get("pad_fraction"),
                        segments=n_riders,
                        segments_per_row=ctx["segments_per_row"],
                        mode="ragged")
                if not req.future.done():
                    req.future.set_exception(e)
                self._on_complete(req, "error", fail_t, e, ctx)
            return
        ctx.update(timings)
        dt = time.perf_counter() - entry["t0"]
        run1 = self.clock()
        self._batch_h.observe(dt)
        self._finalize_h.observe(time.perf_counter() - tf0)
        done_t = self.clock()
        for (req, _, _, _, span), out in zip(riders, outs):
            outcome, err = "ok", None
            try:
                self.finalize(req, out)
            except Exception as e:
                outcome, err = "error", e
                if not req.future.done():
                    req.future.set_exception(e)
            self._latency(done_t - req.enqueued_at)
            if req.trace is not None:
                req.trace.mark_run(run0, run1)
                req.trace.mark_batch(
                    span, R, R,
                    pad_fraction=ctx.get("pad_fraction"),
                    prep_s=ctx.get("prep_s"),
                    device_s=ctx.get("device_s"),
                    segments=n_riders,
                    segments_per_row=ctx["segments_per_row"],
                    mode="ragged")
            self._on_complete(req, outcome, self.clock(), err, ctx)
        with self._pending_lock:
            self.batches_total += 1
            self.rows_total += n_riders
        # Occupancy for a packed grid is token occupancy (1 - pad
        # fraction) when the batch was timed, else segment-slot fill.
        pad = ctx.get("pad_fraction")
        self._occupancy_g.set(1.0 - pad if pad is not None
                              else n_riders / (R * S))
        self._rows_h.observe(n_riders)
        quant_fields = {k: ctx[k] for k in ("quant", "quant_parity_max")
                        if ctx.get(k) is not None}
        self.tele.emit("serve_batch", kind=kind, bucket_len=L,
                       rows=R, batch_class=R,
                       batch_seconds=round(dt, 6),
                       pad_fraction=pad,
                       segments=n_riders,
                       segments_per_row=ctx["segments_per_row"],
                       mode="ragged",
                       heads=ctx.get("heads"), **quant_fields,
                       **self._replica_fields)

    def fail_pending(self, exc: Exception) -> List[Request]:
        with self._pending_lock:
            reqs: List[Request] = []
            for packer in self._packers.values():
                reqs.extend(packer.drain_items())
            self._packers.clear()
        failed = []
        for req in reqs:
            if not req.future.done():
                req.future.set_exception(exc)
                failed.append(req)
        return failed
