"""`Server` — the online-inference facade.

Ties the queue, scheduler, dispatcher, and cache together behind the
same three capabilities the offline surface exposes (inference.py):
`embed`, `predict_go`, `predict_residues` — each available as a
blocking call or a `submit()` future for in-process callers (the HTTP
layer in serve/http.py is a thin JSON shim over exactly this facade).

Request life cycle:

  submit() [client thread]                    scheduler thread
  ├─ over-length policy (reject/truncate+count)
  ├─ tokenize + bucket-route (serve/dispatch)
  ├─ cache lookup — hit returns a resolved future, nothing enqueues
  └─ queue.push (may evict the oldest    ──►  poll(): group by
     request with QueueFullError)             (kind, bucket), dispatch
                                              at max_batch/max_wait —
                                              submit only; a completer
                                              thread fetches results,
                                              finalizes per row: cache
                                              put + future.set_result

Pipelined dispatch (ISSUE 19): dispatch is split into submit (enqueue
the jitted call — JAX dispatch is async, so this returns immediately)
and finalize (blocking host fetch + per-request fan-out), joined by a
bounded in-flight window (`pipeline_depth`, default 2). Batch N+1
forms and submits while batch N computes; the completer thread drains
the window in FIFO order. Depth 1 disables the completer and restores
the serial path bit-for-bit (docs/serving.md "Pipelined dispatch").

Shutdown is two-mode, per the resilience conventions of
train/resilience.GracefulShutdown:

- `drain()` — the queue closes (new submits raise ServerClosedError),
  every queued and in-flight request completes, then the scheduler
  thread exits; emits `serve_end{outcome=drained}`.
- `abort()` — queued + pending futures fail with ServerClosedError,
  the loop stops after the in-flight batch, a `note` lands on the
  telemetry stream and the flight recorder dumps (forensics for the
  requests that were killed); emits `serve_end{outcome=aborted}`.

Request tracing + SLOs (ISSUE 6): with telemetry enabled, every
request carries a `serve/trace.RequestTrace` that collects one clock
mark per stage boundary (submit → queue → batch_form → dispatch →
execute → finalize). Traces SAMPLED at `trace_sample_rate` — plus ALL
requests that end in an error or rejection, regardless of sampling —
emit a `serve_request` event and, when the telemetry carries a span
collector, Perfetto spans on a per-request lane. Every request's
outcome also feeds the optional `obs/slo.SLOEvaluator` (declarative
latency/error-rate objectives; burn rates on `/metrics`,
`stats()["slo"]`, and `pbt diagnose --serve`; breach → optional
on-demand device profile). With the NULL facade no trace objects are
created and every touchpoint is a None check — the served path costs
what it did before tracing existed.

Telemetry (all optional, NULL-facade free when absent —
docs/observability.md): `serve_start`/`serve_batch`/`serve_reject`/
`serve_request`/`slo_breach`/`serve_end` events; `serve_queue_depth`,
`serve_batch_occupancy`, `serve_cache_hit_rate`,
`slo_burn_rate{objective=}` gauges; the `serve_latency` quantile
window (`serve_latency_p50_s`/`p99_s` at scrape time);
`serve_requests_total{kind=}`, `serve_rejected_total{reason=}`,
`serve_truncated_total`, `serve_cache_*_total` counters;
`serve_latency_seconds`, `serve_queue_wait_seconds`,
`serve_batch_seconds`, `serve_batch_rows` histograms. With a neighbor
index attached (ISSUE 17): `neighbor_query` events (sampled) and the
`neighbors_requests_total{outcome=}` per-outcome funnel.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional

import numpy as np

from proteinbert_tpu import inference
from proteinbert_tpu.configs import PretrainConfig
from proteinbert_tpu.heads.registry import (
    HeadRegistry, LoadedHead, TrunkMismatchError, UnknownHeadError,
    trunk_fingerprint,
)
from proteinbert_tpu.serve.cache import EmbeddingCache, content_key
from proteinbert_tpu.serve.dispatch import (
    KINDS, NEIGHBORS_KIND, TASK_KIND, BucketDispatcher, RaggedDispatcher,
)
from proteinbert_tpu.serve.errors import (
    SequenceTooLongError, ServerClosedError,
)
from proteinbert_tpu.serve.queue import Request, RequestQueue
from proteinbert_tpu.serve.scheduler import (
    MicroBatchScheduler, PackedBatchScheduler,
)
from proteinbert_tpu.serve.trace import RequestTrace, stride_sampled

SERVE_MODES = ("bucketed", "ragged")

# Default result size for `/v1/neighbors` when the request carries no
# `k` — matches the recall gate's k (bench.py --neighbors, recall@10).
DEFAULT_NEIGHBORS_K = 10


class Server:
    """Online serving facade over a pretrained trunk (see module doc)."""

    def __init__(
        self,
        params,
        cfg: PretrainConfig,
        *,
        buckets=None,
        max_batch: int = 8,
        max_wait_s: float = 0.01,
        queue_depth: int = 64,
        cache_size: int = 1024,
        default_deadline_s: Optional[float] = None,
        on_long: str = "truncate",
        mesh=None,
        telemetry=None,
        clock=time.monotonic,
        warm_kinds=("embed",),
        batch_classes=None,
        trace_sample_rate: Optional[float] = 1.0,
        slos=None,
        slo_profile_dir: Optional[str] = None,
        slo_breach_cooldown_s: float = 60.0,
        registry=None,
        heads=None,
        partition_heads: bool = False,
        serve_mode: str = "bucketed",
        pack_max_segments: int = 8,
        quant: Optional[str] = None,
        quant_parity_every: Optional[int] = None,
        index=None,
        nprobe: int = 8,
        replica_id: Optional[str] = None,
        pipeline_depth: Optional[int] = None,
        candidate_loader=None,
    ):
        from proteinbert_tpu.obs import as_telemetry

        if on_long not in ("truncate", "reject"):
            raise ValueError(f"on_long must be 'truncate' or 'reject', "
                             f"got {on_long!r}")
        if serve_mode not in SERVE_MODES:
            raise ValueError(f"serve_mode must be one of {SERVE_MODES}, "
                             f"got {serve_mode!r}")
        self.cfg = cfg
        self.on_long = on_long
        self.default_deadline_s = default_deadline_s
        self.clock = clock
        self.serve_mode = serve_mode
        # Quantized executable arm (ISSUE 12): defaults ride the run
        # config (configs.ServeConfig) so `pbt serve --pretrained DIR`
        # inherits the trained-against quantization decision; explicit
        # ctor args override per server.
        serve_cfg = getattr(cfg, "serve", None)
        if quant is None:
            quant = getattr(serve_cfg, "quant", "fp32")
        if quant_parity_every is None:
            quant_parity_every = getattr(serve_cfg,
                                         "quant_parity_every", 0)
        # Pipelined dispatch (ISSUE 19): bounded in-flight window for
        # the scheduler. Depth 1 restores the serial pre-pipeline path
        # (submit + finalize inline on the scheduler thread); depth >= 2
        # starts a completer thread so batch N+1 forms while batch N
        # computes. Same config-then-ctor precedence as quant.
        if pipeline_depth is None:
            pipeline_depth = getattr(serve_cfg, "pipeline_depth", 2)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.quant = quant
        # Fleet identity (ISSUE 18): a stable name the fleet assigns at
        # spawn (`pbt serve --replica-id r0`). Stamped onto every
        # serve_request/serve_batch event so fleet joins key on an
        # explicit identity, never an inferred port.
        self.replica_id = replica_id
        self.tele = as_telemetry(telemetry)
        metrics = self.tele.metrics
        self.cache = EmbeddingCache(cache_size, metrics=metrics)
        self.queue = RequestQueue(queue_depth)
        if serve_mode == "ragged":
            # Ragged packed serving (ISSUE 9): heterogeneous requests
            # PACK into fixed-shape (max_batch, seq_len) rows at their
            # bucket-quantized spans — one warm executable per request
            # kind, outputs matching the bucketed dispatcher's within
            # the documented jitted tolerance (docs/serving.md).
            # `max_batch` means packed ROWS per executable here; a
            # batch carries up to max_batch * pack_max_segments
            # requests.
            if partition_heads:
                raise ValueError(
                    "partition_heads is a bucketed-mode baseline knob; "
                    "ragged packing mixes heads through the shared "
                    "trunk by construction")
            if batch_classes is not None:
                raise ValueError(
                    "batch_classes is meaningless in ragged mode — the "
                    "executable shape is fixed at (max_batch, seq_len)")
            self.dispatcher = RaggedDispatcher(
                params, cfg, buckets=buckets, rows_per_batch=max_batch,
                max_segments=pack_max_segments, mesh=mesh,
                metrics=metrics, quant=quant,
                quant_parity_every=quant_parity_every)
            self.scheduler = PackedBatchScheduler(
                self.queue, self.dispatcher, self._finalize,
                rows_per_batch=max_batch, max_wait_s=max_wait_s,
                clock=clock, max_segments=pack_max_segments,
                telemetry=telemetry, replica_id=replica_id,
                latency_observer=self._observe_latency,
                expire_observer=self._count_expiry,
                complete_observer=self._on_complete,
                pipeline_depth=self.pipeline_depth)
        else:
            self.dispatcher = BucketDispatcher(
                params, cfg, buckets=buckets, max_batch=max_batch,
                batch_classes=batch_classes, mesh=mesh, metrics=metrics,
                quant=quant, quant_parity_every=quant_parity_every)
            self.scheduler = MicroBatchScheduler(
                self.queue, self.dispatcher, self._finalize,
                max_batch=max_batch, max_wait_s=max_wait_s, clock=clock,
                partition_heads=partition_heads,
                telemetry=telemetry, replica_id=replica_id,
                latency_observer=self._observe_latency,
                expire_observer=self._count_expiry,
                complete_observer=self._on_complete,
                pipeline_depth=self.pipeline_depth)
        # Multi-tenant heads (ISSUE 8): an optional registry to resolve
        # head ids from, plus the resident trunk's fingerprint computed
        # LAZILY (one device→host fetch of the whole trunk — only paid
        # when a head is actually loaded). Every registry load checks
        # the artifact's trunk_fingerprint against the resident trunk:
        # a head trained against a different trunk raises the typed
        # TrunkMismatchError instead of silently serving garbage.
        if isinstance(registry, str):
            registry = HeadRegistry(registry)
        self.registry = registry
        self._trunk_fp: Optional[str] = None
        for h in (heads or ()):
            self.add_head(h)
        # Neighbor index (ISSUE 17): an optional scorer.NeighborIndex.
        # `/v1/neighbors` requests ride the embed executable (dispatch
        # normalizes the kind — zero new trunk compiles), then probe
        # this index on the scheduler thread. The index pins the trunk
        # it was built from; a fingerprint mismatch is the same class
        # of error as a mis-trunked head, and gets the same typed
        # refusal before the server can serve garbage neighbors.
        self.index = index
        self.nprobe = int(nprobe)
        if index is not None:
            if self.nprobe < 1:
                raise ValueError(f"nprobe must be >= 1, got {nprobe}")
            fp = self.trunk_fp()
            if index.model_fingerprint != fp:
                raise TrunkMismatchError(
                    "neighbor index was built from embeddings of trunk "
                    f"{index.model_fingerprint[:12]}…, but this server "
                    f"holds trunk {fp[:12]}… — rebuild it with "
                    "`pbt index` over this model's embedding store")
        # Blue-green rollout (ISSUE 20): the candidate/parked arm
        # identities this facade tracks beside the dispatcher's trees,
        # and the loader that resolves a rollout `source` string to a
        # trunk params tree (cli/main.py wires run-dir loading here;
        # drills pass a closure). shadow_total mirrors how many shadow
        # requests ran — the ONLY live counter shadow traffic touches.
        self.candidate_loader = candidate_loader
        self._candidate_fp: Optional[str] = None
        self._parked_fp: Optional[str] = None
        self.shadow_total = 0
        # The p50/p99 ring lives in the obs registry (QuantileWindow):
        # /metrics scrapes, stats(), and serve_request events all read
        # the same ring. A disabled registry (NULL telemetry) returns a
        # live unregistered window so stats() still reports real numbers.
        self.latencies = metrics.quantile_window("serve_latency")
        # Request tracing: None disables trace objects entirely; a rate
        # in [0, 1] traces every request cheaply and EMITS the sampled
        # fraction (errors/rejections always emit). NULL telemetry also
        # disables: there is nowhere to emit to.
        if trace_sample_rate is not None and not self.tele.enabled:
            trace_sample_rate = None
        self.trace_sample_rate = trace_sample_rate
        self._req_ids = itertools.count(1)
        self._id_prefix = f"{os.getpid():x}-"
        self.slo = None
        self.profile_trigger = None
        if slos:
            from proteinbert_tpu.obs.slo import ProfileTrigger, SLOEvaluator

            on_breach = None
            if slo_profile_dir:
                self.profile_trigger = ProfileTrigger(slo_profile_dir,
                                                      clock=clock)
                on_breach = self.profile_trigger
            self.slo = SLOEvaluator(
                slos, metrics=metrics, telemetry=self.tele, clock=clock,
                on_breach=on_breach,
                breach_cooldown_s=slo_breach_cooldown_s)
            stage_objs = [o.name for o in self.slo.objectives
                          if o.kind == "latency" and o.stage != "e2e"]
            if stage_objs and self.trace_sample_rate is None:
                raise ValueError(
                    f"stage-scoped slo objective(s) {stage_objs} need "
                    "request tracing for per-stage durations, but "
                    "tracing is off (telemetry disabled or "
                    "trace_sample_rate=None) — they would never "
                    "observe anything")
            # SLO violation attribution consumes pad/prep/device per
            # request, so every batch must be timed, not just sampled
            # riders' batches.
            self.scheduler.time_batches = True
        self._warm_kinds = tuple(warm_kinds)
        self._started = False
        self._ended = False
        self._depth_g = metrics.gauge("serve_queue_depth")
        self._latency_h = metrics.histogram("serve_latency_seconds")
        self._truncated_c = metrics.counter("serve_truncated_total")
        self._req_c = {k: metrics.counter("serve_requests_total", kind=k)
                       for k in KINDS + (TASK_KIND, NEIGHBORS_KIND)}
        from proteinbert_tpu.obs.events import (
            SERVE_REJECT_REASONS, SERVE_REQUEST_OUTCOMES,
        )

        self._rej_c = {r: metrics.counter("serve_rejected_total", reason=r)
                       for r in SERVE_REJECT_REASONS}
        # Per-outcome `/v1/neighbors` funnel (ISSUE 17): every neighbors
        # request lands in exactly one bucket via the _seal funnel.
        self._nbr_c = {o: metrics.counter("neighbors_requests_total",
                                          outcome=o)
                       for o in SERVE_REQUEST_OUTCOMES}
        self.completed_total = 0
        self.cache_hit_returns = 0
        # Local mirrors of the labeled counters: stats() must report
        # real numbers even under the NULL telemetry facade (whose
        # metric instruments are shared no-ops). Bumped from concurrent
        # client/HTTP threads, so the read-modify-write needs a lock.
        # (completed_total needs none: finalize has exactly one writer
        # — the completer thread when pipeline_depth > 1, else the
        # scheduler thread — never both; see scheduler._finalize_batch.)
        self._mirror_lock = threading.Lock()
        self.truncated_total = 0
        self.rejected_total = {r: 0 for r in self._rej_c}
        self.neighbors_total = {o: 0 for o in self._nbr_c}
        # Kernel fast-path COVERAGE (ISSUEs 10/13): mirror the
        # kernels/fused_block AND kernels/attention dispatch bumps —
        # both the Pallas fast path and the XLA reference path — into
        # the registry as fused_kernel_path_total{path=,reason=} /
        # attention_kernel_path_total{path=,reason=}, so /metrics,
        # stats() and `pbt diagnose --serve` show how many compiled
        # shapes run the fast path, not just the misses. (The
        # one-release deprecated fused_kernel_fallback_total mirror was
        # removed in ISSUE 12, as PR 9 scheduled.) Registered LAST —
        # after every raising statement above — so a failed
        # construction (bad SLO spec, trunk-mismatched head) cannot
        # leak a process-global observer; drain()/abort() unregister
        # them.
        from proteinbert_tpu.kernels.attention import (
            register_attention_path_observer,
        )
        from proteinbert_tpu.kernels.fused_block import (
            register_path_observer,
        )
        from proteinbert_tpu.kernels.one_pass import (
            register_onepass_path_observer,
        )

        self._path_c: Dict[Any, Any] = {}

        # Bind metrics + the counter dict via default args, NOT self: a
        # Server abandoned without drain()/abort() must leak only this
        # small dict through the process-global observer lists, never
        # the params/dispatcher it would pin via a bound method.
        def _mirror(name: str, path: str, reason: str,
                    _metrics=metrics, _c=self._path_c) -> None:
            c = _c.get((name, path, reason))
            if c is None:
                c = _c[(name, path, reason)] = _metrics.counter(
                    name, path=path, reason=reason)
            c.inc()

        def _mirror_path(path: str, reason: str) -> None:
            _mirror("fused_kernel_path_total", path, reason)

        def _mirror_attn_path(path: str, reason: str) -> None:
            _mirror("attention_kernel_path_total", path, reason)

        def _mirror_onepass_path(path: str, reason: str) -> None:
            _mirror("onepass_kernel_path_total", path, reason)

        self._path_cb = _mirror_path
        self._attn_path_cb = _mirror_attn_path
        self._onepass_path_cb = _mirror_onepass_path
        register_path_observer(self._path_cb)
        register_attention_path_observer(self._attn_path_cb)
        register_onepass_path_observer(self._onepass_path_cb)

    def _bump(self, mirror: str, reason: Optional[str] = None) -> None:
        with self._mirror_lock:
            if reason is None:
                setattr(self, mirror, getattr(self, mirror) + 1)
            else:
                self.rejected_total[reason] += 1

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "Server":
        """Warm the compiled shape classes and start the scheduler."""
        if self._started:
            raise RuntimeError("server already started")
        warmed = self.dispatcher.warmup(self._warm_kinds)
        if self.index is not None:
            # Warm the one lookup executable every single-request probe
            # uses — (Q=1, nprobe, k=DEFAULT_NEIGHBORS_K) — so the first
            # /v1/neighbors request pays lookup time, not compile time.
            self.index.lookup_rows(
                np.zeros((1, self.index.dim), np.float32),
                k=DEFAULT_NEIGHBORS_K, nprobe=self.nprobe)
        self.tele.emit("serve_start", pid=os.getpid(), config={
            "serve_mode": self.serve_mode,
            "buckets": list(self.dispatcher.buckets),
            "batch_classes": list(self.dispatcher.batch_classes),
            "pack_max_segments": getattr(self.dispatcher,
                                         "max_segments", None),
            "max_batch": self.scheduler.max_batch,
            "max_wait_s": self.scheduler.max_wait_s,
            "queue_depth": self.queue.max_depth,
            "cache_size": self.cache.capacity,
            "on_long": self.on_long,
            "warmed_executables": warmed,
            "trace_sample_rate": self.trace_sample_rate,
            "slos": ([o.name for o in self.slo.objectives]
                     if self.slo else []),
            "mesh": (dict(self.dispatcher.mesh.shape)
                     if self.dispatcher.mesh is not None else None),
            "heads": sorted(self.dispatcher.heads),
            "warmup": self.dispatcher.warmup_report,
            "quant": self.quant,
            "quant_report": self.dispatcher.quant_report or None,
            "pipeline_depth": self.pipeline_depth,
            "neighbor_index": (self.index.digest
                               if self.index is not None else None),
            "nprobe": self.nprobe if self.index is not None else None,
            "replica_id": self.replica_id,
        })
        self.scheduler.start()
        self._started = True
        return self

    # -------------------------------------------------- multi-tenant heads

    def trunk_fp(self) -> str:
        """The resident trunk's fingerprint (computed once); the value
        every registry load is checked against."""
        if self._trunk_fp is None:
            self._trunk_fp = trunk_fingerprint(self.dispatcher.params)
        return self._trunk_fp

    def add_head(self, head) -> str:
        """Hot-add a head to a (possibly live) server: a head id
        resolved through the registry (trunk-compatibility ENFORCED —
        TrunkMismatchError if it was trained against a different
        trunk), or an already-LoadedHead (trusted: in-process producers
        like tests/bench build these directly). On a live server the
        head's tail is warmed incrementally; the trunk is never
        recompiled. Returns the head id."""
        if isinstance(head, str):
            if self.registry is None:
                raise UnknownHeadError(
                    f"cannot resolve head id {head!r}: this server has "
                    "no registry (pass registry= or a LoadedHead)")
            head = self.registry.load(head, trunk_fp=self.trunk_fp())
        assert isinstance(head, LoadedHead)
        warm_s = self.dispatcher.add_head(
            head, warm=getattr(self, "_started", False))
        self.tele.emit("note", source="serve", kind="head_added",
                       head_id=head.head_id, name=head.name,
                       task=head.task.kind,
                       incremental_warmup_s=round(warm_s, 6))
        return head.head_id

    def remove_head(self, head_id: str) -> None:
        """Hot-remove a head: new submits for it get the typed
        UnknownHeadError (HTTP 404) immediately; already-admitted
        requests carry their own head reference and complete normally
        (drain semantics — tests/test_heads.py exercises this under
        concurrent traffic)."""
        head = self.dispatcher.remove_head(head_id)
        self.tele.emit("note", source="serve", kind="head_removed",
                       head_id=head.head_id, name=head.name)

    def list_heads(self):
        """[{head_id, name, kind, num_outputs}] of the currently
        servable heads."""
        return self.dispatcher.list_heads()

    # ------------------------------------------------ blue-green rollout

    def load_candidate(self, params=None, source: Optional[str] = None,
                       hbm_budget_bytes: Optional[int] = None
                       ) -> Dict[str, Any]:
        """Load a candidate trunk beside the resident one and warm-boot
        it through the compile cache (ISSUE 20). Pass the params tree
        directly or a `source` string for the server's
        `candidate_loader` to resolve. HBM-priced with the typed
        `CandidateUnfitError` refusal when both arms don't fit (see
        dispatch.load_candidate). Returns the candidate report
        {fingerprint, warm_seconds, weight bytes...}."""
        if (params is None) == (source is None):
            raise ValueError("pass exactly one of params= / source=")
        if params is None:
            if self.candidate_loader is None:
                raise ValueError(
                    "this server has no candidate_loader — pass the "
                    "params tree directly, or construct the server "
                    "with candidate_loader=")
            params = self.candidate_loader(source)
        # Fingerprint BEFORE the dispatcher takes ownership (it may
        # host-park or re-place the tree under quant/mesh serving).
        fp = trunk_fingerprint(params)
        report = self.dispatcher.load_candidate(
            params, hbm_budget_bytes=hbm_budget_bytes)
        warm_s = self.dispatcher.warm_candidate()
        self._candidate_fp = fp
        report = dict(report, fingerprint=fp,
                      warm_seconds=round(warm_s, 6))
        self.tele.emit("rollout_state", state="candidate_loaded",
                       fingerprint=fp, source=source or "params")
        return report

    def unload_candidate(self) -> bool:
        """Drop the candidate arm (abort / gate refusal); returns
        whether one was loaded. The resident arm is untouched."""
        had = self.dispatcher.unload_candidate()
        if had:
            fp = self._candidate_fp
            self._candidate_fp = None
            self.tele.emit("rollout_state", state="candidate_unloaded",
                           fingerprint=fp or "")
        return had

    def flip(self) -> Dict[str, Any]:
        """Atomic promotion: the candidate becomes the resident trunk
        (dispatch.flip — zero dropped or torn in-flight requests), the
        outgoing trunk parks on host for instant rollback, and the
        result cache FLUSHES: results the old trunk computed must not
        outlive it, or a cached pre-flip embedding would answer a
        post-flip query with the wrong model."""
        old_fp = self.trunk_fp()
        seconds = self.dispatcher.flip()
        self._parked_fp = old_fp
        self._trunk_fp = self._candidate_fp
        self._candidate_fp = None
        dropped = self.cache.clear()
        self.tele.emit("rollout_flip",
                       replica=self.replica_id or "local", phase="flip",
                       seconds=round(seconds, 6),
                       fingerprint=self._trunk_fp or "", ok=True)
        return {"seconds": round(seconds, 6),
                "fingerprint": self._trunk_fp,
                "parked_fingerprint": self._parked_fp,
                "cache_dropped": dropped}

    def rollback_trunk(self) -> Dict[str, Any]:
        """Instant rollback to the parked trunk — bit-identical
        resident numerics (dispatch.rollback); the demoted trunk moves
        to the candidate slot. Flushes the cache for the same reason
        flip() does."""
        demoted_fp = self.trunk_fp()
        seconds = self.dispatcher.rollback()
        self._trunk_fp = self._parked_fp
        self._candidate_fp = demoted_fp
        self._parked_fp = None
        dropped = self.cache.clear()
        self.tele.emit("rollout_flip",
                       replica=self.replica_id or "local",
                       phase="rollback", seconds=round(seconds, 6),
                       fingerprint=self._trunk_fp or "", ok=True)
        return {"seconds": round(seconds, 6),
                "fingerprint": self._trunk_fp,
                "cache_dropped": dropped}

    def shadow_submit(self, kind: str, seq: str, annotations=None,
                      head_id: Optional[str] = None,
                      top_k: Optional[int] = None):
        """Run ONE request through the CANDIDATE arm, synchronously and
        invisibly (ISSUE 20): same tokenization/bucketing/result
        shaping as the live path, but it never touches the queue, the
        result cache, the SLO evaluator, or any live counter — the only
        bookkeeping is the `shadow_total` mirror. Raises
        NoCandidateError when no candidate is loaded. `neighbors` is
        refused: the ANN index pins the RESIDENT trunk's embedding
        space, so a candidate-arm probe would score garbage."""
        if kind == NEIGHBORS_KIND:
            raise ValueError(
                "neighbors cannot shadow: the ANN index pins the "
                "resident trunk's embedding space")
        if kind not in KINDS and kind != TASK_KIND:
            raise ValueError(f"unknown request kind {kind!r}; have "
                             f"{KINDS + (TASK_KIND,)}")
        if (kind == TASK_KIND) != (head_id is not None):
            raise ValueError(
                f"head_id is required for kind {TASK_KIND!r} and "
                "invalid for every other kind")
        if not seq:
            raise ValueError("empty sequence")
        head = (self.dispatcher.get_head(head_id)
                if kind == TASK_KIND else None)
        if annotations is not None:
            annotations = inference.check_annotations(
                np.asarray(annotations, np.float32)[None], 1, self.cfg)[0]
        bucket_len = self.dispatcher.bucket_len(len(seq))
        tokens = inference._tokenize_masked(
            [seq], self.cfg.data.seq_len, on_overflow="count")[0]
        if self.serve_mode == "ragged":
            # One real rider in row 0 of an otherwise-dummy packed
            # grid; the other rows compute but fan out to nobody.
            from proteinbert_tpu.data.vocab import PAD_ID

            tok, seg, ann, _ = self.dispatcher._dummy_packed()
            tok[0, :] = PAD_ID
            tok[0, :bucket_len] = tokens[:bucket_len]
            seg[0, :] = 0
            seg[0, :bucket_len] = 1
            if annotations is not None:
                ann[0, 0] = annotations
            row = self.dispatcher.run_packed_candidate(
                kind, tok, seg, ann, [(0, 0, 0, bucket_len)],
                heads=[head] if head is not None else None)[0]
        else:
            out = self.dispatcher.run_candidate(
                kind, tokens[None, :bucket_len],
                annotations[None] if annotations is not None else None,
                heads=[head] if head is not None else None)
            if kind == "embed":
                row = {k: v[0] for k, v in out.items()}
            else:
                row = out[0]
        if kind == "embed":
            value = {"global": np.asarray(row["global"]),
                     "local_mean": np.asarray(row["local_mean"])}
        elif kind in ("predict_go", TASK_KIND):
            value = np.asarray(row)
        else:  # predict_residues
            probs = np.asarray(row)
            value = (inference.fill_masked_residues(
                seq, probs, self.cfg.data.seq_len - 2), probs)
        self._bump("shadow_total")
        return self._present(kind, value, top_k)

    def rollout_status(self) -> Dict[str, Any]:
        """The replica's rollout arm state — surfaced on /healthz (via
        stats) so the fleet health sweep sees fingerprints per arm."""
        with self._mirror_lock:
            shadow = self.shadow_total
        return {
            "resident_fingerprint": self.trunk_fp(),
            "candidate_fingerprint": self._candidate_fp,
            "parked_fingerprint": self._parked_fp,
            "shadow_requests": shadow,
            "candidate": self.dispatcher.candidate_status(),
        }

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close(drain=exc_type is None)
        return False

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting, finish everything queued
        and in flight, then emit `serve_end{drained}`. Returns False if
        the scheduler did not exit within `timeout`."""
        self.queue.close()
        done = self.scheduler.join(timeout)
        if not self._ended:
            self._ended = True
            self._release_path_observer()
            self.tele.emit("serve_end", outcome="drained",
                           stats=self.stats())
        return done

    def _release_path_observer(self) -> None:
        from proteinbert_tpu.kernels.attention import (
            unregister_attention_path_observer,
        )
        from proteinbert_tpu.kernels.fused_block import (
            unregister_path_observer,
        )
        from proteinbert_tpu.kernels.one_pass import (
            unregister_onepass_path_observer,
        )

        unregister_path_observer(self._path_cb)
        unregister_attention_path_observer(self._attn_path_cb)
        unregister_onepass_path_observer(self._onepass_path_cb)

    def abort(self) -> None:
        """Hard shutdown: fail all queued + pending work with
        ServerClosedError, leave a flight-recorder trail, emit
        `serve_end{aborted}`. In-flight batches still finish (a jitted
        call cannot be interrupted); their futures resolve normally."""
        self.scheduler.stop()
        exc = ServerClosedError("server aborted before this request ran")
        failed = self.queue.fail_all(exc)
        self.scheduler.join(timeout=30.0)
        failed += self.scheduler.fail_pending(exc)
        now = self.clock()
        for req in failed:
            # Killed requests close their traces too — an abort must
            # not orphan spans (tests/test_serve_trace.py).
            self._seal(req.trace, "aborted", now, error=exc,
                       e2e_fallback=max(0.0, now - req.enqueued_at),
                       kind=req.kind)
        n = len(failed)
        if not self._ended:
            self._ended = True
            self._release_path_observer()
            self.tele.emit("note", source="serve", kind="abort",
                           failed_requests=n)
            self.tele.emit("serve_end", outcome="aborted",
                           stats=self.stats())
            self.tele.dump_flight("serve_abort")

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        if drain:
            self.drain(timeout)
        else:
            self.abort()

    # ------------------------------------------------------------- submit

    def submit(self, kind: str, seq: str, annotations=None,
               deadline_s: Optional[float] = None,
               top_k: Optional[int] = None,
               head_id: Optional[str] = None,
               trace_id: Optional[str] = None) -> Future:
        """Enqueue one request; returns its future (which carries the
        trace id as `.pbt_request_id` when tracing is on — the FLEET
        id when a router propagated one via `trace_id`, so one id
        names the request end-to-end across processes). Raises
        SequenceTooLongError (on_long="reject", or a '?' beyond the
        window for predict_residues), UnknownHeadError (predict_task
        for an unregistered/removed head — the typed 404), and
        ServerClosedError synchronously; QueueFullError /
        DeadlineExceededError land on futures (the evicted/expired
        request's, which may be an earlier caller's — never silently
        dropped)."""
        if kind not in KINDS and kind not in (TASK_KIND, NEIGHBORS_KIND):
            raise ValueError(f"unknown request kind {kind!r}; have "
                             f"{KINDS + (TASK_KIND, NEIGHBORS_KIND)}")
        if kind == NEIGHBORS_KIND and self.index is None:
            raise ValueError(
                "this server has no neighbor index attached — start it "
                "with index= (pbt serve --index DIR) to serve "
                "/v1/neighbors")
        if not seq:
            raise ValueError("empty sequence")
        if (kind == TASK_KIND) != (head_id is not None):
            raise ValueError(
                f"head_id is required for kind {TASK_KIND!r} and invalid "
                "for every other kind")
        now0 = self.clock()
        trace = None
        if self.trace_sample_rate is not None:
            n = next(self._req_ids)
            trace = RequestTrace(
                f"{self._id_prefix}{n:x}", kind, now0,
                sampled=stride_sampled(n, self.trace_sample_rate))
            # Join the propagated fleet context (ISSUE 18): the
            # router-minted id becomes this trace's trace_id/parent,
            # and the replica identity rides every emitted event.
            trace.join(trace_id, self.replica_id)
            trace.head_id = head_id
            # Which executable arm will serve this request (`quant` on
            # serve_request events — the per-request A/B attribution
            # field; absent on the fp32 arm).
            if self.quant != "fp32":
                trace.quant = self.quant
        head = None
        if kind == TASK_KIND:
            try:
                head = self.dispatcher.get_head(head_id)
            except UnknownHeadError as exc:
                # Typed 404: the head was never added or was hot-
                # removed. Counted + traced like every other rejection.
                self._rej_c["unknown_head"].inc()
                self._bump("rejected_total", "unknown_head")
                self.tele.emit("serve_reject", reason="unknown_head",
                               kind=kind, queue_depth=len(self.queue),
                               head_id=head_id)
                self._seal(trace, "rejected", self.clock(),
                           kind=kind)
                if trace is not None:
                    exc.pbt_request_id = trace.public_id()
                raise
        window = self.cfg.data.seq_len - 2
        if len(seq) > window:
            if (self.on_long == "reject"
                    or (kind == "predict_residues"
                        and inference.MASK_CHAR in seq[window:])):
                self._rej_c["too_long"].inc()
                self._bump("rejected_total", "too_long")
                self.tele.emit("serve_reject", reason="too_long",
                               kind=kind, queue_depth=len(self.queue))
                self._seal(trace, "rejected", self.clock(),
                           kind=kind)
                exc = SequenceTooLongError(
                    f"sequence of {len(seq)} residues exceeds the model "
                    f"window of {window}"
                    + (" (and masks a position the model would never "
                       "see)" if kind == "predict_residues" else
                       "; the server is configured to reject rather "
                       "than truncate"))
                if trace is not None:
                    # Synchronous rejections carry the trace id on the
                    # exception: the HTTP layer still answers with an
                    # X-PBT-Request-Id pinning the rejection's trace.
                    exc.pbt_request_id = trace.public_id()
                raise exc
            # The process-wide inference.TRUNCATED_TOTAL is bumped by
            # _tokenize_masked below (cache hits skip tokenization and
            # so don't count there); these are the serving-side counts.
            self._truncated_c.inc()
            self._bump("truncated_total")
        if annotations is not None:
            annotations = inference.check_annotations(
                np.asarray(annotations, np.float32)[None], 1, self.cfg)[0]
        self._req_c[kind].inc()
        future: Future = Future()
        if trace is not None:
            future.pbt_request_id = trace.public_id()
        key = None
        if self.cache.capacity:
            if trace is not None:
                trace.cache = "miss"
            # A head id is content-addressed over its weights + task +
            # trunk, so including it keys cached task results to the
            # exact model that produced them.
            if kind == NEIGHBORS_KIND:
                # Neighbor results depend on the exact index contents
                # (identity digest), the requested k, and the probe
                # breadth — all three scope the key, so a rebuilt index
                # or a different k can never alias a stale answer.
                scope = (f"{kind}:{self.index.digest[:16]}"
                         f":k{top_k or DEFAULT_NEIGHBORS_K}"
                         f":p{self.nprobe}")
            elif head is None:
                scope = kind
            else:
                scope = f"{kind}:{head.head_id}"
            key = content_key(scope, seq, annotations)
            hit = self.cache.get(key)
            if hit is not None:
                self._bump("cache_hit_returns")
                if trace is not None:
                    trace.cache = "hit"
                future.set_result(self._present(kind, hit, top_k))
                self._seal(trace, "cache_hit", self.clock(),
                           kind=kind)
                return future
        bucket_len = self.dispatcher.bucket_len(len(seq))
        tokens = inference._tokenize_masked(
            [seq], self.cfg.data.seq_len, on_overflow="count")[0, :bucket_len]
        now = self.clock()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if trace is not None:
            trace.mark_enqueued(now)
        req = Request(
            kind=kind, seq=seq, tokens=tokens, bucket_len=bucket_len,
            future=future, enqueued_at=now, annotations=annotations,
            deadline=(now + deadline_s if deadline_s is not None else None),
            top_k=top_k, cache_key=key, trace=trace, head=head)
        try:
            evicted = self.queue.push(req)
        except ServerClosedError as exc:
            self._rej_c["closed"].inc()
            self._bump("rejected_total", "closed")
            self.tele.emit("serve_reject", reason="closed", kind=kind,
                           queue_depth=len(self.queue))
            self._seal(trace, "rejected", self.clock(), kind=kind)
            if trace is not None:
                exc.pbt_request_id = trace.public_id()
            raise
        if evicted:
            now2 = self.clock()
            for old in evicted:
                self._rej_c["queue_full"].inc()
                self._bump("rejected_total", "queue_full")
                self.tele.emit("serve_reject", reason="queue_full",
                               kind=old.kind,
                               queue_depth=self.queue.max_depth)
                self._seal(old.trace, "evicted", now2,
                           e2e_fallback=max(0.0, now2 - old.enqueued_at),
                           kind=old.kind)
        self._depth_g.set(len(self.queue))
        return future

    # -------------------------------------------------------- sync facade

    def embed(self, seq: str, annotations=None,
              timeout: Optional[float] = None,
              deadline_s: Optional[float] = None) -> Dict[str, np.ndarray]:
        """{"global": (G,), "local_mean": (C,)} float32 for one
        sequence — the serving form of inference.embed."""
        return self.submit("embed", seq, annotations,
                           deadline_s=deadline_s).result(timeout)

    def predict_go(self, seq: str, top_k: Optional[int] = None,
                   timeout: Optional[float] = None,
                   deadline_s: Optional[float] = None):
        """(A,) sigmoid probabilities, or the top-k
        [(annotation_index, prob), ...] list."""
        return self.submit("predict_go", seq, top_k=top_k,
                           deadline_s=deadline_s).result(timeout)

    def predict_residues(self, seq: str, timeout: Optional[float] = None,
                         deadline_s: Optional[float] = None):
        """(filled_seq, probs (bucket_len, V)) — '?' positions filled
        with the argmax amino acid, like inference.predict_residues."""
        return self.submit("predict_residues", seq,
                           deadline_s=deadline_s).result(timeout)

    def neighbors(self, seq: str, k: Optional[int] = None,
                  timeout: Optional[float] = None,
                  deadline_s: Optional[float] = None):
        """{"neighbors": [(corpus_id, cosine_score), ...]} best-first
        for one query sequence: the sequence embeds through the trunk
        (riding whatever micro-batch is forming), then its global
        vector probes the attached int8 IVF index. Requires a server
        started with `index=`."""
        return self.submit(NEIGHBORS_KIND, seq, top_k=k,
                           deadline_s=deadline_s).result(timeout)

    def predict_task(self, head_id: str, seq: str, annotations=None,
                     timeout: Optional[float] = None,
                     deadline_s: Optional[float] = None) -> np.ndarray:
        """One registered head's float32 output for one sequence:
        (L, num_outputs) logits for token_classification,
        (num_outputs,) logits for sequence_classification, (1,) value
        for sequence_regression — the serving form of
        heads/apply.predict_task_rows. The request rides whatever
        micro-batch is forming for its bucket, alongside requests for
        OTHER heads (one shared trunk pass, per-head tails)."""
        return self.submit(TASK_KIND, seq, annotations,
                           deadline_s=deadline_s,
                           head_id=head_id).result(timeout)

    # ------------------------------------------------------- finalization

    def _present(self, kind: str, value, top_k: Optional[int]):
        """Shape a cached/computed value for one caller (top_k is a
        per-request view over the cached full probability row)."""
        if kind == "predict_go" and top_k is not None:
            probs = value
            k = min(top_k, probs.shape[0])
            idx = np.argsort(-probs)[:k]
            return [(int(j), float(probs[j])) for j in idx]
        return value

    def _finalize(self, req: Request, row) -> None:
        """Scheduler callback: one request's raw model row → its result
        (+ cache insert). Runs on the finalize thread — the completer
        when pipeline_depth > 1, else the scheduler thread; exactly one
        of the two ever calls this (ISSUE 19)."""
        if req.kind == NEIGHBORS_KIND:
            # The embed leg already ran (dispatch served this request
            # as an embed row); the lookup leg probes the resident
            # index here, on the scheduler thread, and is timed into
            # its own `lookup` trace stage.
            g = np.asarray(row["global"])
            k = req.top_k if req.top_k else DEFAULT_NEIGHBORS_K
            t0 = self.clock()
            pairs = self.index.lookup_one(g, k=k, nprobe=self.nprobe)
            t1 = self.clock()
            if req.trace is not None:
                req.trace.mark_lookup(t1)
            if req.trace is not None and req.trace.sampled:
                self.tele.emit(
                    "neighbor_query", k=int(k), nprobe=self.nprobe,
                    candidates=min(
                        self.index.num_vectors,
                        self.nprobe * int(self.index.members.shape[1])),
                    lookup_s=round(max(0.0, t1 - t0), 9),
                    outcome="ok", request_id=req.trace.request_id)
            value = {"neighbors": pairs}
        elif req.kind == "embed":
            value = {"global": np.asarray(row["global"]),
                     "local_mean": np.asarray(row["local_mean"])}
        elif req.kind in ("predict_go", TASK_KIND):
            value = np.asarray(row)
        else:  # predict_residues: fill '?' via the argmax amino acid
            probs = np.asarray(row)
            value = (inference.fill_masked_residues(
                req.seq, probs, self.cfg.data.seq_len - 2), probs)
        if req.cache_key is not None:
            self.cache.put(req.cache_key, value)
        self.completed_total += 1
        if not req.future.done():
            req.future.set_result(self._present(req.kind, value, req.top_k))
        self._depth_g.set(len(self.queue))

    def _count_expiry(self, req: Request) -> None:
        """Scheduler callback per deadline-expired request: the expiry
        IS a rejection, so it must show in serve_rejected_total, stats,
        and the CLI's --max-requests accounting (the serve_reject event
        is emitted scheduler-side already)."""
        self._rej_c["deadline"].inc()
        self._bump("rejected_total", "deadline")

    def _observe_latency(self, seconds: float) -> None:
        """Scheduler callback per successfully batched row: one ring
        (the registry QuantileWindow) serves stats(), /metrics, and the
        percentile gauges — computed at read time, no refresh cadence
        to drift."""
        self.latencies.observe(seconds)
        self._latency_h.observe(seconds)

    def _on_complete(self, req: Request, outcome: str, now: float,
                     error: Optional[BaseException],
                     ctx: Optional[dict]) -> None:
        """Scheduler callback per terminal request (ok/error/expired):
        seal the trace, emit, feed the SLO evaluator."""
        self._seal(req.trace, outcome, now, error=error,
                   e2e_fallback=max(0.0, now - req.enqueued_at),
                   kind=req.kind)

    def _seal(self, trace: Optional[RequestTrace], outcome: str,
              now: float, error: Optional[BaseException] = None,
              e2e_fallback: float = 0.0,
              kind: Optional[str] = None) -> None:
        """The single terminal funnel: every request reaches this
        exactly once per outcome path. Emits the serve_request event +
        spans for sampled or failed requests; feeds every completion
        (traced or not) to the SLO evaluator. `kind` lets untraced
        requests still feed the per-kind outcome funnels (neighbors);
        traced requests fall back to the trace's own kind."""
        stages = None
        e2e = e2e_fallback
        rid = None
        if kind is None and trace is not None:
            kind = trace.kind
        if trace is not None:
            if not trace.finish(outcome, now, error):
                return  # already sealed by an earlier outcome path
            e2e = trace.e2e_s()
            rid = trace.request_id
            emit = trace.sampled or outcome not in ("ok", "cache_hit")
            if emit or self.slo:
                # Stage decomposition only when something consumes it:
                # a sampled-out request with no SLOs pays marks, not
                # dict-building (the <1%-of-latency contract).
                stages = trace.stages()
            if emit:
                self.tele.emit("serve_request",
                               **trace.event_fields(stages=stages))
                if self.tele.spans is not None:
                    trace.export_spans(self.tele.spans)
        if kind == NEIGHBORS_KIND:
            c = self._nbr_c.get(outcome)
            if c is not None:
                c.inc()
            with self._mirror_lock:
                self.neighbors_total[outcome] = \
                    self.neighbors_total.get(outcome, 0) + 1
        if self.slo:
            if stages is not None and trace.pad_fraction \
                    and "execute" in stages:
                # Synthetic attribution stage: the share of device time
                # spent computing padding — the ragged-serving lever.
                stages = dict(stages)
                stages["pad_wasted"] = round(
                    stages["execute"] * trace.pad_fraction, 9)
            self.slo.observe(outcome, e2e, stages=stages,
                             request_id=rid, now=now)

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        with self._mirror_lock:
            mirrors = {
                "cache_hit_returns": self.cache_hit_returns,
                "truncated": self.truncated_total,
                "rejected": dict(self.rejected_total),
            }
            neighbors_by_outcome = dict(self.neighbors_total)
        from proteinbert_tpu.kernels.attention import ATTN_PATH_TOTAL
        from proteinbert_tpu.kernels.fused_block import PATH_TOTAL
        from proteinbert_tpu.kernels.one_pass import ONEPASS_PATH_TOTAL

        qw = self.scheduler.queue_wait
        # One coherent locked read of the dispatch counters: the
        # scheduler thread updates them under its lock (ISSUE 15
        # lock-discipline rule), so an unlocked field read here could
        # see a torn batches/rows pair mid-dispatch.
        batches, rows, expired = self.scheduler.stats_counts()
        out = {
            "completed": self.completed_total,
            **mirrors,
            "serve_mode": self.serve_mode,
            # Executable-zoo accounting (ISSUE 9): warm trunk-level
            # executables + cumulative warmup seconds — the numbers the
            # ragged mode's O(kinds) collapse is measured by.
            "executables": self.dispatcher.executable_count,
            "warmup_seconds": round(self.dispatcher.warmup_seconds_total,
                                    6),
            # Process-wide fused-kernel path coverage (trace-time, one
            # bump per executable): "path/reason" → executables built
            # on that path. "pallas/*" is the fast path; "reference/*"
            # the XLA composition (ISSUE 10 two-sided counter).
            "fused_path": {f"{p}/{r}": n
                           for (p, r), n in sorted(PATH_TOTAL.items())},
            # Same two-sided coverage for the ragged attention kernel
            # (kernels/attention.py, ISSUE 13).
            "attention_path": {f"{p}/{r}": n
                               for (p, r), n
                               in sorted(ATTN_PATH_TOTAL.items())},
            # One-pass trunk coverage (kernels/one_pass.py, ISSUE 16):
            # "pallas/*" means the whole block — local track AND
            # attention — ran as a single VMEM-resident kernel;
            # "reference/*" is the two-kernel composition fallback.
            "onepass_path": {f"{p}/{r}": n
                             for (p, r), n
                             in sorted(ONEPASS_PATH_TOTAL.items())},
            # Quantized executable arm (ISSUE 12): which arm serves,
            # the measured weight-HBM footprint, and the worst sampled
            # parity deviation vs the fp32 shadow (None = fp32 arm).
            "quant": ({"mode": self.quant, **self.dispatcher.quant_report}
                      if self.quant != "fp32" else None),
            "heads": len(self.dispatcher.heads),
            "batches": batches,
            "batched_rows": rows,
            "queue_depth": len(self.queue),
            "evicted": self.queue.evicted_total,
            "expired": expired,
            "cache": self.cache.stats(),
            "latency": self.latencies.summary(),
            "queue_wait": {
                "count": qw.count,
                "mean_s": (round(qw.total / qw.count, 6)
                           if qw.count else None),
                "max_s": (round(qw.max, 6) if qw.count else None),
            },
            # Pipelined dispatch (ISSUE 19): window depth, the deepest
            # the window actually got (overlap observed ⇔ >= 2), and
            # the share of finalize seconds that overlapped device
            # compute of a later batch.
            "pipeline": self.scheduler.pipeline_stats(),
            # Blue-green rollout arms (ISSUE 20): per-arm fingerprints
            # + shadow-request count — the fields the fleet health
            # sweep joins on to flag a mixed-fingerprint fleet.
            "rollout": self.rollout_status(),
        }
        # Neighbor-index arm (ISSUE 17): which index serves, its size,
        # and how many distinct lookup shapes have compiled — the
        # "one warm executable per (nprobe, k)" evidence.
        out["neighbors"] = (None if self.index is None else {
            "index_digest": self.index.digest,
            "corpus_digest": self.index.corpus_digest,
            "num_vectors": self.index.num_vectors,
            "nprobe": self.nprobe,
            "lookup_executables": self.index.executables(),
            "by_outcome": neighbors_by_outcome,
        })
        if self.slo:
            out["slo"] = self.slo.status()
        return out
