"""Content-addressed LRU result cache for the serving layer.

Keys are sha256 digests over (kind, sequence, annotations bytes) —
content addressing, so two textually identical queries hit the same
entry no matter which client sent them, and an annotation vector that
differs by one bit misses. Values are whatever the finalizer produced
for that request kind (an embed dict, a GO probability row, a filled
sequence + residue probs) — small host numpy arrays, held strongly.

Hit/miss/eviction counts feed both local stats() and, when a metrics
registry is supplied, the `serve_cache_{hits,misses,evictions}_total`
counters plus the `serve_cache_hit_rate` gauge (docs/observability.md).

Thread-safe: submit paths race against scheduler-thread inserts.
capacity == 0 disables the cache (every get misses, puts are dropped) —
the contract bench.py --serve uses for its no-cache comparison.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Any, Dict, Optional

import numpy as np


def content_key(kind: str, seq: str, annotations=None) -> str:
    """sha256 content address of one query.

    The kind participates (an `embed` and a `predict_go` of the same
    sequence are different results); annotations participate by shape +
    raw float32 bytes so "no annotations" (None / all-zero is NOT
    collapsed: None means the model's trained hide-all input, an
    explicit vector is data)."""
    h = hashlib.sha256()
    h.update(kind.encode())
    h.update(b"\x00")
    h.update(seq.encode())
    if annotations is not None:
        a = np.ascontiguousarray(annotations, dtype=np.float32)
        h.update(b"\x00")
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class EmbeddingCache:
    """Bounded LRU over content keys with counted evictions."""

    def __init__(self, capacity: int = 1024, metrics=None):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self._entries: "collections.OrderedDict[str, Any]" = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        if metrics is not None:
            self._hit_c = metrics.counter("serve_cache_hits_total")
            self._miss_c = metrics.counter("serve_cache_misses_total")
            self._evict_c = metrics.counter("serve_cache_evictions_total")
            self._rate_g = metrics.gauge("serve_cache_hit_rate")
        else:
            self._hit_c = self._miss_c = self._evict_c = self._rate_g = None

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: str) -> Optional[Any]:
        """The cached value (moved to most-recent), or None on miss."""
        with self._lock:
            value = self._entries.get(key)
            if value is None:
                self.misses += 1
                if self._miss_c is not None:
                    self._miss_c.inc()
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                if self._hit_c is not None:
                    self._hit_c.inc()
            if self._rate_g is not None:
                self._rate_g.set(self.hit_rate)
            return value

    def put(self, key: str, value: Any) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
                if self._evict_c is not None:
                    self._evict_c.inc()

    def clear(self) -> int:
        """Drop every entry (hit/miss/eviction counters keep their
        history); returns how many entries were dropped. The blue-green
        flip calls this: results computed by the outgoing trunk must
        not outlive it (a cached pre-flip embedding answering a
        post-flip query would silently mix trunks — ISSUE 20)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    @property
    def hit_rate(self) -> float:
        n = self.hits + self.misses
        return self.hits / n if n else 0.0

    def stats(self) -> Dict[str, Any]:
        return {"size": len(self._entries), "capacity": self.capacity,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": round(self.hit_rate, 4)}
