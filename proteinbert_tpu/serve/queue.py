"""Thread-safe bounded request queue with admission control.

The serving front door: client threads `push()` requests, the
scheduler thread drains them. Three contracts, all typed (serve/
errors.py) and all OBSERVED by the affected request's future — nothing
is ever silently dropped:

- **bounded depth / oldest-first rejection**: when the queue is full,
  the OLDEST queued request is evicted and its future fails with
  `QueueFullError`, and the new request is admitted. Newest-work-wins
  is the right default for interactive traffic: the oldest request is
  the one most likely to have already blown its client timeout, so it
  is the cheapest to reject (the classic bounded-mailbox policy).
- **closed state**: after `close()`, `push` raises `ServerClosedError`
  (drain: queued work still completes); `fail_all` empties the queue
  onto an exception (abort).

Per-request deadlines are enforced scheduler-side: every `poll()`
drains the queue via `pop_all()` first, so overdue requests are failed
with `DeadlineExceededError` by `MicroBatchScheduler._expire_pending`
before they waste a batch slot — one expiry implementation, not two.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from proteinbert_tpu.serve.errors import QueueFullError, ServerClosedError


@dataclasses.dataclass
class Request:
    """One admitted unit of work.

    tokens are already sliced to the request's bucket length
    (tokenization + bucket routing happen at submit time on the CLIENT
    thread, keeping the scheduler thread's work per request O(1));
    `deadline` is an absolute clock value or None; `future` carries the
    result or the typed rejection."""

    kind: str
    seq: str
    tokens: np.ndarray                       # (bucket_len,) int32
    bucket_len: int
    future: Future
    enqueued_at: float
    annotations: Optional[np.ndarray] = None  # (A,) float32 or None
    deadline: Optional[float] = None          # absolute clock value
    top_k: Optional[int] = None               # predict_go only
    cache_key: Optional[str] = None           # None = uncacheable/disabled
    trace: Optional[object] = None            # serve/trace.RequestTrace
                                              # (None = telemetry off)
    head: Optional[object] = None             # heads/registry.LoadedHead
                                              # (predict_task only).
                                              # Resolved at ADMISSION:
                                              # the request keeps its
                                              # own reference, so a hot
                                              # remove_head drains
                                              # queued work instead of
                                              # failing it


class RequestQueue:
    """FIFO of admitted requests, bounded at `max_depth`."""

    def __init__(self, max_depth: int = 64):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self._items: "collections.deque[Request]" = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False
        self.evicted_total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def closed(self) -> bool:
        return self._closed

    def push(self, req: Request) -> List[Request]:
        """Admit one request; returns the evicted requests (oldest-first
        overflow victims — already failed with QueueFullError, returned
        so the caller can count/emit them). Raises ServerClosedError
        when draining/closed."""
        evicted: List[Request] = []
        with self._lock:
            if self._closed:
                raise ServerClosedError(
                    "server is draining; not accepting new requests")
            while len(self._items) >= self.max_depth:
                evicted.append(self._items.popleft())
                self.evicted_total += 1
            self._items.append(req)
            self._nonempty.notify()
        for old in evicted:
            old.future.set_exception(QueueFullError(
                f"queue overflowed (depth {self.max_depth}); oldest "
                "request evicted to admit newer work"))
        return evicted

    def pop_all(self) -> List[Request]:
        """Drain every queued request (scheduler side)."""
        with self._lock:
            items = list(self._items)
            self._items.clear()
        return items

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is non-empty or closed; True if there
        is (probably) work. The scheduler's idle parking spot."""
        with self._lock:
            if self._items or self._closed:
                return bool(self._items)
            self._nonempty.wait(timeout)
            return bool(self._items)

    def close(self) -> None:
        """Stop admitting; queued work remains for the drain."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

    def fail_all(self, exc: Exception) -> List[Request]:
        """Abort path: empty the queue onto `exc`; returns the failed
        requests."""
        failed = self.pop_all()
        for req in failed:
            req.future.set_exception(exc)
        return failed
