"""Typed serving errors — the backpressure/deadline/drain contract.

Every way the serving layer can refuse work has its own exception type,
so callers (and the HTTP layer's status mapping) can distinguish "try
again later" (QueueFullError, 429) from "you were too slow"
(DeadlineExceededError, 504) from "the server is going away"
(ServerClosedError, 503) from "this input can never be served"
(SequenceTooLongError, 400). A rejected request always OBSERVES its
rejection — the error lands on its future (or raises synchronously at
submit) — never a silent drop.
"""

from __future__ import annotations

# Re-exported here so serving callers import every typed error from one
# place; it lives in inference.py because the OFFLINE surface raises it
# too (the silent-truncation fix) and inference must not depend on serve.
from proteinbert_tpu.inference import SequenceTooLongError  # noqa: F401

# Same convention for the multi-tenant head errors (ISSUE 8): they live
# in heads/registry.py because the registry raises them offline too;
# the serving layer maps UnknownHeadError to a typed 404 ("this head
# does not exist / was removed") and TrunkMismatchError to a 400 at
# head-add time ("this head cannot ever be served by this trunk").
from proteinbert_tpu.heads.registry import (  # noqa: F401
    TrunkMismatchError, UnknownHeadError,
)


class ServeError(Exception):
    """Base class for all serving-layer rejections."""


class QueueFullError(ServeError):
    """Admission control fired: the bounded queue overflowed and this
    (oldest) request was evicted to admit newer work."""


class DeadlineExceededError(ServeError):
    """The request's deadline passed before a batch could run it."""


class ServerClosedError(ServeError):
    """The server is draining or closed; no new work is accepted (and
    on abort, pending work fails with this)."""


class CandidateUnfitError(ServeError):
    """A candidate trunk (`Server.load_candidate`, ISSUE 20) does not
    fit beside the resident one within the device's HBM budget — the
    typed refusal of the blue-green rollout contract (two fp32 trunks
    usually don't fit; the int8 arm's ~0.27x resident bytes are the
    headroom a second trunk rides in). Mapped to HTTP 409."""


class NoCandidateError(ServeError):
    """A rollout verb (flip / rollback / shadow) was asked of a replica
    that holds no candidate (or no parked) trunk in that slot — a
    state error, not a capacity one. Mapped to HTTP 409."""
