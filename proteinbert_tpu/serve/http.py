"""Thin stdlib JSON/HTTP endpoint over the Server facade.

Deliberately `http.server`, not a framework: the repo's no-new-deps
rule, and the endpoint's job is only transport — every serving
behavior (batching, backpressure, deadlines, cache) lives in
serve/server.py and is identical for in-process callers.

Routes (POST bodies and responses are JSON):

  POST /v1/embed             {"seq", "annotations"?, "deadline_ms"?}
       → {"global": [...], "local_mean": [...]}
  POST /v1/predict_go        {"seq", "top_k"?, "deadline_ms"?}
       → {"top": [[idx, prob], ...]} or {"probs": [...]}
  POST /v1/predict_residues  {"seq", "deadline_ms"?}
       → {"filled": "..."} (probs stay server-side: a (L, V) matrix
         per request is transfer weight, not serving signal)
  POST /v1/predict_task      {"head_id", "seq", "annotations"?,
                              "deadline_ms"?}
       → {"head_id", "outputs": [...]} — one registered head's float32
         logits/prediction, shaped by its task kind (multi-tenant
         serving, ISSUE 8); unknown/removed head → typed 404
  POST /v1/neighbors         {"seq", "k"?, "deadline_ms"?}
       → {"neighbors": [[corpus_id, cosine_score], ...]} best-first —
         the sequence embeds through the trunk, then probes the
         server's attached int8 IVF index (`pbt serve --index`,
         ISSUE 17); no index attached → 400
  GET  /v1/heads             → {"heads": [{head_id, name, kind, ...}]}
  POST /v1/heads/add         {"head_id"} → load from the server's
                             registry (trunk-compat enforced; mismatch
                             → 400 {"type": "trunk_mismatch"})
  POST /v1/heads/remove      {"head_id"} → hot-remove (drain: queued
                             requests for it still complete)
  POST /v1/rollout/load      {"source", "hbm_budget_bytes"?} → load +
                             warm-boot a candidate trunk beside the
                             resident one (blue-green rollout,
                             ISSUE 20); doesn't fit → 409
                             {"type": "candidate_unfit"}
  POST /v1/rollout/flip      {} → atomic promotion (candidate becomes
                             resident, old trunk parked on host,
                             result cache flushed); no candidate →
                             409 {"type": "no_candidate"}
  POST /v1/rollout/rollback  {} → instant rollback to the parked
                             trunk (bit-identical numerics); nothing
                             parked → 409 {"type": "no_candidate"}
  POST /v1/rollout/unload    {} → drop the candidate arm (abort)
  GET  /healthz              → {"ok": true, "mode": "bucketed"|"ragged",
                               "quant": "fp32"|"int8"|"int8_act",
                               "trunk_fingerprint": "...",
                               "stats": {...}} — `mode` is the serving
                               dispatch mode (`pbt serve --serve-mode`,
                               ISSUE 9), `quant` the executable arm
                               (`pbt serve --quant`, ISSUE 12),
                               `trunk_fingerprint` the RESIDENT trunk's
                               identity (the field the fleet health
                               sweep joins on to flag a mixed-
                               fingerprint fleet, ISSUE 20; per-arm
                               detail under stats["rollout"]); stats
                               carries the executable-zoo accounting
                               (executables, warmup_seconds, fused_path
                               coverage) and, on a quantized arm, the
                               weight-bytes footprint + sampled parity
                               under "quant"

Shadow traffic (ISSUE 20): an inference POST carrying the header
`X-PBT-Shadow: 1` runs through the CANDIDATE trunk synchronously —
same response shape, but it never enqueues, never caches, never feeds
the SLO evaluator or any live counter. The fleet router mirrors
sampled live requests this way; no candidate loaded → 409.
  GET  /metrics              → Prometheus textfile (the registry's
                               exposition; empty when telemetry is off)
  GET  /metrics.json         → {"replica_id", "snapshot", "windows"} —
                               the registry snapshot plus RAW quantile-
                               window values, the machine-readable form
                               the fleet router's /fleet/metrics
                               aggregation scrapes (counters summed,
                               gauges labeled by replica, windows
                               merged value-by-value; ISSUE 18)

Typed-error → status mapping (the backpressure contract, visible to
clients): QueueFullError → 429, DeadlineExceededError → 504,
ServerClosedError → 503, UnknownHeadError → 404,
TrunkMismatchError/SequenceTooLongError/ValueError/bad JSON → 400.
`ThreadingHTTPServer` gives one thread per connection; they all
funnel into the one scheduler through Server.submit, so HTTP
concurrency IS the micro-batching concurrency.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from proteinbert_tpu.serve.errors import (
    CandidateUnfitError, DeadlineExceededError, NoCandidateError,
    QueueFullError, SequenceTooLongError, ServerClosedError,
    TrunkMismatchError, UnknownHeadError,
)
from proteinbert_tpu.serve.server import Server

_MAX_BODY = 32 * 1024 * 1024  # a seq + an 8943-float annotation vector fit


def _result_payload(kind: str, value, top_k: Optional[int],
                    head_id: Optional[str] = None):
    if kind == "embed":
        return {"global": [float(x) for x in value["global"]],
                "local_mean": [float(x) for x in value["local_mean"]]}
    if kind == "predict_go":
        if top_k is not None:
            return {"top": [[i, p] for i, p in value]}
        return {"probs": [float(x) for x in value]}
    if kind == "predict_task":
        return {"head_id": head_id, "outputs": value.tolist()}
    if kind == "neighbors":
        return {"neighbors": [[i, float(s)]
                              for i, s in value["neighbors"]]}
    filled, _probs = value
    return {"filled": filled}


def make_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet: telemetry covers it
            pass

        def _reply(self, status: int, payload,
                   request_id: Optional[str] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if request_id is not None:
                # The trace id (serve_request events, Perfetto lanes):
                # a client report quoting this header pins the exact
                # trace to pull up (docs/serving.md).
                self.send_header("X-PBT-Request-Id", request_id)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/healthz", "/stats"):
                self._reply(200, {"ok": True, "mode": server.serve_mode,
                                  "quant": server.quant,
                                  "trunk_fingerprint": server.trunk_fp(),
                                  "stats": server.stats()})
            elif self.path == "/v1/heads":
                self._reply(200, {"heads": server.list_heads()})
            elif self.path == "/metrics":
                text = ""
                if getattr(server.tele, "metrics", None) is not None:
                    if server.slo:
                        # Prune-at-scrape: an idle stream's burn rate
                        # decays with its window instead of freezing.
                        server.slo.refresh_gauges()
                    text = server.tele.metrics.prometheus_text()
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/metrics.json":
                snapshot, windows = {}, {}
                metrics = getattr(server.tele, "metrics", None)
                if metrics is not None:
                    if server.slo:
                        server.slo.refresh_gauges()
                    snapshot = metrics.snapshot()
                    # Raw ring values (not just the summary): the
                    # router merges fleet percentiles over the
                    # CONCATENATED values — p99 of a fleet is not any
                    # function of per-replica p99s.
                    windows = metrics.window_values()
                self._reply(200, {"replica_id": server.replica_id,
                                  "snapshot": snapshot,
                                  "windows": windows})
            else:
                self._reply(404, {"error": f"no such route {self.path}"})

        def _read_body(self):
            length = int(self.headers.get("Content-Length", 0))
            if not 0 < length <= _MAX_BODY:
                raise ValueError(f"bad Content-Length {length}")
            return json.loads(self.rfile.read(length))

        def _head_lifecycle(self, add: bool) -> None:
            """POST /v1/heads/{add,remove}: hot head management on the
            live server (the multi-tenant control plane)."""
            try:
                body = self._read_body()
                head_id = body["head_id"]
                if not isinstance(head_id, str):
                    raise ValueError("'head_id' must be a string")
                if add:
                    server.add_head(head_id)
                else:
                    server.remove_head(head_id)
            except UnknownHeadError as e:
                self._reply(404, {"error": str(e), "type": "unknown_head"})
            except TrunkMismatchError as e:
                self._reply(400, {"error": str(e),
                                  "type": "trunk_mismatch"})
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad request: {e}",
                                  "type": "bad_request"})
            else:
                self._reply(200, {"ok": True, "head_id": head_id,
                                  "heads": server.list_heads()})

        def _rollout_control(self, verb: str) -> None:
            """POST /v1/rollout/{load,flip,rollback,unload}: the
            blue-green control plane (ISSUE 20). Typed 409s:
            candidate_unfit (HBM refusal) and no_candidate (flip or
            rollback with an empty slot)."""
            try:
                if verb != "load":
                    # Drain any (ignored) body so keep-alive framing
                    # stays in sync.
                    length = int(self.headers.get("Content-Length", 0)
                                 or 0)
                    if length > 0:
                        self.rfile.read(min(length, _MAX_BODY))
                if verb == "load":
                    body = self._read_body()
                    source = body["source"]
                    if not isinstance(source, str):
                        raise ValueError("'source' must be a string")
                    budget = body.get("hbm_budget_bytes")
                    if budget is not None and (
                            isinstance(budget, bool)
                            or not isinstance(budget, int)):
                        raise ValueError(
                            "'hbm_budget_bytes' must be an integer")
                    out = server.load_candidate(source=source,
                                                hbm_budget_bytes=budget)
                elif verb == "flip":
                    out = server.flip()
                elif verb == "rollback":
                    out = server.rollback_trunk()
                else:  # unload
                    out = {"unloaded": server.unload_candidate()}
            except CandidateUnfitError as e:
                self._reply(409, {"error": str(e),
                                  "type": "candidate_unfit"})
            except NoCandidateError as e:
                self._reply(409, {"error": str(e),
                                  "type": "no_candidate"})
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad request: {e}",
                                  "type": "bad_request"})
            except Exception as e:  # noqa: BLE001 — a loader/placement
                # failure must answer, not drop the connection.
                self._reply(500, {"error": f"internal error: {e}",
                                  "type": "internal"})
            else:
                self._reply(200, {"ok": True, **out})

        def do_POST(self):
            if self.path == "/v1/heads/add":
                self._head_lifecycle(add=True)
                return
            if self.path == "/v1/heads/remove":
                self._head_lifecycle(add=False)
                return
            if self.path.startswith("/v1/rollout/"):
                verb = self.path[len("/v1/rollout/"):]
                if verb not in ("load", "flip", "rollback", "unload"):
                    self._reply(404,
                                {"error": f"no such route {self.path}"})
                    return
                self._rollout_control(verb)
                return
            route = {"/v1/embed": "embed",
                     "/v1/predict_go": "predict_go",
                     "/v1/predict_residues": "predict_residues",
                     "/v1/predict_task": "predict_task",
                     "/v1/neighbors": "neighbors"}
            kind = route.get(self.path)
            if kind is None:
                self._reply(404, {"error": f"no such route {self.path}"})
                return
            request_id = None
            head_id = None
            try:
                body = self._read_body()
                seq = body["seq"]
                if not isinstance(seq, str):
                    raise ValueError("'seq' must be a string")
                deadline_ms = body.get("deadline_ms")
                if deadline_ms is not None and (
                        isinstance(deadline_ms, bool)
                        or not isinstance(deadline_ms, (int, float))):
                    raise ValueError("'deadline_ms' must be a number")
                top_k = body.get("top_k") if kind == "predict_go" else None
                if kind == "neighbors":
                    top_k = body.get("k")
                    if top_k is not None and (isinstance(top_k, bool)
                                              or not isinstance(top_k, int)
                                              or top_k < 1):
                        raise ValueError("'k' must be a positive integer")
                elif top_k is not None and (isinstance(top_k, bool)
                                            or not isinstance(top_k, int)):
                    raise ValueError("'top_k' must be an integer")
                if kind == "predict_task":
                    head_id = body["head_id"]
                    if not isinstance(head_id, str):
                        raise ValueError("'head_id' must be a string")
                # Shadow traffic (ISSUE 20): the router's mirrored
                # copy of a live request runs through the CANDIDATE
                # arm synchronously — never enqueued, never cached,
                # never counted on the live path.
                if self.headers.get("X-PBT-Shadow") == "1":
                    value = server.shadow_submit(
                        kind, seq, annotations=body.get("annotations"),
                        head_id=head_id, top_k=top_k)
                else:
                    # Fleet-scope causal context (ISSUE 18): a router
                    # injects its minted trace id here; the trace
                    # joins it and X-PBT-Request-Id answers with the
                    # FLEET id, so one id names the request end-to-end
                    # across processes.
                    trace_id = self.headers.get("X-PBT-Trace")
                    future = server.submit(
                        kind, seq, annotations=body.get("annotations"),
                        deadline_s=(deadline_ms / 1000.0
                                    if deadline_ms is not None
                                    else None),
                        top_k=top_k, head_id=head_id, trace_id=trace_id)
                    request_id = getattr(future, "pbt_request_id", None)
                    value = future.result()
            except UnknownHeadError as e:
                # The typed 404 of the multi-tenant contract: this head
                # does not exist on this server (never added, or hot-
                # removed). Distinct from a route 404 by its body type.
                self._reply(404, {"error": str(e), "type": "unknown_head"},
                            getattr(e, "pbt_request_id", request_id))
            except QueueFullError as e:
                self._reply(429, {"error": str(e), "type": "queue_full"},
                            request_id)
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e), "type": "deadline"},
                            request_id)
            except ServerClosedError as e:
                # Rejected before a future existed: submit() stamps
                # the trace id on the exception instead.
                self._reply(503, {"error": str(e), "type": "closed"},
                            getattr(e, "pbt_request_id", request_id))
            except SequenceTooLongError as e:
                self._reply(400, {"error": str(e), "type": "too_long"},
                            getattr(e, "pbt_request_id", request_id))
            except NoCandidateError as e:
                # Shadow asked of a replica with an empty candidate
                # slot (a race with unload/flip): typed 409 so the
                # mirror records it without touching live accounting.
                self._reply(409, {"error": str(e),
                                  "type": "no_candidate"}, request_id)
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad request: {e}",
                                  "type": "bad_request"}, request_id)
            except Exception as e:  # noqa: BLE001 — a dispatch-side
                # failure lands on the future; a dropped connection
                # would hide it from the client, so map it to a 500.
                self._reply(500, {"error": f"internal error: {e}",
                                  "type": "internal"}, request_id)
            else:
                self._reply(200, _result_payload(kind, value, top_k,
                                                 head_id),
                            request_id)

    return Handler


def make_http_server(server: Server, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral; read `.server_address[1]`) but do not
    serve — callers run `.serve_forever()` themselves (the CLI does,
    under GracefulShutdown) so shutdown stays in their hands."""
    httpd = ThreadingHTTPServer((host, port), make_handler(server))
    httpd.daemon_threads = True
    return httpd
