"""Batched IVF-flat neighbor lookup — the `/v1/neighbors` hot path.

`NeighborIndex.load` pulls a built index (see index/store.py) into
host memory in its QUANTIZED form — int8 residual codes + per-block
fp32 channel scales + int32 centroid assignments, ~0.25× the fp32
bytes — and groups rows per centroid into one padded member table. A
lookup is then a single jitted executable:

    q̂ · centroidsᵀ → top-nprobe shortlist
    → gather the shortlist's member rows (codes, scale rows)
    → score = (codes · scale) · q̂ + q̂ · centroid   (cosine, since both
      sides are L2-normalized and vectors are stored as residuals)
    → masked top-k over the candidate set

One warm executable per (batch, nprobe, k) shape — the same
compile-once-serve-forever discipline as serve/dispatch.py's bucketed
entries; `executables()` exposes the warm count so stats can prove no
per-request recompilation. The int8 dequant (codes × scales) happens
INSIDE the executable, so host memory keeps the small form.

Exact brute-force helpers (`exact_topk`, `evaluate_recall`) live here
too: the recall@k gate in bench.py --neighbors and the
quantized-vs-fp32 bound in tests/test_index.py both score against
them, and `evaluate_recall` is what feeds the `neighbors_recall_at_k`
gauge.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from proteinbert_tpu.index.store import (
    INDEX_KIND, EmbeddingStore, ShardCursor, StoreConfigError,
    index_identity, load_centroids, next_offset,
)
from proteinbert_tpu.obs import as_telemetry


def _normalize(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.float32)
    norm = np.linalg.norm(x, axis=-1, keepdims=True)
    return (x / np.where(norm > 0, norm, 1.0)).astype(np.float32)


@partial(jax.jit, static_argnames=("nprobe", "k"))
def _lookup_jit(qhat, centroids, members, codes, scales, scale_row,
                nprobe: int, k: int):
    """(scores (Q, k), rows (Q, k)) — rows are GLOBAL index rows, -inf
    scores mark slots beyond the candidate set. Static (nprobe, k)
    keep this one executable per served shape."""
    cd = qhat @ centroids.T                                 # (Q, K)
    cent_score, probe = jax.lax.top_k(cd, nprobe)           # (Q, P)
    cand = members[probe]                                   # (Q, P, L)
    valid = cand >= 0
    rows = jnp.where(valid, cand, 0)
    resid = codes[rows].astype(jnp.float32) * scales[scale_row[rows]]
    score = jnp.einsum("qpld,qd->qpl", resid, qhat) \
        + cent_score[..., None]                             # (Q, P, L)
    score = jnp.where(valid, score, -jnp.inf)
    flat = score.reshape(score.shape[0], -1)
    rows_flat = rows.reshape(rows.shape[0], -1)
    best, pos = jax.lax.top_k(flat, k)
    return best, jnp.take_along_axis(rows_flat, pos, axis=1)


class NeighborIndex:
    """A loaded index: quantized vectors resident, lookups jitted."""

    def __init__(self, ids: np.ndarray, codes: np.ndarray,
                 scale_row: np.ndarray, scales: np.ndarray,
                 assign: np.ndarray, centroids: np.ndarray,
                 manifest: Dict[str, Any], digest: str):
        self.ids = ids                      # (N,) 'S' bytes
        self.codes = codes                  # (N, d) int8
        self.scale_row = scale_row          # (N,) int32 → row of scales
        self.scales = scales                # (B, d) fp32, one per block
        self.assign = assign                # (N,) int32
        self.centroids = centroids          # (K, d) fp32
        self.manifest = manifest
        self.digest = digest                # index_identity(index_dir)
        self._warm: Dict[Tuple[int, int, int], int] = {}
        k_cent = centroids.shape[0]
        counts = np.bincount(assign, minlength=k_cent)
        width = max(1, int(counts.max()) if counts.size else 1)
        members = np.full((k_cent, width), -1, np.int32)
        fill = np.zeros(k_cent, np.int64)
        for row, c in enumerate(assign):    # corpus order within a list
            members[c, fill[c]] = row
            fill[c] += 1
        self.members = members

    # ------------------------------------------------------------- load

    @classmethod
    def load(cls, index_dir: str) -> "NeighborIndex":
        """Digest-verified load of a COMPLETE index (every shard done);
        an incomplete or foreign directory is a typed refusal."""
        store = EmbeddingStore(index_dir)
        manifest = store.load_manifest()
        if manifest is None:
            raise StoreConfigError(f"{index_dir} has no manifest.json — "
                                   "not a neighbor index")
        if manifest.get("kind") != INDEX_KIND:
            raise StoreConfigError(
                f"{index_dir} manifest kind {manifest.get('kind')!r} "
                f"is not {INDEX_KIND!r}")
        centroids, _cdigest = load_centroids(index_dir)
        ids: List[np.ndarray] = []
        codes: List[np.ndarray] = []
        scales: List[np.ndarray] = []
        scale_row: List[np.ndarray] = []
        assign: List[np.ndarray] = []
        block_row = 0
        for shard in range(int(manifest["num_shards"])):
            state, _ = ShardCursor(index_dir, shard).load()
            if not state["done"]:
                raise StoreConfigError(
                    f"index shard {shard} is not done "
                    f"({next_offset(state)} vectors) — resume "
                    "`pbt index` before serving it")
            for entry in state["blocks"]:
                _meta, arrays = store.read_block(entry["digest"])
                n = int(entry["n"])
                ids.append(arrays["ids"])
                codes.append(arrays["codes"])
                assign.append(arrays["assign"])
                scales.append(arrays["scales"][None, :])
                scale_row.append(np.full(n, block_row, np.int32))
                block_row += 1
        return cls(
            ids=np.concatenate(ids, axis=0),
            codes=np.ascontiguousarray(np.concatenate(codes, axis=0)),
            scale_row=np.concatenate(scale_row, axis=0),
            scales=np.ascontiguousarray(
                np.concatenate(scales, axis=0, dtype=np.float32)),
            assign=np.concatenate(assign, axis=0),
            centroids=centroids,
            manifest=manifest,
            digest=index_identity(index_dir),
        )

    # ---------------------------------------------------------- queries

    @property
    def num_vectors(self) -> int:
        return int(self.ids.shape[0])

    @property
    def dim(self) -> int:
        return int(self.codes.shape[1])

    @property
    def model_fingerprint(self) -> str:
        return str(self.manifest.get("model_fingerprint", ""))

    @property
    def corpus_digest(self) -> str:
        return str(self.manifest.get("corpus_digest", ""))

    def executables(self) -> int:
        """Distinct (batch, nprobe, k) shapes served so far — the
        no-per-request-recompilation evidence in Server.stats()."""
        return len(self._warm)

    def _clamp(self, k: int, nprobe: int) -> Tuple[int, int]:
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if nprobe < 1:
            raise ValueError(f"nprobe must be >= 1, got {nprobe}")
        return (min(int(k), self.num_vectors),
                min(int(nprobe), int(self.centroids.shape[0])))

    def lookup_rows(self, queries: np.ndarray, k: int = 10,
                    nprobe: int = 8) -> Tuple[np.ndarray, np.ndarray]:
        """(scores (Q, k), global rows (Q, k)) for a batch of raw
        query vectors; -inf score marks a slot the probed lists could
        not fill. The batched entry bench drives for sustained QPS."""
        qhat = np.atleast_2d(_normalize(queries))
        k, nprobe = self._clamp(k, nprobe)
        key = (int(qhat.shape[0]), nprobe, k)
        self._warm[key] = self._warm.get(key, 0) + 1
        scores, rows = _lookup_jit(
            jnp.asarray(qhat), jnp.asarray(self.centroids),
            jnp.asarray(self.members), jnp.asarray(self.codes),
            jnp.asarray(self.scales), jnp.asarray(self.scale_row),
            nprobe=nprobe, k=k)
        return np.asarray(scores), np.asarray(rows)

    def lookup_one(self, query: np.ndarray, k: int = 10,
                   nprobe: int = 8) -> List[Tuple[str, float]]:
        """[(corpus id, cosine score)] best-first for ONE query vector
        — the serve-path entry (Server._finalize)."""
        scores, rows = self.lookup_rows(np.asarray(query)[None, :],
                                        k=k, nprobe=nprobe)
        out: List[Tuple[str, float]] = []
        for s, r in zip(scores[0], rows[0]):
            if not np.isfinite(s):
                continue
            out.append((self.ids[int(r)].decode(), float(s)))
        return out


# ------------------------------------------------------- recall helpers

def exact_topk(vectors: np.ndarray, queries: np.ndarray,
               k: int) -> np.ndarray:
    """Ground-truth cosine top-k row indices (Q, k) by brute force over
    the FP32 vectors — what the ANN answers are measured against."""
    vhat = _normalize(vectors)
    qhat = np.atleast_2d(_normalize(queries))
    sims = qhat @ vhat.T
    k = min(int(k), vhat.shape[0])
    part = np.argpartition(-sims, k - 1, axis=1)[:, :k]
    order = np.take_along_axis(sims, part, axis=1).argsort(axis=1)[:, ::-1]
    return np.take_along_axis(part, order, axis=1)


def recall_at_k(approx_rows: np.ndarray, exact_rows: np.ndarray) -> float:
    """Mean fraction of exact top-k rows the approximate answer
    recovered (order-insensitive — the standard ANN recall@k)."""
    approx_rows = np.atleast_2d(approx_rows)
    exact_rows = np.atleast_2d(exact_rows)
    hits = 0
    total = 0
    for a, e in zip(approx_rows, exact_rows):
        es = set(int(x) for x in e)
        hits += len(es & set(int(x) for x in a))
        total += len(es)
    return hits / total if total else 0.0


def evaluate_recall(index: NeighborIndex, vectors: np.ndarray,
                    queries: np.ndarray, k: int = 10, nprobe: int = 8,
                    telemetry=None) -> float:
    """recall@k of the quantized index vs exact fp32 brute force over
    `vectors` (the store's fp32 embeddings, index row order). Sets the
    `neighbors_recall_at_k` gauge — the instrument the bench gate and
    diagnose read."""
    _scores, rows = index.lookup_rows(queries, k=k, nprobe=nprobe)
    exact = exact_topk(vectors, queries, k=k)
    recall = recall_at_k(rows, exact)
    as_telemetry(telemetry).metrics.gauge(
        "neighbors_recall_at_k", k=str(int(k))).set(recall)
    return recall


def store_vectors_in_index_order(store_dir: str) -> np.ndarray:
    """The store's fp32 `global` vectors concatenated in the index's
    row order (shard-major, corpus order within a shard) — the
    brute-force side of every recall measurement."""
    from proteinbert_tpu.mapper.store import iter_embeddings
    return np.stack([rec["global"]
                     for _id, rec in iter_embeddings(store_dir)]) \
        .astype(np.float32)
