"""Neighbor index subsystem (`pbt index` + `/v1/neighbors`).

- `index.store` — stdlib+numpy build/verify half: the resumable,
  kill-anywhere `pbt index` builder on the mapper's cursor protocol,
  `verify_index`, and the digest helpers (importable without jax, same
  contract as `mapper.store`).
- `index.scorer` — the jax half: `NeighborIndex.load` + the jitted
  batched IVF-flat lookup, plus the exact brute-force recall helpers.

Only the store half is re-exported here so `import proteinbert_tpu.index`
stays jax-free; serving/bench code imports the scorer explicitly:
`from proteinbert_tpu.index.scorer import NeighborIndex`.
"""

from proteinbert_tpu.index.store import (
    CENTROIDS_POINTER, DEFAULT_BLOCK_SIZE, DEFAULT_CENTROIDS,
    INDEX_BUILD_STATES, INDEX_FAULT_ENV, INDEX_KIND, IndexBuildError,
    build_index, index_digests, index_identity, load_centroids,
    verify_index,
)

__all__ = [
    "CENTROIDS_POINTER", "DEFAULT_BLOCK_SIZE", "DEFAULT_CENTROIDS",
    "INDEX_BUILD_STATES", "INDEX_FAULT_ENV", "INDEX_KIND",
    "IndexBuildError",
    "build_index", "index_digests", "index_identity", "load_centroids",
    "verify_index",
]
