"""IVF-flat neighbor index over the embedding store (`pbt index`).

The read-heavy half of the ROADMAP-1 story: once `pbt map` has embedded
a corpus into the verified content-addressed store, answering "what is
this sequence similar to?" should cost an index probe, not a trunk
forward per corpus row. This module builds that index — and it reuses
the mapper's durability machinery WHOLESALE rather than reinventing it:

- **Same block format.** Index blocks are `mapper.store.serialize_block`
  payloads (magic + sorted-key JSON header + raw C-order arrays),
  content-addressed under `objects/` in the index directory.
- **Same cursor protocol.** Per-shard `ShardCursor` documents advanced
  only after the block they record is durably on disk
  (`commit_block`: quarantine → object tmp+fsync+rename → cursor
  prev-generation copy + atomic replace). A SIGKILL anywhere loses at
  most one block per shard; `resume_shard` re-verifies the tail.
- **Same manifest drift check.** `EmbeddingStore.ensure_manifest` on
  the index directory pins the index to the SOURCE STORE's
  `corpus_digest` and `model_fingerprint` (plus the index geometry):
  resuming — or rebuilding — against a store whose corpus or trunk
  changed is a typed `StoreConfigError` raised before any write.
- **Same fault seams.** The builder consumes `mapper.faults.MapFaults`
  specs from `PBT_INDEX_FAULTS`, so tools/index_drill.py kills it at
  the exact filesystem boundaries the map drill already exercises.

Index layout (everything deterministic — two builds of the same store
with the same knobs produce byte-identical objects, the drill's gate):

    index_dir/
      manifest.json          pinned config (see build_index)
      centroids.json         {"digest": <sha256 of the centroids block>}
      objects/<aa>/<digest>  centroids block + per-shard vector blocks
      shards/<s>/cursor.json mapper-format cursors (+ .prev, quarantine)

Vectors are the store's `global` embeddings, L2-normalized (cosine
metric). Coarse centroids come from a seeded spherical k-means over a
strided sample; each vector stores its centroid assignment plus an
int8-quantized RESIDUAL (v̂ − centroid) with per-channel symmetric
scales per block (`parallel.quant.quantize_rows_int8` — the same
amax/127 round-to-nearest convention as the int8 serving trunk). At
~1 byte/channel + one fp32 scale row per block the index holds ≤0.30×
the fp32 vector bytes while recall@10 stays ≥0.95 (gated in
bench.py --neighbors).

Stdlib + numpy at module level (the jax-free verify contract of
mapper/store.py); the quantizer import is deferred into the build path.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from proteinbert_tpu.mapper.faults import MapFaults
from proteinbert_tpu.mapper.store import (
    BlockIntegrityError, EmbeddingStore, ShardCursor, StoreConfigError,
    StoreError, block_digest, commit_block, deserialize_block,
    next_offset, resume_shard, serialize_block, _atomic_write,
)
from proteinbert_tpu.obs import as_telemetry

logger = logging.getLogger(__name__)

INDEX_KIND = "neighbor_index"
INDEX_FAULT_ENV = "PBT_INDEX_FAULTS"
CENTROIDS_POINTER = "centroids.json"

# Builder defaults — small enough that the tier-1 drill builds in
# seconds, documented in docs/neighbors.md with the sizing rule.
DEFAULT_BLOCK_SIZE = 256
DEFAULT_CENTROIDS = 64
DEFAULT_KMEANS_ITERS = 8
DEFAULT_SAMPLE_CAP = 4096

INDEX_BUILD_STATES = ("start", "completed", "preempted", "error")


class IndexBuildError(StoreError):
    """The source store cannot be indexed as-is: missing/foreign
    manifest, unfinished shards, or an empty corpus. Raised before any
    index write."""


def _l2_normalize(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.float32)
    norm = np.linalg.norm(x, axis=-1, keepdims=True)
    return (x / np.where(norm > 0, norm, 1.0)).astype(np.float32)


def _spherical_kmeans(sample_hat: np.ndarray, k: int, iters: int,
                      seed: int) -> np.ndarray:
    """Seeded spherical k-means on L2-normalized rows. Fully
    deterministic for a given (sample, k, iters, seed): the centroids
    block's bytes are part of the drill's byte-identity gate."""
    rng = np.random.default_rng(seed)
    init = rng.permutation(len(sample_hat))[:k]
    cent = sample_hat[init].copy()
    for _ in range(max(0, iters)):
        sims = sample_hat @ cent.T                       # (n, k)
        assign = np.argmax(sims, axis=1)
        for j in range(k):
            members = sample_hat[assign == j]
            if len(members):
                v = members.mean(axis=0, dtype=np.float32)
                norm = float(np.linalg.norm(v))
                if norm > 0:
                    cent[j] = (v / norm).astype(np.float32)
            else:
                # Re-seed an empty cluster at the worst-served point —
                # deterministic (argmin breaks ties by first index).
                cent[j] = sample_hat[int(np.argmin(np.max(sims, axis=1)))]
    return np.ascontiguousarray(cent, np.float32)


def _load_store_for_index(store_dir: str):
    """Validate the source store and collect what the builder needs:
    (store, store_manifest, per-shard block entries, per-shard vector
    counts, dim). Typed refusals, no writes."""
    store = EmbeddingStore(store_dir)
    manifest = store.load_manifest()
    if manifest is None:
        raise IndexBuildError(f"{store_dir} has no manifest.json — "
                              "not an embedding store")
    if manifest.get("kind") != "embedding_store":
        raise IndexBuildError(
            f"{store_dir} manifest kind {manifest.get('kind')!r} is not "
            "'embedding_store' — refusing to index it")
    num_shards = int(manifest["num_shards"])
    shard_entries: List[List[Dict[str, Any]]] = []
    shard_vectors: List[int] = []
    for shard in range(num_shards):
        state, _source = ShardCursor(store_dir, shard).load()
        if not state["done"]:
            raise IndexBuildError(
                f"store shard {shard} is not done ({next_offset(state)} "
                f"sequences consumed) — finish `pbt map` before "
                "indexing; a partial index would silently answer from "
                "a partial corpus")
        shard_entries.append(list(state["blocks"]))
        shard_vectors.append(sum(int(e["n"]) for e in state["blocks"]))
    total = sum(shard_vectors)
    if total == 0:
        raise IndexBuildError(
            f"store {store_dir} holds zero embedded sequences — "
            "nothing to index")
    first_shard = next(s for s, n in enumerate(shard_vectors) if n)
    _meta, arrays = store.read_block(shard_entries[first_shard][0]["digest"])
    dim = int(arrays["global"].shape[1])
    return store, manifest, shard_entries, shard_vectors, dim


def _sample_vectors(store: EmbeddingStore,
                    shard_entries: List[List[Dict[str, Any]]],
                    total: int, cap: int) -> np.ndarray:
    """Strided global sample of L2-normalized vectors for the k-means
    pass — deterministic (stride from the pinned corpus size)."""
    stride = max(1, total // max(1, cap))
    rows: List[np.ndarray] = []
    pos = 0
    for entries in shard_entries:
        for entry in entries:
            n = int(entry["n"])
            take = [i for i in range(n) if (pos + i) % stride == 0]
            if take:
                _meta, arrays = store.read_block(entry["digest"])
                rows.append(np.asarray(arrays["global"],
                                       np.float32)[take])
            pos += n
    return _l2_normalize(np.concatenate(rows, axis=0))


def _ensure_centroids(index_store: EmbeddingStore, sample_hat: np.ndarray,
                      num_centroids: int, iters: int,
                      seed: int) -> Tuple[np.ndarray, str]:
    """Compute (deterministically) and persist the centroids block;
    idempotent across resumes. The pointer file is tiny JSON written
    atomically AFTER the content-addressed object, so a crash between
    the two re-converges on the next run (same bytes, same digest,
    `write_object` is idempotent). A pointer that disagrees with the
    recomputation is a typed refusal — it means the index directory
    belongs to a different build."""
    cent = _spherical_kmeans(sample_hat, num_centroids, iters, seed)
    payload = serialize_block(
        {"kind": "centroids", "num_centroids": int(cent.shape[0]),
         "dim": int(cent.shape[1]), "seed": int(seed),
         "kmeans_iters": int(iters)},
        {"centroids": cent})
    digest = block_digest(payload)
    ptr_path = os.path.join(index_store.directory, CENTROIDS_POINTER)
    if os.path.exists(ptr_path):
        with open(ptr_path) as f:
            ptr = json.load(f)
        if ptr.get("digest") != digest:
            raise StoreConfigError(
                f"index {index_store.directory} centroids pointer "
                f"{ptr.get('digest')!r} does not match the "
                f"deterministic recomputation {digest} — the index was "
                "built with different inputs; refusing to mix builds")
    index_store.write_object(payload, digest)  # idempotent / repairing
    if not os.path.exists(ptr_path):
        _atomic_write(ptr_path, json.dumps(
            {"digest": digest}, sort_keys=True, indent=1).encode())
    return cent, digest


def load_centroids(index_dir: str) -> Tuple[np.ndarray, str]:
    """(centroids fp32 (K, d), digest) from a built index —
    digest-verified via the object store read path."""
    ptr_path = os.path.join(os.path.abspath(index_dir), CENTROIDS_POINTER)
    try:
        with open(ptr_path) as f:
            ptr = json.load(f)
    except FileNotFoundError:
        raise BlockIntegrityError(
            f"{index_dir} has no {CENTROIDS_POINTER} — index was never "
            "built (or its build never reached the centroids phase)",
            reason="missing") from None
    except ValueError as e:
        raise BlockIntegrityError(
            f"{ptr_path} is unreadable ({e})", reason="malformed") \
            from None
    digest = str(ptr.get("digest", ""))
    _meta, arrays = EmbeddingStore(index_dir).read_block(digest)
    return np.asarray(arrays["centroids"], np.float32), digest


def _quantize_block(vectors: np.ndarray, centroids: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(assign int32, codes int8, scales fp32) for one block of raw
    store vectors: normalize → nearest centroid by dot product →
    int8-quantize the residuals with per-channel scales."""
    # Deferred: parallel.quant imports jax at module level, and this
    # module keeps the mapper store's jax-free verify contract.
    from proteinbert_tpu.parallel.quant import quantize_rows_int8
    vhat = _l2_normalize(vectors)
    assign = np.argmax(vhat @ centroids.T, axis=1).astype(np.int32)
    resid = vhat - centroids[assign]
    codes, scales = quantize_rows_int8(resid)
    return assign, codes, scales


def build_index(store_dir: str, index_dir: str, *,
                num_centroids: int = DEFAULT_CENTROIDS,
                block_size: int = DEFAULT_BLOCK_SIZE,
                seed: int = 0,
                kmeans_iters: int = DEFAULT_KMEANS_ITERS,
                sample_cap: int = DEFAULT_SAMPLE_CAP,
                max_blocks: Optional[int] = None,
                stop_flag: Optional[Callable[[], bool]] = None,
                telemetry=None,
                faults: Optional[MapFaults] = None) -> Dict[str, Any]:
    """Build (or resume) the neighbor index for a COMPLETE embedding
    store. Kill-anywhere: every committed block survives, a crash loses
    at most one block per shard, and re-runs converge on byte-identical
    objects. Returns the stats dict of the terminal `index_build`
    event; outcome ∈ {"completed", "preempted"} (errors raise typed)."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if num_centroids < 1:
        raise ValueError(f"num_centroids must be >= 1, "
                         f"got {num_centroids}")
    ev = as_telemetry(telemetry)
    if faults is None:
        faults = MapFaults.from_env(INDEX_FAULT_ENV)
    if faults.armed():
        logger.warning("index fault injection armed via %s",
                       INDEX_FAULT_ENV)

    (store, smanifest, shard_entries, shard_vectors,
     dim) = _load_store_for_index(store_dir)
    total = sum(shard_vectors)
    num_centroids = min(int(num_centroids), total)
    num_shards = len(shard_vectors)

    index_store = EmbeddingStore(index_dir)
    # THE stale-pin refusal: corpus digest + trunk fingerprint ride the
    # manifest, so an index directory can never silently mix builds
    # against a changed corpus or a retrained trunk.
    manifest = index_store.ensure_manifest({
        "kind": INDEX_KIND,
        "corpus_digest": smanifest["corpus_digest"],
        "model_fingerprint": smanifest["model_fingerprint"],
        "corpus_n": int(smanifest["corpus_n"]),
        "num_shards": num_shards,
        "shard_vectors": [int(n) for n in shard_vectors],
        "block_size": int(block_size),
        "num_centroids": int(num_centroids),
        "dim": int(dim),
        "vector": "global",
        "metric": "cosine",
        "seed": int(seed),
        "kmeans_iters": int(kmeans_iters),
        "sample_cap": int(sample_cap),
    })

    config = {k: manifest[k] for k in sorted(manifest)}
    ev.emit("index_build", state="start", stats={}, config=config,
            pid=os.getpid())

    sample_hat = _sample_vectors(store, shard_entries, total, sample_cap)
    centroids, centroids_digest = _ensure_centroids(
        index_store, sample_hat, num_centroids, kmeans_iters, seed)

    stats = {"shards": num_shards, "vectors": 0, "blocks": 0,
             "reworked_blocks": 0, "centroids_digest": centroids_digest,
             "index_vector_bytes": 0,
             "fp32_vector_bytes": int(total) * int(dim) * 4}
    outcome = "completed"
    budget = [max_blocks]  # None = unbounded; mutated by _spend

    def _stopped() -> bool:
        return stop_flag is not None and stop_flag()

    def _spend() -> bool:
        if budget[0] is None:
            return True
        if budget[0] <= 0:
            return False
        budget[0] -= 1
        return True

    for shard in range(num_shards):
        if _stopped() or (budget[0] is not None and budget[0] <= 0):
            outcome = "preempted"
            break
        cursor = ShardCursor(index_dir, shard)
        state, info = resume_shard(index_store, shard)
        size = shard_vectors[shard]
        nxt = next_offset(state)
        reworked = (1 if info["tail_dropped"] is not None else 0) \
            + (1 if info["source"] == "prev" and nxt < size else 0)
        stats["reworked_blocks"] += reworked
        if info["source"] == "fresh":
            # Persist generation 0 before the first block so the first
            # advance has a .prev to fall back to (mirrors run_map).
            state = cursor.write_state(state)
        ev.emit("index_shard", shard=shard,
                state="start" if info["source"] == "fresh" else "resume",
                next=nxt, size=size, blocks=len(state["blocks"]),
                cursor_source=info["source"], tail_reworked=reworked)
        vec_c = ev.metrics.counter("index_vectors_total", shard=str(shard))
        while nxt < size:
            if _stopped():
                outcome = "preempted"
                break
            if not _spend():
                outcome = "preempted"
                break
            block_idx = nxt // block_size
            end = min(nxt + block_size, size)
            ids, vectors = _read_shard_rows(
                store, shard_entries[shard], nxt, end)
            assign, codes, scales = _quantize_block(vectors, centroids)
            payload = serialize_block(
                {"shard": shard, "block": block_idx, "start": nxt,
                 "end": end, "n": end - nxt,
                 "centroids": centroids_digest},
                {"ids": ids, "assign": assign, "codes": codes,
                 "scales": scales})
            entry = {"block": block_idx, "digest": block_digest(payload),
                     "start": nxt, "end": end, "n": end - nxt}
            state = commit_block(index_store, cursor, state, payload,
                                 entry,
                                 crash=faults.crash_hook(shard, block_idx))
            stats["blocks"] += 1
            stats["vectors"] += end - nxt
            stats["index_vector_bytes"] += (
                codes.nbytes + scales.nbytes + assign.nbytes)
            vec_c.inc(end - nxt)
            nxt = end
        if outcome != "completed":
            ev.emit("index_shard", shard=shard, state="preempted",
                    next=nxt, size=size, blocks=len(state["blocks"]))
            break
        if not state["done"]:
            state = cursor.write_state(dict(state, done=True))
        ev.emit("index_shard", shard=shard, state="done", next=nxt,
                size=size, blocks=len(state["blocks"]))

    fp32 = stats["fp32_vector_bytes"]
    stats["bytes_ratio"] = (stats["index_vector_bytes"] / fp32
                            if fp32 else 0.0)
    stats["outcome"] = outcome
    ev.emit("index_build", state=outcome, stats=stats, pid=os.getpid())
    return stats


def _read_shard_rows(store: EmbeddingStore,
                     entries: List[Dict[str, Any]], start: int,
                     end: int) -> Tuple[np.ndarray, np.ndarray]:
    """(ids 'S' array, global vectors fp32) for shard-local rows
    [start, end) — spans store blocks (index block size need not match
    the store's)."""
    ids: List[np.ndarray] = []
    vecs: List[np.ndarray] = []
    for entry in entries:
        lo, hi = int(entry["start"]), int(entry["end"])
        if hi <= start or lo >= end:
            continue
        _meta, arrays = store.read_block(entry["digest"])
        s = max(start, lo) - lo
        e = min(end, hi) - lo
        ids.append(arrays["ids"][s:e])
        vecs.append(np.asarray(arrays["global"], np.float32)[s:e])
    return (np.concatenate(ids, axis=0),
            np.concatenate(vecs, axis=0))


# ----------------------------------------------------------- verification

def verify_index(index_dir: str) -> Dict[str, Any]:
    """Recompute every referenced digest and audit geometry/coverage —
    the `pbt index --verify` pass, mirroring mapper.store.verify_store:
    content problems land in the report (ok=False), only an
    uninterpretable manifest raises."""
    index_store = EmbeddingStore(index_dir)
    manifest = index_store.load_manifest()
    if manifest is None:
        raise StoreConfigError(f"{index_dir} has no manifest.json — "
                               "not a neighbor index")
    if manifest.get("kind") != INDEX_KIND:
        raise StoreConfigError(
            f"{index_dir} manifest kind {manifest.get('kind')!r} is "
            f"not {INDEX_KIND!r}")
    num_shards = int(manifest["num_shards"])
    shard_vectors = [int(n) for n in manifest["shard_vectors"]]
    dim = int(manifest["dim"])
    num_centroids = int(manifest["num_centroids"])
    holes: List[Dict[str, Any]] = []
    corrupt: List[Dict[str, Any]] = []
    coverage_errors: List[str] = []
    shards_out: List[Dict[str, Any]] = []
    blocks_checked = 0
    vectors = 0
    all_done = True

    centroids_digest = ""
    try:
        centroids, centroids_digest = load_centroids(index_dir)
        if centroids.shape != (num_centroids, dim):
            corrupt.append({"kind": "centroids",
                            "digest": centroids_digest,
                            "reason": "shape_mismatch"})
    except BlockIntegrityError as e:
        (holes if e.reason == "missing" else corrupt).append(
            {"kind": "centroids", "digest": e.digest,
             "reason": e.reason})

    for shard in range(num_shards):
        cursor = ShardCursor(index_dir, shard)
        try:
            state, source = cursor.load()
        except StoreError as e:
            coverage_errors.append(str(e))
            all_done = False
            shards_out.append({"shard": shard, "error": str(e)})
            continue
        expected_start = 0
        for entry in state["blocks"]:
            blocks_checked += 1
            if entry["start"] != expected_start:
                coverage_errors.append(
                    f"shard {shard} block {entry['block']}: starts at "
                    f"{entry['start']}, expected {expected_start} "
                    "(gap or overlap)")
            expected_start = entry["end"]
            vectors += int(entry["n"])
            try:
                meta, arrays = index_store.read_block(entry["digest"])
            except BlockIntegrityError as e:
                rec = {"shard": shard, "block": entry["block"],
                       "digest": entry["digest"], "reason": e.reason}
                (holes if e.reason == "missing" else corrupt).append(rec)
                continue
            n = int(entry["n"])
            reason = None
            if arrays["ids"].shape[0] != n \
                    or arrays["assign"].shape != (n,) \
                    or arrays["codes"].shape != (n, dim) \
                    or arrays["scales"].shape != (dim,):
                reason = "shape_mismatch"
            elif arrays["codes"].dtype != np.int8:
                reason = "dtype_mismatch"
            elif n and not (0 <= int(arrays["assign"].min())
                            and int(arrays["assign"].max())
                            < num_centroids):
                reason = "assign_out_of_range"
            elif centroids_digest \
                    and meta.get("centroids") != centroids_digest:
                reason = "centroids_mismatch"
            if reason:
                corrupt.append({"shard": shard, "block": entry["block"],
                                "digest": entry["digest"],
                                "reason": reason})
        consumed = next_offset(state)
        if state["done"] and consumed != shard_vectors[shard]:
            coverage_errors.append(
                f"shard {shard} marked done at "
                f"{consumed}/{shard_vectors[shard]} vectors")
        if not state["done"]:
            all_done = False
        shards_out.append({
            "shard": shard, "size": shard_vectors[shard],
            "consumed": consumed, "blocks": len(state["blocks"]),
            "done": state["done"], "cursor_source": source,
        })

    report = {
        "index": index_store.directory,
        "manifest": manifest,
        "centroids_digest": centroids_digest,
        "shards": shards_out,
        "blocks_checked": blocks_checked,
        "vectors": vectors,
        "holes": holes,
        "corrupt": corrupt,
        "coverage_errors": coverage_errors,
        "complete": all_done,
    }
    report["ok"] = not (holes or corrupt or coverage_errors)
    return report


def index_digests(index_dir: str) -> Dict[str, str]:
    """{"centroids": digest, "<shard>/<block>": digest} over the whole
    index — the drill's byte-identity comparison key (objects are
    content-addressed, so equal digests mean byte-identical files)."""
    index_store = EmbeddingStore(index_dir)
    manifest = index_store.load_manifest()
    if manifest is None:
        raise StoreConfigError(f"{index_dir} has no manifest.json")
    out: Dict[str, str] = {}
    ptr_path = os.path.join(index_store.directory, CENTROIDS_POINTER)
    if os.path.exists(ptr_path):
        with open(ptr_path) as f:
            out["centroids"] = str(json.load(f).get("digest", ""))
    for shard in range(int(manifest["num_shards"])):
        state, _ = ShardCursor(index_dir, shard).load()
        for entry in state["blocks"]:
            out[f"{shard}/{int(entry['block'])}"] = entry["digest"]
    return out


def index_identity(index_dir: str) -> str:
    """One digest naming the whole index CONTENT (manifest pins +
    centroids + every block digest) — the cache-scoping key: two
    servers answer `/v1/neighbors` from the same cache entry iff they
    serve the same index bytes."""
    index_store = EmbeddingStore(index_dir)
    manifest = index_store.load_manifest() or {}
    h = hashlib.sha256()
    h.update(str(manifest.get("corpus_digest", "")).encode())
    h.update(b"\x00")
    h.update(str(manifest.get("model_fingerprint", "")).encode())
    for key, digest in sorted(index_digests(index_dir).items()):
        h.update(b"\x00")
        h.update(key.encode())
        h.update(b"\x01")
        h.update(digest.encode())
    return h.hexdigest()
