"""Blue-green trunk rollout (ISSUE 20).

A rollout drives a CANDIDATE trunk through shadow → gate → flip →
(rollback) beside the resident one:

  - every replica loads the candidate as a second executable arm
    (`Server.load_candidate`, warm-booted through the compile cache,
    HBM-priced with a typed refusal when two trunks don't fit);
  - the fleet router mirrors a sampled fraction of live traffic to the
    candidate as sealed shadow attempts (`rollout_shadow` events under
    the live request's trace_id — never retried, never user-visible,
    never cache-writing);
  - the controller closes per-window gates (shadow parity, SLO-burn
    delta, heads-eval score delta) and promotes only after N
    consecutive green windows;
  - promotion is an atomic per-replica flip (the old trunk parks on
    host for instant rollback) with frozen heads re-pinned via
    `HeadRegistry.migrate_fingerprint`.

`tools/rollout_drill.py` proves the lifecycle end to end in tier-1.
"""

from proteinbert_tpu.rollout.controller import RolloutController
from proteinbert_tpu.rollout.gates import HeadsEvalGate

__all__ = ["RolloutController", "HeadsEvalGate"]
