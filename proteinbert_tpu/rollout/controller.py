"""The rollout controller (ISSUE 20): shadow → gate → flip → rollback.

One controller drives one candidate trunk through the fleet. The
router's sealed-200 path calls `mirror()` AFTER every live response is
sealed; the controller samples a deterministic stride of those, replays
each against the serving replica's CANDIDATE arm (X-PBT-Shadow), and
scores the response pair. Shadows are observations, not requests: they
never retry, never write a cache, never touch a live counter, and a
full mirror queue drops the copy rather than slowing the live path.

Gates close per window of `window_requests` shadows:

  parity      max |live − shadow| over shared numeric leaves
  slo_burn    fleet max burn-rate now − the baseline at start()
  heads_eval  worst registered-head score drop through the candidate
              (HeadsEvalGate, cached — the eval runs once per rollout)
  failures    zero shadow transport/HTTP failures in the window

`windows_required` consecutive green windows promote (when
auto_promote); the same count of consecutive red windows refuses and
unloads the candidate everywhere. Promotion flips each replica in turn
(`_pre_flip_hook` is the drill's chaos seam), re-pins frozen heads, and
flushes the router cache — old-trunk responses must not outlive the
flip. A post-promotion `breach()` rolls every flipped replica back to
the host-parked trunk (bit-identical numerics) and restores the pins.
"""

from __future__ import annotations

import json
import logging
import math
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from proteinbert_tpu.obs import as_telemetry

logger = logging.getLogger("proteinbert_tpu.rollout")

# Event-enum states a controller can report (schema: rollout_state).
TERMINAL_STATES = ("idle", "refused", "aborted", "promoted",
                   "rolled_back")


def _has_numeric(x: Any) -> bool:
    if isinstance(x, bool):
        return False
    if isinstance(x, (int, float)):
        return True
    if isinstance(x, dict):
        return any(_has_numeric(v) for v in x.values())
    if isinstance(x, list):
        return any(_has_numeric(v) for v in x)
    return False


def parity_delta(live: Any, shadow: Any) -> float:
    """Max abs difference over the numeric leaves two JSON bodies
    share; +inf on structural mismatch (a numeric leaf present on one
    side only, shape/length skew, or type disagreement). Non-numeric
    leaves (request ids, names) may differ freely — identity fields
    are EXPECTED to differ between a live response and its shadow."""
    if isinstance(live, bool) or isinstance(shadow, bool):
        return 0.0 if live == shadow else math.inf
    if isinstance(live, (int, float)) and isinstance(shadow, (int, float)):
        return abs(float(live) - float(shadow))
    if isinstance(live, dict) and isinstance(shadow, dict):
        worst = 0.0
        for k in set(live) | set(shadow):
            if k not in live or k not in shadow:
                if _has_numeric(live.get(k)) or _has_numeric(shadow.get(k)):
                    return math.inf
                continue
            worst = max(worst, parity_delta(live[k], shadow[k]))
            if math.isinf(worst):
                return worst
        return worst
    if isinstance(live, list) and isinstance(shadow, list):
        if len(live) != len(shadow):
            return math.inf
        worst = 0.0
        for a, b in zip(live, shadow):
            worst = max(worst, parity_delta(a, b))
            if math.isinf(worst):
                return worst
        return worst
    if type(live) is type(shadow):
        return 0.0
    return math.inf


class RolloutController:
    """See module doc. Thread model: `mirror()` runs on router handler
    threads (sample + enqueue only); one worker thread replays shadows
    and closes windows; control verbs (start/promote/abort/breach) run
    on whichever thread calls them, guarded by short `_lock` holds with
    all network I/O outside the lock."""

    def __init__(
        self,
        router,
        *,
        telemetry=None,
        source: str,
        sample_every: int = 2,
        window_requests: int = 8,
        windows_required: int = 2,
        shadow_parity_max: float = 1e-3,
        slo_burn_delta_max: float = 0.5,
        heads_eval_drop_max: float = 0.05,
        heads_eval=None,
        auto_promote: bool = True,
        hbm_budget_bytes: Optional[int] = None,
        clock=time.monotonic,
    ):
        if not isinstance(source, str) or not source:
            raise ValueError("rollout 'source' must be a non-empty "
                             "string (the candidate_loader key)")
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if window_requests < 1:
            raise ValueError("window_requests must be >= 1")
        if windows_required < 1:
            raise ValueError("windows_required must be >= 1")
        self.router = router
        self.tele = as_telemetry(telemetry)
        self.clock = clock
        self.source = source
        self.sample_every = int(sample_every)
        self.window_requests = int(window_requests)
        self.windows_required = int(windows_required)
        self.shadow_parity_max = float(shadow_parity_max)
        self.slo_burn_delta_max = float(slo_burn_delta_max)
        self.heads_eval_drop_max = float(heads_eval_drop_max)
        self.heads_eval = heads_eval
        self.auto_promote = bool(auto_promote)
        self.hbm_budget_bytes = hbm_budget_bytes

        self.state = "idle"
        self.candidate_fp: Optional[str] = None
        self.loaded: List[str] = []
        self.flipped: List[str] = []
        self.baseline_burn = 0.0
        self.windows_green = 0

        self._lock = threading.Lock()
        self._mirror_seen = 0       # guarded-by: _lock
        self._sampled = 0           # guarded-by: _lock
        self._dropped = 0           # guarded-by: _lock
        self._ok_total = 0          # guarded-by: _lock
        self._failed_total = 0      # guarded-by: _lock
        self._window = 0            # guarded-by: _lock
        self._w_parity = 0.0        # guarded-by: _lock
        self._w_ok = 0              # guarded-by: _lock
        self._w_failed = 0          # guarded-by: _lock
        self._red_streak = 0
        self._heads_delta_cached: Optional[float] = None
        self._flip_seconds: Optional[float] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=64)
        self._worker: Optional[threading.Thread] = None
        # Test seam: called with the replica name IMMEDIATELY before
        # its flip verb is posted — the drill's mid-flip SIGKILL lands
        # here to prove the fleet converges anyway.
        self._pre_flip_hook = lambda name: None

        metrics = self.tele.metrics
        self._shadow_c = {o: metrics.counter("rollout_shadow_total",
                                             outcome=o)
                          for o in ("ok", "failed")}
        self._parity_g = metrics.gauge("rollout_shadow_parity_max")
        self._green_g = metrics.gauge("rollout_windows_green")
        self._flip_g = metrics.gauge("rollout_flip_seconds")

    # ---------------------------------------------------------- lifecycle

    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def _routable_names(self) -> List[str]:
        with self.router._lock:
            return [r.name for r in self.router.replicas if r.routable()]

    def _fleet_burn(self) -> float:
        with self.router._lock:
            burns = [r.burn_rate for r in self.router.replicas
                     if r.routable()]
        return max(burns, default=0.0)

    def start(self) -> Dict[str, Any]:
        """Load + warm the candidate on every routable replica, verify
        the arms agree on the candidate's identity, enter shadowing.
        Any replica's typed refusal (409 candidate_unfit / anything
        non-200) refuses the WHOLE rollout and unloads the others —
        a fleet that can only half-host a candidate must not shadow."""
        with self._lock:
            if self.state != "idle":
                raise RuntimeError(f"rollout already {self.state}")
            self.state = "loading"
        names = self._routable_names()
        if not names:
            self.state = "refused"
            raise RuntimeError("no routable replica to load a "
                               "candidate on")
        self.baseline_burn = self._fleet_burn()
        body: Dict[str, Any] = {"source": self.source}
        if self.hbm_budget_bytes is not None:
            body["hbm_budget_bytes"] = int(self.hbm_budget_bytes)
        loaded: List[str] = []
        fps: Dict[str, Optional[str]] = {}
        for name in names:
            status, resp = self.router.control_forward(
                name, "/v1/rollout/load", body)
            if status != 200:
                self._unload(loaded)
                self.state = "refused"
                reason = resp.decode("utf-8", "replace")[:300] \
                    if resp else f"transport failure (status {status})"
                self.tele.emit("rollout_state", state="refused",
                               source=self.source, reason=reason)
                raise RuntimeError(
                    f"replica {name} refused the candidate "
                    f"(HTTP {status}): {reason}")
            try:
                fps[name] = json.loads(resp).get("fingerprint")
            except ValueError:
                fps[name] = None
            loaded.append(name)
        distinct = set(fps.values())
        if len(distinct) != 1 or None in distinct:
            self._unload(loaded)
            self.state = "refused"
            self.tele.emit("rollout_state", state="refused",
                           source=self.source,
                           reason=f"candidate fingerprints disagree: "
                                  f"{fps}")
            raise RuntimeError(
                f"candidate fingerprints disagree across replicas: "
                f"{fps} — every arm must load the SAME trunk")
        self.candidate_fp = distinct.pop()
        self.loaded = loaded
        with self._lock:
            self.state = "shadowing"
        self.tele.emit("rollout_state", state="shadowing",
                       source=self.source,
                       fingerprint=self.candidate_fp or "",
                       windows_green=0)
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="rollout-shadow",
                                        daemon=True)
        self._worker.start()
        return self.status()

    def _unload(self, names: List[str]) -> None:
        for name in names:
            status, _ = self.router.control_forward(
                name, "/v1/rollout/unload")
            if status != 200:
                logger.warning("rollout: unload on %s answered %s",
                               name, status)

    # ------------------------------------------------------ shadow plane

    def mirror(self, path: str, raw_body: bytes, trace_id: str,
               live_resp: bytes, replica: str) -> None:
        """Router hook (sealed-200 path): sample by deterministic
        stride, enqueue, never block. Runs on live handler threads —
        everything heavier happens on the worker."""
        if self.state != "shadowing":
            return
        with self._lock:
            n = self._mirror_seen
            self._mirror_seen += 1
        if n % self.sample_every != 0:
            return
        try:
            self._queue.put_nowait(
                (path, raw_body, trace_id, live_resp, replica))
            with self._lock:
                self._sampled += 1
        except queue.Full:
            with self._lock:
                self._dropped += 1

    def _worker_loop(self) -> None:
        while True:
            try:
                item = self._queue.get(timeout=0.25)
            except queue.Empty:
                if self.terminal():
                    return
                continue
            if item is None:
                return
            try:
                self._do_shadow(*item)
            except Exception:  # noqa: BLE001 — a shadow-plane crash
                # must never take the worker (and the rollout) down.
                logger.exception("shadow replay failed")

    def _do_shadow(self, path: str, raw_body: bytes, trace_id: str,
                   live_resp: bytes, replica: str) -> None:
        status, body = self.router.shadow_forward(
            replica, path, raw_body, trace_id)
        ok = status == 200
        parity: Optional[float] = None
        if ok:
            try:
                parity = parity_delta(json.loads(live_resp),
                                      json.loads(body))
            except ValueError:
                parity = math.inf
        fields: Dict[str, Any] = {"status": int(status), "path": path}
        if parity is not None and math.isfinite(parity):
            fields["parity_max"] = round(parity, 9)
        self.tele.emit("rollout_shadow", trace_id=trace_id,
                       replica=replica,
                       outcome="ok" if ok else "failed",
                       shadow=True, **fields)
        self._shadow_c["ok" if ok else "failed"].inc()
        close = False
        with self._lock:
            if ok:
                self._ok_total += 1
                self._w_ok += 1
                if parity is not None:
                    self._w_parity = max(self._w_parity, parity)
            else:
                self._failed_total += 1
                self._w_failed += 1
            self._parity_g.set(0.0 if math.isinf(self._w_parity)
                               else self._w_parity)
            close = (self._w_ok + self._w_failed) >= self.window_requests
        if close:
            self._close_window()

    # ------------------------------------------------------------- gates

    def _heads_delta(self) -> float:
        if self.heads_eval is None:
            return 0.0
        if self._heads_delta_cached is None:
            try:
                self._heads_delta_cached = float(self.heads_eval())
            except Exception:  # noqa: BLE001 — an eval harness crash
                # must FAIL the gate (worst possible delta), not strand
                # the rollout in shadowing with windows that never
                # close their verdict.
                logger.exception("heads-eval gate crashed; scoring it "
                                 "as a failed gate")
                self._heads_delta_cached = math.inf
        return self._heads_delta_cached

    def _close_window(self) -> None:
        with self._lock:
            idx = self._window
            parity = self._w_parity
            ok_n, fail_n = self._w_ok, self._w_failed
            self._window += 1
            self._w_parity = 0.0
            self._w_ok = 0
            self._w_failed = 0
        slo_delta = self._fleet_burn() - self.baseline_burn
        heads_delta = self._heads_delta()
        checks = {
            "parity": parity <= self.shadow_parity_max,
            "slo_burn": slo_delta <= self.slo_burn_delta_max,
            "heads_eval": heads_delta <= self.heads_eval_drop_max,
            "shadow_failures": fail_n == 0,
        }
        verdict = "pass" if all(checks.values()) else "fail"
        fields: Dict[str, Any] = {
            "slo_burn_delta": round(slo_delta, 6),
            "shadow_ok": ok_n, "shadow_failed": fail_n,
        }
        if math.isfinite(heads_delta):
            fields["heads_eval_delta"] = round(heads_delta, 6)
        if math.isfinite(parity):
            fields["parity_max"] = round(parity, 9)
        self.tele.emit("rollout_window", window=idx, verdict=verdict,
                       **fields)
        if verdict == "pass":
            self.windows_green += 1
            self._red_streak = 0
            self._green_g.set(self.windows_green)
            if (self.auto_promote
                    and self.windows_green >= self.windows_required
                    and self.state == "shadowing"):
                try:
                    self.promote()
                except RuntimeError as e:
                    logger.warning("auto-promote refused: %s", e)
        else:
            self.windows_green = 0
            self._red_streak += 1
            self._green_g.set(0)
            if self._red_streak >= self.windows_required:
                failed = ",".join(k for k, v in checks.items() if not v)
                self._refuse(f"gates failed {self._red_streak} "
                             f"consecutive windows ({failed})")

    def _refuse(self, reason: str) -> None:
        with self._lock:
            if self.state != "shadowing":
                return
            self.state = "refused"
        self._unload(self.loaded)
        self.tele.emit("rollout_state", state="refused",
                       source=self.source,
                       fingerprint=self.candidate_fp or "",
                       reason=reason)

    # ----------------------------------------------------- control verbs

    def promote(self) -> Dict[str, Any]:
        """Atomic per-replica flip, gated: promotion needs the full
        green streak — `pbt rollout promote` is 'promote now that the
        gates hold', not 'skip the gates'. ≥1 landed flip promotes (a
        replica that died mid-flip converges via the health plane: it
        is dead, not mixed); zero landed flips aborts."""
        with self._lock:
            if self.state != "shadowing":
                raise RuntimeError(
                    f"cannot promote a rollout in state {self.state!r}")
            if self.windows_green < self.windows_required:
                raise RuntimeError(
                    f"gates not satisfied: {self.windows_green}/"
                    f"{self.windows_required} consecutive green windows")
            self.state = "promoting"
        self.tele.emit("rollout_state", state="promoting",
                       fingerprint=self.candidate_fp or "",
                       windows_green=self.windows_green)
        flipped: List[str] = []
        failed: List[Tuple[str, int]] = []
        worst = 0.0
        for name in list(self.loaded):
            try:
                self._pre_flip_hook(name)
            except Exception:  # noqa: BLE001 — the chaos seam must not
                # decide the flip's fate; the verb below does.
                logger.exception("pre-flip hook raised for %s", name)
            status, resp = self.router.control_forward(
                name, "/v1/rollout/flip")
            if status == 200:
                flipped.append(name)
                try:
                    worst = max(worst, float(
                        json.loads(resp).get("seconds") or 0.0))
                except ValueError:
                    pass
            else:
                failed.append((name, status))
                logger.warning("flip on %s answered %s", name, status)
        if not flipped:
            with self._lock:
                self.state = "aborted"
            self.tele.emit("rollout_state", state="aborted",
                           fingerprint=self.candidate_fp or "",
                           reason=f"flip landed nowhere: {failed}")
            raise RuntimeError(f"flip failed on every replica: {failed}")
        self.flipped = flipped
        if self.heads_eval is not None:
            refused = self.heads_eval.commit()
            for r in refused:
                logger.warning("head %s not migrated: unfrozen",
                               r["head_id"])
        # Old-trunk responses must not outlive the flip: the ROUTER's
        # cache too, not just each replica's (the replica flushes its
        # own inside Server.flip()).
        self.router.cache.clear()
        self._flip_seconds = worst
        self._flip_g.set(worst)
        with self._lock:
            self.state = "promoted"
        self.tele.emit("rollout_state", state="promoted",
                       fingerprint=self.candidate_fp or "",
                       windows_green=self.windows_green,
                       flip_seconds=round(worst, 6))
        return self.status()

    def breach(self, reason: str = "slo_breach") -> Dict[str, Any]:
        """Post-promotion gate breach: instant rollback. Every flipped
        replica re-promotes its host-parked trunk (bit-identical —
        the drill proves it against pre-rollout probes), head pins are
        restored, and both cache tiers flush again."""
        with self._lock:
            if self.state != "promoted":
                raise RuntimeError(
                    f"cannot roll back a rollout in state "
                    f"{self.state!r}")
            self.state = "rolling_back"
        failed: List[Tuple[str, int]] = []
        for name in list(self.flipped):
            status, _ = self.router.control_forward(
                name, "/v1/rollout/rollback")
            if status != 200:
                failed.append((name, status))
                logger.warning("rollback on %s answered %s", name,
                               status)
        if self.heads_eval is not None:
            self.heads_eval.restore()
        self.router.cache.clear()
        with self._lock:
            self.state = "rolled_back"
        self.tele.emit("rollout_state", state="rolled_back",
                       fingerprint=self.candidate_fp or "",
                       reason=reason)
        if failed:
            raise RuntimeError(
                f"rollback did not land everywhere: {failed} — those "
                "replicas still serve the demoted trunk (the health "
                "sweep will flag the fleet degraded)")
        return self.status()

    def abort(self) -> Dict[str, Any]:
        """Operator abort: pre-promotion this unloads the candidate
        everywhere; post-promotion it is a rollback."""
        if self.state == "promoted":
            return self.breach(reason="operator_abort")
        with self._lock:
            if self.state not in ("shadowing", "loading", "promoting"):
                raise RuntimeError(
                    f"no active rollout to abort (state "
                    f"{self.state!r})")
            self.state = "aborted"
        self._unload(self.loaded)
        self.tele.emit("rollout_state", state="aborted",
                       source=self.source,
                       fingerprint=self.candidate_fp or "",
                       reason="operator")
        return self.status()

    # ------------------------------------------------------------- status

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.state,
                "source": self.source,
                "candidate_fingerprint": self.candidate_fp,
                "windows_green": self.windows_green,
                "windows_required": self.windows_required,
                "window": self._window,
                "mirrored": self._mirror_seen,
                "sampled": self._sampled,
                "dropped": self._dropped,
                "shadow_ok": self._ok_total,
                "shadow_failed": self._failed_total,
                "heads_eval_delta": self._heads_delta_cached,
                "flip_seconds": self._flip_seconds,
                "flipped": list(self.flipped),
                "baseline_burn": round(self.baseline_burn, 6),
                "gates": {
                    "sample_every": self.sample_every,
                    "window_requests": self.window_requests,
                    "shadow_parity_max": self.shadow_parity_max,
                    "slo_burn_delta_max": self.slo_burn_delta_max,
                    "heads_eval_drop_max": self.heads_eval_drop_max,
                    "auto_promote": self.auto_promote,
                },
            }
