"""The heads-eval promotion gate (ISSUE 20).

A trunk flip silently invalidates every registered head unless someone
proves the candidate's output space still carries them. This gate
re-runs the PR 7 eval harness (heads/eval.evaluate_heads) through BOTH
trunks over the same labeled batches and reports the worst-head score
drop; the rollout controller refuses promotion when the drop exceeds
`heads_eval_drop_max`.

Re-fingerprinting is deliberately deferred to `commit()`: evaluation
loads heads WITHOUT a fingerprint pin (the weights are what they are —
the question is how they score), so the registry stays untouched until
a promotion actually lands. `commit()` re-pins every frozen head to the
candidate fingerprint via `HeadRegistry.migrate_fingerprint` (unfrozen
heads get a recorded refusal — they co-adapted to the old trunk and
must be re-finetuned); `restore()` un-pins them after a rollback.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from proteinbert_tpu.heads.eval import evaluate_heads
from proteinbert_tpu.heads.registry import (HeadRegistry,
                                            HeadRegistryError,
                                            UnfrozenHeadError)

logger = logging.getLogger("proteinbert_tpu.rollout")


class HeadsEvalGate:
    """Callable gate: `gate()` → worst-head score delta (resident −
    candidate; positive = the candidate regressed), cached after the
    first call (the eval is the expensive part of a window close).
    `commit()` / `restore()` move the registry pins with an audit note.
    """

    def __init__(
        self,
        registry: HeadRegistry,
        model_cfg,
        batches_for,
        resident_params,
        candidate_params,
        resident_fp: str,
        candidate_fp: str,
        telemetry=None,
    ):
        self.registry = registry
        self.model_cfg = model_cfg
        self.batches_for = batches_for
        self.resident_params = resident_params
        self.candidate_params = candidate_params
        self.resident_fp = str(resident_fp)
        self.candidate_fp = str(candidate_fp)
        self.telemetry = telemetry
        self.delta: Optional[float] = None
        self.scores: Dict[str, Dict[str, float]] = {}
        self.migrated: List[str] = []
        self.refused: List[Dict[str, str]] = []

    # ------------------------------------------------------------- eval

    def _eligible_heads(self):
        """Every loadable head pinned to the resident trunk — frozen or
        not: the SCORE question applies to all of them (an unfrozen
        head that craters under the candidate should block promotion
        even though it will never be migrated)."""
        heads = []
        for meta in self.registry.list_heads():
            if meta.get("trunk_fingerprint") != self.resident_fp:
                continue
            try:
                heads.append(self.registry.load(meta["head_id"]))
            except HeadRegistryError as e:
                logger.warning("heads-eval gate skipping %s: %s",
                               meta["head_id"], e)
        return heads

    def __call__(self) -> float:
        if self.delta is not None:
            return self.delta
        heads = self._eligible_heads()
        if not heads:
            self.delta = 0.0
            return self.delta
        resident = evaluate_heads(self.resident_params, self.model_cfg,
                                  heads, self.batches_for,
                                  telemetry=self.telemetry)
        candidate = evaluate_heads(self.candidate_params, self.model_cfg,
                                   heads, self.batches_for,
                                   telemetry=self.telemetry)
        res_min = min(m["score"] for m in resident.values())
        cand_min = min(m["score"] for m in candidate.values())
        self.scores = {
            h.head_id: {"resident": float(resident[h.head_id]["score"]),
                        "candidate": float(candidate[h.head_id]["score"])}
            for h in heads
        }
        self.delta = float(res_min - cand_min)
        return self.delta

    # ----------------------------------------------------- pin movement

    def commit(self, note: str = "") -> List[Dict[str, str]]:
        """Permanently re-pin frozen heads to the candidate trunk.
        Returns the refusal records for heads that could not migrate
        (unfrozen — trained with the trunk unfrozen, so their weights
        are functions of the OLD trunk's interior, not its outputs)."""
        self.migrated = []
        self.refused = []
        for meta in self.registry.list_heads():
            if meta.get("trunk_fingerprint") != self.resident_fp:
                continue
            head_id = meta["head_id"]
            try:
                self.registry.migrate_fingerprint(
                    head_id, self.candidate_fp,
                    note=note or "rollout promotion "
                                 f"{self.resident_fp[:12]}… → "
                                 f"{self.candidate_fp[:12]}…")
                self.migrated.append(head_id)
            except UnfrozenHeadError as e:
                self.refused.append({"head_id": head_id,
                                     "reason": str(e)})
        return self.refused

    def restore(self, note: str = "") -> List[str]:
        """Rollback partner of commit(): re-pin every head commit()
        moved back to the (re-promoted) resident trunk."""
        restored = []
        for head_id in self.migrated:
            try:
                self.registry.migrate_fingerprint(
                    head_id, self.resident_fp,
                    note=note or "rollout rollback — restoring "
                                 f"{self.resident_fp[:12]}…")
                restored.append(head_id)
            except HeadRegistryError as e:  # pragma: no cover — a head
                # deleted mid-rollback is a registry race, not ours.
                logger.warning("rollback could not restore %s: %s",
                               head_id, e)
        self.migrated = [h for h in self.migrated if h not in restored]
        return restored
