"""Two-sided kernel fast-path accounting shared by the fused-block and
global-attention dispatches (ISSUE 13 satellite).

Each Pallas kernel family keeps one process-wide `KernelPathCounter`:
a count of kernel dispatch decisions keyed by `(path, reason)`, bumped
at TRACE time — once per traced block body, i.e. once per compiled
executable under `cfg.scan_blocks` (see kernels/fused_block.py module
docs for why that is the granularity the MFU question needs). Paths
are "pallas" (the fused kernel ran) and "reference" (the XLA
composition ran); the reason vocabulary labels WHY/WHAT (dense,
packed, segments, unsupported_shape, forced).

`register` lets a telemetry owner (serve/server.Server, or any trainer
holding a registry) mirror bumps into a registry counter
(`fused_kernel_path_total` / `attention_kernel_path_total`
`{path=,reason=}`) so fast-path COVERAGE — not just misses — is
visible in /metrics, Server.stats() and `pbt diagnose --serve`.

Reference dispatches warn ONCE per (reason, call-site shape): a server
that builds a reference executable for a NEW shape after a fused one
must still warn (the shape-keyed latch from ISSUE 10)."""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class KernelPathCounter:
    """Process-wide (path, reason) dispatch counter for one kernel
    family. `total` is a plain dict so callers can snapshot it with
    `dict(counter.total)` and diff across a trace (the bench gates)."""

    def __init__(self, kernel_name: str, metric_name: str,
                 log: Optional[logging.Logger] = None) -> None:
        self.kernel_name = kernel_name
        self.metric_name = metric_name
        # Warnings go through the OWNING module's logger (when given)
        # so per-family log handlers/filters keep working.
        self.logger = log or logger
        self.total: Dict[Tuple[str, str], int] = {}
        self._observers: List[Callable[[str, str], None]] = []
        self._warned: set = set()

    def register(self, cb: Callable[[str, str], None]) -> None:
        """`cb(path, reason)` is invoked on every dispatch bump (trace
        time), both fast-path and reference — the coverage feed."""
        self._observers.append(cb)

    def unregister(self, cb: Callable[[str, str], None]) -> None:
        if cb in self._observers:
            self._observers.remove(cb)

    def note(self, path: str, reason: str,
             shape: Optional[tuple] = None) -> None:
        """Record one kernel dispatch decision (trace time = once per
        executable). `shape` keys the one-time reference warning per
        (reason, call-site shape)."""
        if path not in ("pallas", "reference"):
            raise ValueError(f"path must be 'pallas' or 'reference', "
                             f"got {path!r}")
        self.total[(path, reason)] = self.total.get((path, reason), 0) + 1
        for cb in list(self._observers):
            cb(path, reason)
        if path != "reference":
            return
        warn_key = (reason, shape)
        if warn_key not in self._warned:
            self._warned.add(warn_key)
            self.logger.warning(
                "%s fell back to the XLA reference path (reason=%s, "
                "shape=%s) — this executable runs without the Pallas "
                "fast path; counted in %s{path=reference}",
                self.kernel_name, reason, shape, self.metric_name)
