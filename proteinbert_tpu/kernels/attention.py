"""Pallas TPU kernel: ragged global attention (ISSUE 13 tentpole).

The global track attends over the local track with one query set per
protein (ops/attention.py). On PACKED rows the masked-XLA form
(`packed_global_attention_apply`) materialises a (B, S, H, L) float32
score tensor and (B, S, L) boolean segment masks in HBM — per layer.
Following Ragged Paged Attention (PAPERS.md), this kernel consumes the
packed segment layout natively instead: per batch row, the whole
attention chain — Q/K/V projections, per-segment q·K scores, masked
softmax, weighted-V reduction — runs in one VMEM-resident pass, with
segment membership carried as the same (L, S) one-hot block the fused
local-track kernel rides (`_seg_tap_matmuls`' trick): the one-hot IS
the mask, applied in (L, S) score layout with no transposes and no
materialised (B, S, L)/(B, S, H, L) tensors.

Per head h (static loop — H is small), one grid step per batch row:

  K_h = tanh(local · wk[h])        (L, C) @ (C, k) -> (L, k)
  V_h = gelu(local · wv[h])        (L, C) @ (C, v) -> (L, v)
  q_h = tanh(global · wq[h])       (S, G) @ (G, k) -> (S, k)
  scores = K_h · q_hᵀ / sqrt(k)    MXU A·Bᵀ       -> (L, S) fp32
  masked softmax over L            one-hot mask, exact-0 cross-segment
  out_h = weightsᵀ · V_h           MXU Aᵀ·B       -> (S, v)

Heads concatenate to (S, G); empty segment slots are zeroed exactly as
the reference (`zero_empty`) so the (B, S, G) state stays leak-proof.
Cross-segment contributions are exact 0.0 (the -1e30 mask's exp
underflows to +0.0 in float32 and 0·v terms add exactly nothing), so
the leakage test asserts BIT-identity (tests/test_attention_kernel.py).

The DENSE (S=1) entry phrases plain pad-masked attention as the same
kernel with the pad mask as a one-column one-hot and `zero_empty=False`
(an all-pad row keeps the reference's uniform softmax), so the bucketed
serve path and unpacked training share the kernel with packed training
and ragged serving — no supported shape leaves the fast path.

Backward mirrors the fused block's remat contract: a custom VJP whose
backward recomputes the plain-JAX one-hot composition
(`attention_oh_reference`) and differentiates it, saving only
(params, local, global, one-hot).

Dispatch is guarded by `pallas_attention_supported` (VMEM-priced) with
the masked-XLA reference as fallback; every decision feeds the
two-sided `ATTN_PATH_TOTAL` / `attention_kernel_path_total{path=,
reason=}` counter (kernels/path_counter.py — same machinery as the
fused block's `fused_kernel_path_total`), and the shared
PBT_FORCE_REFERENCE_KERNEL debug override forces the reference path
for this kernel family too (reason=forced, read at trace time).
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from proteinbert_tpu.kernels import vmem_budget as _vb
from proteinbert_tpu.kernels.fused_block import (
    dequant_params,
    force_reference_requested,
    is_quant_leaf,
    weight_leaf,
)
from proteinbert_tpu.kernels.path_counter import KernelPathCounter
from proteinbert_tpu.kernels.vmem_budget import lanes as _lanes

Params = Dict[str, jax.Array]

# Two-sided fast-path accounting for the attention family (ISSUE 13):
# same trace-time granularity and reason vocabulary as the fused
# block's PATH_TOTAL —
#   pallas/packed     — the segment-aware kernel (packed rows)
#   pallas/dense      — the S=1 entry (bucketed serving / unpacked)
#   reference/segments          — packed shape with no VMEM plan
#   reference/unsupported_shape — dense shape with no VMEM plan
#   reference/forced            — PBT_FORCE_REFERENCE_KERNEL override
logger = logging.getLogger(__name__)

_COUNTER = KernelPathCounter("global-attention kernel",
                             "attention_kernel_path_total", log=logger)
ATTN_PATH_TOTAL: Dict[Tuple[str, str], int] = _COUNTER.total
# Shape-keyed one-time-warning latch (same contract as
# fused_block._FALLBACK_WARNED).
_FALLBACK_WARNED: set = _COUNTER._warned


def register_attention_path_observer(cb) -> None:
    """`cb(path, reason)` on every attention dispatch bump (trace
    time) — the coverage feed for `attention_kernel_path_total`."""
    _COUNTER.register(cb)


def unregister_attention_path_observer(cb) -> None:
    _COUNTER.unregister(cb)


def note_attention_path(path: str, reason: str,
                        shape: Optional[tuple] = None) -> None:
    _COUNTER.note(path, reason, shape)


def pallas_attention_supported(
    local_dim: int, global_dim: int, seq_len: int, max_segments: int,
    key_dim: int, num_heads: int, dtype: str = "bfloat16",
) -> bool:
    """Whether the attention kernel handles this shape+dtype within the
    VMEM budget (else the dispatch falls back to the masked-XLA
    reference). Unlike the fused local track, the weights here are tiny
    (H·(G+2C)·k-ish), so the whole ProteinBERT range — including the
    Large C=1024 — prices in; the budget is dominated by the (L, C)
    activation row and the per-head fp32 temporaries. `max_segments` is
    1 for the dense entry."""
    if not _vb.shape_prechecks(local_dim, seq_len, max_segments):
        return False
    if global_dim < 1 or global_dim % num_heads:
        return False
    item = _vb.itemsize(dtype)
    C, G, L, S, H, k = (local_dim, global_dim, seq_len, max_segments,
                        num_heads, key_dim)
    # Blocks whose index map varies with b are double-buffered by the
    # pipeline; weight blocks are whole (single buffer).
    row = 2 * L * C * item
    oh = 2 * L * _lanes(S) * item
    gseg = 2 * S * _lanes(G) * item
    out = 2 * S * _lanes(G) * item
    weights = _vb.attention_weight_bytes(C, G, k, H, item)
    temps = _vb.attention_temp_bytes(L, S, G, k, H)
    return _vb.fits(row, oh, gseg, out, weights, temps)


def attention_oh_reference(
    params: Params, local: jax.Array, global_seg: jax.Array,
    seg_oh: jax.Array, zero_empty: bool = True,
) -> jax.Array:
    """Plain-JAX ground truth of the attention kernel, phrased in the
    one-hot form the kernel consumes: `seg_oh` (B, L, S) is 1.0 where
    position l belongs to segment s AND is a real token (0.0 at pad,
    halo, and masked-out serving <pad> spans). Bit-compatible with
    `packed_global_attention_apply(params, local, global_, segment_ids,
    real_mask)` when seg_oh = onehot(segment_ids)·real_mask (the
    boolean mask `seg_oh > 0` reproduces its `seg_mask` exactly). The
    kernel's custom VJP rematerialises and differentiates THIS
    composition. `zero_empty=False` is the dense (S=1) entry's
    semantics: an all-masked row keeps the uniform softmax of
    `global_attention_apply` instead of a zero output."""
    dtype = local.dtype
    wq = params["wq"].astype(dtype)
    wk = params["wk"].astype(dtype)
    wv = params["wv"].astype(dtype)
    key_dim = wq.shape[-1]

    q = jnp.tanh(jnp.einsum("bsg,hgk->bshk", global_seg.astype(dtype), wq))
    k = jnp.tanh(jnp.einsum("blc,hck->bhlk", local, wk))
    v = jax.nn.gelu(jnp.einsum("blc,hcv->bhlv", local, wv))

    scores = jnp.einsum("bshk,bhlk->bshl", q, k) / jnp.sqrt(
        jnp.asarray(key_dim, dtype)
    )
    scores = scores.astype(jnp.float32)
    mask = jnp.transpose(seg_oh, (0, 2, 1)) > 0  # (B, S, L)
    scores = jnp.where(mask[:, :, None, :], scores, jnp.float32(-1e30))
    weights = jax.nn.softmax(scores, axis=-1).astype(dtype)

    out = jnp.einsum("bshl,bhlv->bshv", weights, v)
    if zero_empty:
        seg_exists = mask.any(axis=-1)  # (B, S)
        out = jnp.where(seg_exists[:, :, None, None], out,
                        jnp.zeros((), dtype))
    b, s, h, vd = out.shape
    return out.reshape(b, s, h * vd)


def _attention_body(
    x, oh, g, wq, wk, wv,
    *, key_dim, num_heads, zero_empty,
):
    """The whole VMEM-resident attention chain on VALUES: `x` (L, C)
    activations, `oh` (L, S) one-hot mask, `g` (S, G) global rows,
    `wq`/`wk`/`wv` the (H, ·, ·) projections (refs or arrays — only
    indexed). Factored out of `_attention_kernel` so the one-pass trunk
    kernel (kernels/one_pass.py, ISSUE 16) can feed it the local-track
    output it just computed WITHOUT an HBM round-trip. Returns the
    (S, G) output in x's dtype."""
    dtype = x.dtype
    inv_scale = 1.0 / jnp.sqrt(jnp.asarray(key_dim, jnp.float32))

    heads = []
    for h in range(num_heads):
        q_h = jnp.tanh(lax.dot_general(
            g, wq[h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dtype))  # (S, k)
        k_h = jnp.tanh(lax.dot_general(
            x, wk[h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dtype))  # (L, k)
        v_h = jax.nn.gelu(lax.dot_general(
            x, wv[h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dtype))  # (L, v)

        # (L, S) scores: position l's score against segment s's query —
        # A·Bᵀ on the MXU; the one-hot applies as-is, no transposes.
        scores = lax.dot_general(
            k_h, q_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * inv_scale
        scores = jnp.where(oh > 0, scores, jnp.float32(-1e30))
        # Masked softmax over L (axis 0): -1e30 entries underflow to
        # exact +0.0 after the max shift, so cross-segment V rows
        # contribute exact zeros to the weighted sum (bit-identity,
        # tests/test_attention_kernel.py). An all-masked column yields
        # the uniform 1/L weights of the XLA reference; the packed
        # entry zeroes those segments below.
        m = jnp.max(scores, axis=0, keepdims=True)
        e = jnp.exp(scores - m)
        w = (e / jnp.sum(e, axis=0, keepdims=True)).astype(dtype)
        # (S, v) = weightsᵀ · V — Aᵀ·B on the MXU.
        heads.append(lax.dot_general(
            w, v_h, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ))
    # Head-major assembly as Σ_h out_h @ E_h with E_h the static
    # (v, G) slot selector — a contraction, NOT a concatenate: the
    # SPMD partitioner handles sharded-operand contractions inside the
    # interpreted grid loop exactly (partial sums + all-reduce), while
    # a concatenate whose pieces ride an fsdp-sharded value-dim (the
    # ZeRO/fsdp state shards every param's last axis) was observed to
    # produce silently wrong lanes on jax 0.4.x CPU — the
    # tests/multidevice_packed_child.py zero_pallas parity gate pins
    # this. The selector matmuls are (S, v) @ (v, G) — negligible.
    v_dim = heads[0].shape[1]
    G = num_heads * v_dim
    eye = jnp.eye(v_dim, dtype=jnp.float32)
    out = None
    for h, out_h in enumerate(heads):
        sel = jnp.pad(eye, ((0, 0), (h * v_dim, G - (h + 1) * v_dim)))
        part = lax.dot_general(out_h, sel, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
        out = part if out is None else out + part  # (S, G) fp32
    if zero_empty:
        seg_exists = jnp.sum(oh.astype(jnp.float32), axis=0,
                             keepdims=True) > 0  # (1, S)
        out = jnp.where(seg_exists.reshape(-1, 1), out,
                        jnp.float32(0.0))
    return out.astype(dtype)


def _attention_kernel(
    x_ref, oh_ref, g_ref, wq_ref, wk_ref, wv_ref,
    *rest,
    key_dim, num_heads, zero_empty, quantized=False,
):
    out_ref = rest[-1]
    dtype = x_ref.dtype
    if quantized:
        # int8 projections + per-channel scales are VMEM-resident; the
        # q·scale dequant (fp32 multiply, cast to the activation dtype)
        # runs per grid step inside the kernel — bit-identical numerics
        # to the HLO dequant, int8 bytes on the HBM wire (ISSUE 16).
        wqs_ref, wks_ref, wvs_ref = rest[0], rest[1], rest[2]
        wq = (wq_ref[:].astype(jnp.float32) * wqs_ref[:]).astype(dtype)
        wk = (wk_ref[:].astype(jnp.float32) * wks_ref[:]).astype(dtype)
        wv = (wv_ref[:].astype(jnp.float32) * wvs_ref[:]).astype(dtype)
    else:
        wq, wk, wv = wq_ref, wk_ref, wv_ref
    out_ref[0] = _attention_body(
        x_ref[0], oh_ref[0], g_ref[0], wq, wk, wv,
        key_dim=key_dim, num_heads=num_heads, zero_empty=zero_empty)


def _pallas_attention_forward(
    params: Params, local: jax.Array, global_seg: jax.Array,
    seg_oh: jax.Array, zero_empty: bool, interpret: bool,
) -> jax.Array:
    B, L, C = local.shape
    S, G = global_seg.shape[1], global_seg.shape[2]
    dtype = local.dtype
    quantized = is_quant_leaf(params["wq"])
    if quantized:
        wq, wk, wv = (params[n]["q"] for n in ("wq", "wk", "wv"))
        # (H, k)/(H, v) scales reshaped to (H, 1, ·) so the in-kernel
        # q·scale multiply broadcasts per output channel exactly like
        # dequantize_params' scale[..., None, :].
        scales = tuple(
            params[n]["scale"][:, None, :].astype(jnp.float32)
            for n in ("wq", "wk", "wv"))
    else:
        wq = params["wq"].astype(dtype)  # (H, G, k)
        wk = params["wk"].astype(dtype)  # (H, C, k)
        wv = params["wv"].astype(dtype)  # (H, C, v)
        scales = ()
    H, _, key_dim = wq.shape

    def whole(a):
        return pl.BlockSpec(a.shape, lambda b: (0,) * a.ndim,
                            memory_space=pltpu.VMEM)

    # Projections dominate: 2·L·C·(k+v) + 2·S·G·k MACs per head, plus
    # the O(L·S·(k+v)) score/reduce matmuls.
    v_dim = G // H
    flops = 2 * B * H * (L * C * (key_dim + v_dim) + S * G * key_dim
                         + L * S * (key_dim + v_dim))
    cost = pl.CostEstimate(
        flops=flops,
        bytes_accessed=local.size * local.dtype.itemsize * 2,
        transcendentals=B * H * L * (key_dim + v_dim + S),
    )
    kernel = functools.partial(
        _attention_kernel, key_dim=key_dim, num_heads=H,
        zero_empty=zero_empty, quantized=quantized,
    )
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, L, C), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, L, S), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, G), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            whole(wq), whole(wk), whole(wv),
            *[whole(s) for s in scales],
        ],
        out_specs=pl.BlockSpec((1, S, G), lambda b: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, S, G), dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(local, seg_oh.astype(dtype), global_seg.astype(dtype), wq, wk, wv,
      *scales)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_attention(
    params: Params, local: jax.Array, global_seg: jax.Array,
    seg_oh: jax.Array, zero_empty: bool = True, interpret: bool = False,
) -> jax.Array:
    """Attention kernel under the fused block's memory contract:
    Pallas forward, rematerialised backward (the VJP recomputes
    `attention_oh_reference` and differentiates it, saving only
    params, local, global_seg, seg_oh)."""
    return _pallas_attention_forward(params, local, global_seg, seg_oh,
                                     zero_empty, interpret)


def _fwd_attention(params, local, global_seg, seg_oh,
                   zero_empty, interpret):
    y = _pallas_attention_forward(params, local, global_seg, seg_oh,
                                  zero_empty, interpret)
    return y, (params, local, global_seg, seg_oh)


def _bwd_attention(zero_empty, interpret, res, g):
    params, local, global_seg, seg_oh = res
    _, vjp = jax.vjp(
        lambda p, xx, gg, oo: attention_oh_reference(
            p, xx, gg, oo, zero_empty
        ),
        params, local, global_seg, seg_oh,
    )
    return vjp(g)


_fused_attention.defvjp(_fwd_attention, _bwd_attention)


def _segment_one_hot(segment_ids: jax.Array, S: int, dtype,
                     real_mask: Optional[jax.Array] = None) -> jax.Array:
    """(B, L) segment ids (+ optional real-token mask) → the (B, L, S)
    one-hot block the kernel consumes. Ids outside 1..S and masked-out
    positions get all-zero rows (= fully masked)."""
    oh = (segment_ids[..., None]
          == jnp.arange(1, S + 1, dtype=segment_ids.dtype)
          ).astype(dtype)
    if real_mask is not None:
        oh = oh * real_mask[..., None].astype(dtype)
    return oh


def fused_packed_attention(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    segment_ids: jax.Array,
    real_mask: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-segment global attention over a packed row — the dispatch
    that closes the attention leg of ROADMAP item 3: on supported
    shapes (`pallas_attention_supported`) the Pallas kernel consumes
    the segment layout natively; unsupported shapes (and the
    PBT_FORCE_REFERENCE_KERNEL debug override) take the masked-XLA
    reference `packed_global_attention_apply` — semantically
    identical. Same signature/semantics as the reference: `global_`
    is the per-segment (B, S, G) track, `real_mask` the ragged-serving
    real-token mask (None = every in-segment position is real).

    Every dispatch counts in `ATTN_PATH_TOTAL[(path, reason)]` at
    trace time: ("pallas", "packed") on the fast path, ("reference",
    "segments"|"forced") otherwise, with a one-time warning per
    (reason, shape)."""
    from proteinbert_tpu.ops.attention import packed_global_attention_apply

    B, L, C = local.shape
    S, G = global_.shape[1], global_.shape[2]
    H, _, key_dim = weight_leaf(params["wq"]).shape
    quantized = is_quant_leaf(params["wq"])
    shape_key = (B, L, C, S, G, str(jnp.dtype(local.dtype)))
    if force_reference_requested():
        reason = "forced"
    elif pallas_attention_supported(C, G, L, S, key_dim, H,
                                    local.dtype):
        reason = None
    else:
        reason = "segments"
    if reason is None:
        note_attention_path("pallas", "packed", shape_key)
        oh = _segment_one_hot(segment_ids, S, local.dtype, real_mask)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if quantized:
            # Inference-only int8 path: in-kernel dequant, no VJP
            # (quantized params carry no gradient contract).
            return _pallas_attention_forward(params, local, global_, oh,
                                             True, interpret)
        return _fused_attention(params, local, global_, oh, True,
                                interpret)
    note_attention_path("reference", reason, shape_key)
    if quantized:
        params = dequant_params(params)
    return packed_global_attention_apply(params, local, global_,
                                         segment_ids, real_mask)


def fused_global_attention(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    pad_mask: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """DENSE (unpacked) global attention through the same kernel: the
    (B, G) global track is an S=1 segment set and the pad mask a
    one-column one-hot, so bucketed serving and unpacked training
    share the packed kernel's executable shape family. All-pad rows
    keep the reference's uniform softmax (`zero_empty=False`) — a
    batch-class padding row must stay bit-compatible with
    `global_attention_apply`. Fallback reasons: "unsupported_shape"
    (no VMEM plan), "forced" (debug override)."""
    from proteinbert_tpu.ops.attention import global_attention_apply

    B, L, C = local.shape
    G = global_.shape[-1]
    H, _, key_dim = weight_leaf(params["wq"]).shape
    quantized = is_quant_leaf(params["wq"])
    shape_key = (B, L, C, 1, G, str(jnp.dtype(local.dtype)))
    if force_reference_requested():
        reason = "forced"
    elif pallas_attention_supported(C, G, L, 1, key_dim, H,
                                    local.dtype):
        reason = None
    else:
        reason = "unsupported_shape"
    if reason is None:
        note_attention_path("pallas", "dense", shape_key)
        if pad_mask is None:
            oh = jnp.ones((B, L, 1), local.dtype)
        else:
            oh = pad_mask[..., None].astype(local.dtype)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if quantized:
            out = _pallas_attention_forward(params, local,
                                            global_[:, None, :], oh,
                                            False, interpret)
        else:
            out = _fused_attention(params, local, global_[:, None, :],
                                   oh, False, interpret)
        return out.reshape(B, G)
    note_attention_path("reference", reason, shape_key)
    if quantized:
        params = dequant_params(params)
    return global_attention_apply(params, local, global_, pad_mask)
