"""Pallas TPU kernel: fused local-track block (SURVEY §7 stage 8).

The local (per-residue) track of a ProteinBERT block is the FLOPs and
bandwidth hot spot (SURVEY §3.4; reference modules.py:201-217):

    h  = x + gelu(narrow_conv(x)) + gelu(wide_conv(x)) + broadcast
    x1 = LN(h)
    y  = LN(x1 + gelu(dense(x1)))

Composed from jax.nn ops, XLA materialises several (B, L, C) intermediates
in HBM. This kernel computes the whole chain in one VMEM-resident pass:

- each 'SAME' dilated conv is lowered to K shifted (TL, C) @ (C, C)
  matmuls on the MXU (an implicit GEMM: tap t of a kernel-size-K,
  dilation-d conv contributes x[l + (t-(K-1)/2)·d] @ W[t]);
- the input is zero-padded by the widest halo (20 rows for k=9, d=5) on
  the host side so every tap is a static in-VMEM slice;
- conv accumulation and LayerNorm statistics are float32; matmul inputs
  stay in the activation dtype (bfloat16 on TPU) so the MXU runs native;
- grid is (B, L/TL); the full padded row sits in VMEM and is re-fetched
  only when the batch index changes (the L-tile axis iterates fastest).

Backward: `fused_local_track` is a jax.custom_vjp whose backward pass
recomputes the plain-JAX composition (`local_track_reference`) and
differentiates it — i.e. the kernel behaves like a rematerialised
(jax.checkpoint) block, saving only (params, x, broadcast).

PACKED rows (data/packing.py) run a SEGMENT-AWARE variant of the same
kernel (`fused_local_track_segments`, ISSUE 10): each tap's shifted
matmul operand is masked by segment-id equality inside the block (a
one-hot lane reduction — exact 0.0 across boundaries, the
`_segment_conv` semantics), and the per-position global→local
broadcast is gathered from each position's own segment IN the kernel
as a (TL, S) @ (S, C) one-hot matmul, so the packed fast path never
materialises the (B, L, C) broadcast tensor. Beyond C = MAX_PALLAS_DIM
a channel-tiled SEGMENT variant runs (`_fused_segment_kernel_tiled`,
ISSUE 13) — ProteinBERT-Large packed rows stay on the fast path.
Shapes neither plan fits fall back to the XLA reference path, counted
in `PATH_TOTAL` / `fused_kernel_path_total{path=,reason=}`.

VMEM budget: weights dominate at 2·K·C² + C² activation-dtype bytes
(~10 MB at C=512 bf16). Up to C = 512 the whole weight set resides in
VMEM and the grid is (B, L/TL). Beyond that (ProteinBERT-Large C=1024)
a CHANNEL-TILED variant runs instead: the grid grows a third, fastest
axis over output-channel tiles of width TC — each step loads only one
conv's (K, C, TC) weight slice and accumulates its (TL, TC) slice of
    gelu(narrow) + gelu(wide)
into a persistent (TL, C) fp32 VMEM scratch (TPU grid steps run
sequentially, so scratch carries across the c-axis); the final c step
adds x + broadcast over the FULL row (static slices only — Mosaic
cannot lower lax.dynamic_slice on materialized values, so nothing may
column-slice x/broadcast by the dynamic grid index) and then computes
LN → dense (+GELU, residual) → LN. The grid order adapts to VMEM:
when an fp32 scratch covering the full (L, C) row set fits, the L-tile
axis runs FASTEST so each conv weight slice stays resident across the
whole L sweep (weight HBM traffic O(weights), not O(B·L/TL·weights));
otherwise the per-row order runs with phase fastest. Shapes the tiled
plan cannot fit either way fall back to the XLA path automatically.

OFFICIAL SCOPE (rounds 2-3, measured on v5e — BASELINE.md "Kernel
same-batch verdict"): a tiled plan exists only at C <= 512 (the full
weight set VMEM-resident), but even there the full train step LOSES to
the remat_policy="convs" XLA path at every measured batch (0.478 vs
0.547 MFU at B=256/L=512, round 3) — its only full-step win was over
NON-remat XLA, a configuration no preset uses. At C = 1024 every
schedule is weight-bandwidth-bound (38 MB of conv weights vs 16 MB
VMEM) and the measured kernel is 0.88-1.03x XLA. Every preset
therefore trains on the XLA path with remat_policy="convs"; the kernel
remains an opt-in (`model.use_pallas`) validated for correctness —
including the Mosaic-only resident-order semantics — by
tests/tpu_kernel_child.py on real hardware, and is the reference
implementation for fused-local-track schedules at sharded
(seq-parallel) shapes.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from proteinbert_tpu.kernels.path_counter import KernelPathCounter
from proteinbert_tpu.kernels import vmem_budget as _vb
from proteinbert_tpu.kernels.vmem_budget import (  # noqa: F401
    LANE as _LANE,
    MAX_PALLAS_DIM,
    MAX_TILED_DIM,
    VMEM_BUDGET as _VMEM_BUDGET,
)

logger = logging.getLogger(__name__)

Params = Dict[str, jax.Array]

# Two-sided fast-path accounting (ISSUE 10 satellite): process-wide
# count of kernel dispatch decisions keyed by (path, reason), bumped at
# TRACE time — once per traced BLOCK BODY. Under cfg.scan_blocks (every
# preset) the N blocks share one traced body, so that is once per
# EXECUTABLE — exactly the granularity the MFU question needs ("how
# many of my compiled shapes run the fast path"), not once per step;
# with scan_blocks=False an executable contributes num_blocks bumps
# (all on the same path — the ratio, and the zero-miss gates, are
# unaffected). Paths are
# "pallas" (the fused kernel ran) and "reference" (the XLA composition
# ran); reasons label WHY/WHAT:
#   pallas/dense      — the unpacked fused kernel
#   pallas/packed     — the segment-aware fused kernel (packed rows)
#   reference/segments          — packed shape the segment kernel has
#                                 no VMEM plan for (C > MAX_PALLAS_DIM,
#                                 non-lane-aligned C, ...)
#   reference/unsupported_shape — dense shape outside pallas_supported
#   reference/forced            — PBT_FORCE_REFERENCE_KERNEL debug
#                                 override (read at trace time)
# `register_path_observer` lets a telemetry owner (serve/server.Server,
# or any trainer holding a registry) mirror bumps into a registry
# counter (`fused_kernel_path_total{path=,reason=}`) so fast-path
# COVERAGE — not just misses — is visible in /metrics, Server.stats()
# and `pbt diagnose --serve`. The mechanics (dict, observers, one-time
# shape-keyed reference warning) live in the shared KernelPathCounter
# (kernels/path_counter.py) so the attention kernel's counter cannot
# drift from this one; the module-level API here is kept verbatim.
_COUNTER = KernelPathCounter("fused local-track kernel",
                             "fused_kernel_path_total", log=logger)
PATH_TOTAL: Dict[Tuple[str, str], int] = _COUNTER.total
# The shape-keyed one-time-warning latch, exposed for tests that reset
# specific (reason, shape) keys to make warning counts deterministic.
_FALLBACK_WARNED: set = _COUNTER._warned

# Debug override: force every fused_local_track_segments dispatch onto
# the XLA reference path. Read at TRACE time — set it before the first
# call of a given (shape, config), or the cached fused executable wins.
FORCE_REFERENCE_ENV = "PBT_FORCE_REFERENCE_KERNEL"


def force_reference_requested() -> bool:
    """Whether the debug override is ON. Parsed like the other PBT_*
    flags: "0"/"false"/empty mean off — a `=0` export must not
    silently force the slow path."""
    return os.environ.get(FORCE_REFERENCE_ENV, "").strip().lower() not in (
        "", "0", "false")


def register_path_observer(cb: Callable[[str, str], None]) -> None:
    """`cb(path, reason)` is invoked on every dispatch bump (trace
    time), both fast-path and reference — the coverage feed."""
    _COUNTER.register(cb)


def unregister_path_observer(cb: Callable[[str, str], None]) -> None:
    _COUNTER.unregister(cb)


def note_kernel_path(path: str, reason: str,
                     shape: Optional[tuple] = None) -> None:
    """Record one kernel dispatch decision (trace time = once per
    executable). `shape` keys the one-time reference warning per
    (reason, call-site shape)."""
    _COUNTER.note(path, reason, shape)

# The VMEM constants (MAX_PALLAS_DIM, MAX_TILED_DIM, _LANE,
# _VMEM_BUDGET) are owned by kernels/vmem_budget.py since ISSUE 16 and
# re-exported above under their historical names.


def _gelu(x):
    return jax.nn.gelu(x)


# ------------------------------------------- int8 weight leaves (ISSUE 16)
# parallel/quant.quantize_params turns every >= 2-D float leaf into
# {"q": int8, "scale": fp32} (symmetric per-output-channel, scale
# reduced over axis -2). The kernel dispatches accept those leaves
# directly so the quantized serving arm loads int8 weights into VMEM
# and dequantizes per-tile INSIDE the kernel. The predicates are
# duplicated from parallel/quant (they must match bit-for-bit) because
# kernels/ cannot import parallel/ without a cycle.


def is_quant_leaf(x) -> bool:
    """Whether `x` is a quantize_params leaf ({"q": int8, "scale":
    fp32}) rather than a plain weight array."""
    return isinstance(x, dict) and set(x) == {"q", "scale"}


def weight_leaf(x):
    """The array carrying a (possibly quantized) weight's SHAPE."""
    return x["q"] if is_quant_leaf(x) else x


def dequant_leaf(x):
    """HLO dequant of one quant leaf — the exact
    parallel/quant.dequantize_params formula, used on kernel paths
    that do not dequantize in-kernel (XLA reference fallbacks and the
    channel-tiled variants)."""
    if is_quant_leaf(x):
        return x["q"].astype(jnp.float32) * x["scale"][..., None, :]
    return x


def dequant_params(params):
    """Dequantize every quant leaf of a param subtree in HLO."""
    return jax.tree.map(dequant_leaf, params, is_leaf=is_quant_leaf)


def local_track_reference(
    params: Params, x: jax.Array, broadcast: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
) -> jax.Array:
    """Plain-JAX local track, the kernel's semantic ground truth (and its
    recompute path in the backward pass). Mirrors models/proteinbert.py
    block_apply's local half (reference modules.py:201-217)."""
    from proteinbert_tpu.ops.layers import conv1d_apply, dense_apply, layer_norm_apply

    narrow = _gelu(conv1d_apply(params["narrow_conv"], x, dilation=narrow_dilation))
    wide = _gelu(conv1d_apply(params["wide_conv"], x, dilation=wide_dilation))
    h = layer_norm_apply(
        params["local_ln1"], x + narrow + wide + broadcast[:, None, :]
    )
    return layer_norm_apply(
        params["local_ln2"],
        h + _gelu(dense_apply(params["local_dense"], h)),
    )


def _segment_conv(
    p: Params, x: jax.Array, segment_ids: jax.Array, dilation: int
) -> jax.Array:
    """'SAME' dilated conv whose taps NEVER cross a segment boundary.

    Lowered as K shifted (B, L, C) @ (C, C) matmuls (the same implicit-
    GEMM decomposition the Pallas kernel uses, _tap_matmuls): tap t of a
    kernel-size-K, dilation-d conv reads x[l + (t-(K-1)/2)·d]; here that
    shifted operand is ZEROED wherever its segment id differs from the
    center position's (or the center is pad), so a contribution from
    another packed protein is an exact 0.0 — multiplication by a zero
    mask, not a subtraction — which is what lets the leakage test assert
    BIT-identity across segments (tests/test_packing.py). FLOPs equal
    the plain conv (K·C² MACs/position either way).
    """
    kernel = p["kernel"].astype(x.dtype)
    taps = kernel.shape[0]
    L = x.shape[1]
    # 'SAME' halo, asymmetric for even kernels exactly like
    # conv1d_apply's padding="SAME" (lo = total//2, extra on the right).
    total = (taps - 1) * dilation
    lo = total // 2
    xp = jnp.pad(x, ((0, 0), (lo, total - lo), (0, 0)))
    sp = jnp.pad(segment_ids, ((0, 0), (lo, total - lo)))
    real = segment_ids > 0
    acc = None
    for t in range(taps):
        off = t * dilation
        xs = lax.slice_in_dim(xp, off, off + L, axis=1)
        ss = lax.slice_in_dim(sp, off, off + L, axis=1)
        mask = ((ss == segment_ids) & real).astype(x.dtype)[..., None]
        part = (xs * mask) @ kernel[t]
        acc = part if acc is None else acc + part
    # Same remat tag as conv1d_apply so model.remat_policy="convs" also
    # bites on the packed path; inert without remat.
    return checkpoint_name(acc + p["bias"].astype(x.dtype), "conv_out")


def local_track_segment_reference(
    params: Params, x: jax.Array, broadcast_pos: jax.Array,
    segment_ids: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
) -> jax.Array:
    """Segment-aware local track for PACKED rows (data/packing.py).

    Same dataflow as local_track_reference with two changes: the convs
    are boundary-masked (`_segment_conv`), and `broadcast_pos` is
    already per-POSITION (B, L, C) — each position receives its own
    segment's global→local projection (gathered by the model), not one
    row-wide vector.
    """
    from proteinbert_tpu.ops.layers import dense_apply, layer_norm_apply

    narrow = _gelu(_segment_conv(params["narrow_conv"], x, segment_ids,
                                 narrow_dilation))
    wide = _gelu(_segment_conv(params["wide_conv"], x, segment_ids,
                               wide_dilation))
    h = layer_norm_apply(
        params["local_ln1"], x + narrow + wide + broadcast_pos
    )
    return layer_norm_apply(
        params["local_ln2"],
        h + _gelu(dense_apply(params["local_dense"], h)),
    )


def local_track_segment_oh_reference(
    params: Params, x: jax.Array, broadcast_seg: jax.Array,
    seg_oh: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
) -> jax.Array:
    """Plain-JAX ground truth of the SEGMENT kernel, phrased in terms
    of the one-hot segment matrix `seg_oh` (B, L, S) — the form the
    kernel consumes — instead of integer segment ids. Tap masks are
    one-hot dot products (Σ_s oh[l]·oh[l+off], exact 0.0/1.0, so a
    cross-segment contribution is an exact zero like `_segment_conv`'s)
    and the own-segment global→local gather is the matmul
    `seg_oh @ broadcast_seg` (a pad position's all-zero one-hot row
    receives exact 0.0). Bit-compatible with gathering (B, L, C)
    broadcast rows and calling `local_track_segment_reference` for
    segment ids in 0..S (the packer contract). The fused kernel's
    backward differentiates THIS composition (rematerialised, like the
    dense kernel's backward differentiates local_track_reference)."""
    from proteinbert_tpu.ops.layers import dense_apply, layer_norm_apply

    oh = seg_oh.astype(x.dtype)
    L = x.shape[1]

    def conv(p, dilation):
        kernel = p["kernel"].astype(x.dtype)
        taps = kernel.shape[0]
        total = (taps - 1) * dilation
        lo = total // 2
        xp = jnp.pad(x, ((0, 0), (lo, total - lo), (0, 0)))
        ohp = jnp.pad(oh, ((0, 0), (lo, total - lo), (0, 0)))
        acc = None
        for t in range(taps):
            off = t * dilation
            xs = lax.slice_in_dim(xp, off, off + L, axis=1)
            ohs = lax.slice_in_dim(ohp, off, off + L, axis=1)
            mask = jnp.sum(oh * ohs, axis=-1, keepdims=True)
            part = (xs * mask.astype(x.dtype)) @ kernel[t]
            acc = part if acc is None else acc + part
        # Same remat tag as _segment_conv/conv1d_apply; inert w/o remat.
        return checkpoint_name(acc + p["bias"].astype(x.dtype), "conv_out")

    narrow = _gelu(conv(params["narrow_conv"], narrow_dilation))
    wide = _gelu(conv(params["wide_conv"], wide_dilation))
    broadcast_pos = jnp.einsum("bls,bsc->blc", oh,
                               broadcast_seg.astype(x.dtype))
    h = layer_norm_apply(
        params["local_ln1"], x + narrow + wide + broadcast_pos
    )
    return layer_norm_apply(
        params["local_ln2"],
        h + _gelu(dense_apply(params["local_dense"], h)),
    )


def gather_segment_broadcast(broadcast_seg: jax.Array,
                             segment_ids: jax.Array) -> jax.Array:
    """(B, S, C) per-segment broadcast + (B, L) segment ids → (B, L, C)
    per-position broadcast, exact 0.0 at pad — the materialised gather
    the fused segment kernel folds into its block (shared by the
    model's non-pallas packed path and the reference fallback here)."""
    idx = jnp.clip(segment_ids - 1, 0)[..., None]
    broadcast_pos = jnp.take_along_axis(broadcast_seg, idx, axis=1)
    return jnp.where((segment_ids > 0)[..., None], broadcast_pos,
                     jnp.zeros((), broadcast_pos.dtype))


def fused_local_track_segments(
    params: Params, x: jax.Array, broadcast_seg: jax.Array,
    segment_ids: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    interpret: bool = False,
) -> jax.Array:
    """Segment-aware fused local track for PACKED rows — the dispatch
    point that closes ROADMAP item 2: on supported shapes
    (`pallas_segments_supported`) the Pallas kernel runs with
    cross-segment boundary masks folded into its tap matmuls AND the
    per-position global→local broadcast gathered from each position's
    own segment INSIDE the block (a one-hot matmul on the MXU), so the
    model never materialises the (B, L, C) broadcast tensor on the
    fast path. Unsupported shapes (and the PBT_FORCE_REFERENCE_KERNEL
    debug override) take the XLA reference path — semantically
    identical, boundary-masked.

    Args:
      broadcast_seg: (B, S, C) PER-SEGMENT projected global vectors
        (gelu(dense(global)) per segment) — NOT the per-position
        (B, L, C) gather.
      segment_ids: (B, L) int, 0 = pad, 1..S = packed protein index
        (ids above S are treated as pad — the packer never emits them).

    Every dispatch counts in `PATH_TOTAL[(path, reason)]` at trace time
    (once per executable): ("pallas", "packed") on the fast path,
    ("reference", "segments"|"forced") otherwise, with a one-time
    warning per (reason, shape). Backward matches the unpacked fused
    path's memory behavior: a custom VJP that recomputes the reference
    composition (saving only params/x/broadcast/one-hot), with the
    conv_out remat tag intact inside the recompute."""
    B, L, C = x.shape
    S = broadcast_seg.shape[1]
    quantized = is_quant_leaf(params["narrow_conv"]["kernel"])
    nk = weight_leaf(params["narrow_conv"]["kernel"])
    wk = weight_leaf(params["wide_conv"]["kernel"])
    shape_key = (B, L, C, S, str(jnp.dtype(x.dtype)))
    if force_reference_requested():
        reason = "forced"
    elif pallas_segments_supported(
            C, L, S, x.dtype, nk.shape[0], wk.shape[0],
            wide_dilation, narrow_dilation):
        reason = None
    else:
        reason = "segments"
    if reason is None:
        note_kernel_path("pallas", "packed", shape_key)
        seg_oh = (segment_ids[..., None]
                  == jnp.arange(1, S + 1, dtype=segment_ids.dtype)
                  ).astype(x.dtype)
        if quantized:
            if C <= MAX_PALLAS_DIM:
                # int8 weights dequantize per-tile IN the kernel
                # (inference-only: the quantized arm never
                # differentiates, so the custom-VJP wrapper is skipped).
                return _pallas_segments_forward(
                    params, x, broadcast_seg, seg_oh,
                    narrow_dilation, wide_dilation, interpret)
            # Channel-tiled range: HLO dequant, still the Pallas path.
            params = dequant_params(params)
        return _fused_segments(params, x, broadcast_seg, seg_oh,
                               narrow_dilation, wide_dilation, interpret)
    note_kernel_path("reference", reason, shape_key)
    if quantized:
        params = dequant_params(params)
    broadcast_pos = gather_segment_broadcast(broadcast_seg, segment_ids)
    return local_track_segment_reference(
        params, x, broadcast_pos, segment_ids, narrow_dilation,
        wide_dilation
    )


def track_halo(params: Params, narrow_dilation: int = 1,
               wide_dilation: int = 5) -> int:
    """Context rows each side a shard needs for exact conv results (20 for
    the reference k=9/d=5 geometry)."""
    nt = params["narrow_conv"]["kernel"].shape[0]
    wt = params["wide_conv"]["kernel"].shape[0]
    return max((nt - 1) // 2 * narrow_dilation, (wt - 1) // 2 * wide_dilation)


def local_track_valid_reference(
    params: Params, xh: jax.Array, broadcast: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
) -> jax.Array:
    """Local track on a PRE-HALOED shard: `xh` is (B, L + 2·halo, C) whose
    first/last `halo` rows are real neighbor context (sequence
    parallelism, parallel/halo.py) rather than zeros; output is the (B, L,
    C) center. Semantically equals slicing rows [halo, halo+L) out of
    local_track_reference applied to the neighbor-stitched sequence."""
    from proteinbert_tpu.ops.layers import dense_apply, layer_norm_apply

    H = track_halo(params, narrow_dilation, wide_dilation)
    L = xh.shape[1] - 2 * H

    def valid_conv(p, dilation):
        y = lax.conv_general_dilated(
            xh, p["kernel"].astype(xh.dtype), window_strides=(1,),
            padding="VALID", rhs_dilation=(dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        # Same remat tag as conv1d_apply so the "convs" policy also
        # bites on the sequence-parallel XLA path (parallel/seq_parallel
        # wraps this body in jax.checkpoint); inert everywhere else.
        return checkpoint_name(y + p["bias"].astype(xh.dtype), "conv_out")

    # VALID output row m covers input rows starting at m; center row l of
    # a 'SAME' conv corresponds to window start l + H - ((k-1)/2)·d.
    n_off = H - (params["narrow_conv"]["kernel"].shape[0] - 1) // 2 * narrow_dilation
    w_off = H - (params["wide_conv"]["kernel"].shape[0] - 1) // 2 * wide_dilation
    narrow = _gelu(valid_conv(params["narrow_conv"], narrow_dilation)
                   [:, n_off:n_off + L])
    wide = _gelu(valid_conv(params["wide_conv"], wide_dilation)
                 [:, w_off:w_off + L])
    h = layer_norm_apply(
        params["local_ln1"],
        xh[:, H:H + L] + narrow + wide + broadcast[:, None, :],
    )
    return layer_norm_apply(
        params["local_ln2"],
        h + _gelu(dense_apply(params["local_dense"], h)),
    )


def _tap_matmuls(window, kernel, taps, dilation, halo, tile):
    """Σ_t window[halo + (t-(K-1)/2)·d : …+tile] @ kernel[t]  (fp32 acc).

    `window` is (tile + 2·halo, C) in activation dtype; every slice is
    static so XLA/Mosaic sees `taps` plain MXU matmuls.
    """
    center = (taps - 1) // 2
    acc = None
    for t in range(taps):
        off = halo + (t - center) * dilation
        part = lax.dot_general(
            window[off:off + tile],
            kernel[t],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = part if acc is None else acc + part
    return acc


def _layer_norm_f32(x32, scale, bias, eps=1e-5):
    mean = x32.mean(axis=-1, keepdims=True)
    var = x32.var(axis=-1, keepdims=True)
    return (x32 - mean) * lax.rsqrt(var + eps) * scale + bias


def _finish_row(h32, s1_ref, b1_ref, dk_ref, db_ref, s2_ref, b2_ref, dtype):
    """LN → dense(+GELU, residual) → LN tail shared by both kernel
    variants (they must never diverge numerically)."""
    x1 = _layer_norm_f32(h32, s1_ref[0], b1_ref[0]).astype(dtype)
    d = lax.dot_general(
        x1, dk_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + db_ref[0].astype(jnp.float32)
    h2 = x1.astype(jnp.float32) + _gelu(d)
    return _layer_norm_f32(h2, s2_ref[0], b2_ref[0]).astype(dtype)


def _fused_kernel(
    x_ref, bcast_ref,
    nk_ref, nb_ref, wk_ref, wb_ref,
    s1_ref, b1_ref, dk_ref, db_ref, s2_ref, b2_ref,
    out_ref,
    *, tile, halo, narrow_taps, wide_taps, narrow_dilation, wide_dilation,
):
    j = pl.program_id(1)
    dtype = x_ref.dtype
    # Window of padded rows covering this tile plus both halos.
    window = x_ref[0, pl.ds(j * tile, tile + 2 * halo), :]
    x_center = window[halo:halo + tile].astype(jnp.float32)

    narrow = _tap_matmuls(window, nk_ref[:], narrow_taps, narrow_dilation, halo, tile)
    narrow = _gelu(narrow + nb_ref[0].astype(jnp.float32))
    wide = _tap_matmuls(window, wk_ref[:], wide_taps, wide_dilation, halo, tile)
    wide = _gelu(wide + wb_ref[0].astype(jnp.float32))

    # bcast is shaped (B, 1, C) outside so this program's (1, 1, C) block
    # satisfies Mosaic's last-two-dims tiling rule (a (1, C) slice of a
    # (B, C) array does not, nor does a dynamic row-select).
    h = x_center + narrow + wide + bcast_ref[0, 0].astype(jnp.float32)[None, :]
    out_ref[0] = _finish_row(h, s1_ref, b1_ref, dk_ref, db_ref,
                             s2_ref, b2_ref, dtype)


def _fused_kernel_tiled(
    x_ref, bcast_ref,
    cw_ref, cb_ref,
    s1_ref, b1_ref, dk_ref, db_ref, s2_ref, b2_ref,
    out_ref,
    h_scratch,
    *, tile, halo, taps, narrow_dilation, wide_dilation, c_tiles,
    resident,
):
    """Channel-tiled body, one of two grid orders (see _plan_tiled):

    - resident=False: grid (B, L/tile, c_tiles, 2), phase fastest,
      scratch covers ONE (tile, C) row. Conv weight slices are refetched
      for every L tile and batch row.
    - resident=True: grid (B, c_tiles, 2, L/tile), L-tile fastest,
      scratch covers the FULL (L, C) row set of one batch entry. The
      conv weight slice's block index varies only with the slow (c,
      phase) axes, so Mosaic's pipeline keeps each slice resident across
      the whole L sweep — weight HBM traffic drops from
      O(B · L/tile · weights) to O(weights) per call. Preferred
      whenever the full-row scratch fits the VMEM budget.

    The two convs are stacked on a leading axis of `cw_ref`/`cb_ref` and
    visited as grid phases so only ONE conv's (taps, C, TC) weight slice
    is resident per step (the conv weights dominate VMEM at C=1024; see
    _plan_tiled). Phase 0 seeds this c tile's columns of the fp32
    scratch row with gelu(narrow); phase 1 adds gelu(wide); the final
    (c, phase) step adds x + broadcast over the FULL row — static
    slices only; Mosaic cannot lower lax.dynamic_slice on materialized
    values, so nothing may column-slice `window`/`bcast` by the dynamic
    grid index `c` — then finishes (LN → dense residual → LN) and
    writes the output block.
    """
    if resident:
        c = pl.program_id(1)
        phase = pl.program_id(2)
        j = pl.program_id(3)
        rsel = pl.ds(j * tile, tile)
    else:
        j = pl.program_id(1)
        c = pl.program_id(2)
        phase = pl.program_id(3)
        rsel = slice(None)
    dtype = x_ref.dtype
    window = x_ref[0, pl.ds(j * tile, tile + 2 * halo), :]

    tc = cw_ref.shape[-1]

    @pl.when(phase == 0)
    def _narrow():
        conv = _tap_matmuls(window, cw_ref[0], taps, narrow_dilation,
                            halo, tile)
        h_scratch[rsel, pl.ds(c * tc, tc)] = _gelu(
            conv + cb_ref[0, 0].astype(jnp.float32))

    @pl.when(phase == 1)
    def _wide():
        conv = _tap_matmuls(window, cw_ref[0], taps, wide_dilation,
                            halo, tile)
        h_scratch[rsel, pl.ds(c * tc, tc)] += _gelu(
            conv + cb_ref[0, 0].astype(jnp.float32))

    @pl.when((c == c_tiles - 1) & (phase == 1))
    def _finish():
        h32 = (h_scratch[rsel, :]
               + window[halo:halo + tile].astype(jnp.float32)
               + bcast_ref[0, 0].astype(jnp.float32)[None, :])
        out_ref[0] = _finish_row(h32, s1_ref, b1_ref,
                                 dk_ref, db_ref, s2_ref, b2_ref, dtype)


def _fused_segment_kernel_tiled(
    x_ref, oh_ref, bcast_ref,
    cw_ref, cb_ref,
    s1_ref, b1_ref, dk_ref, db_ref, s2_ref, b2_ref,
    out_ref,
    h_scratch,
    *, tile, halo, taps, narrow_dilation, wide_dilation, c_tiles,
    resident,
):
    """Channel-tiled SEGMENT body (ISSUE 13 second leg): the same two
    grid orders and phase layout as `_fused_kernel_tiled`, with the
    segment one-hot folded in exactly like the weights-resident segment
    kernel — every tap's shifted operand is masked by the one-hot lane
    reduction (`_seg_tap_matmuls`), and the finish step's broadcast is
    the own-segment (TL, S) @ (S, C) one-hot gather instead of the
    row-wide vector. The one-hot row block and per-segment broadcast
    ride the b-varying specs (priced in `_plan_tiled(max_segments=)`);
    nothing column-slices them by the dynamic grid index, so the
    static-slice rule the dense tiled kernel obeys holds here too."""
    if resident:
        c = pl.program_id(1)
        phase = pl.program_id(2)
        j = pl.program_id(3)
        rsel = pl.ds(j * tile, tile)
    else:
        j = pl.program_id(1)
        c = pl.program_id(2)
        phase = pl.program_id(3)
        rsel = slice(None)
    dtype = x_ref.dtype
    window = x_ref[0, pl.ds(j * tile, tile + 2 * halo), :]
    oh_window = oh_ref[0, pl.ds(j * tile, tile + 2 * halo), :]

    tc = cw_ref.shape[-1]

    @pl.when(phase == 0)
    def _narrow():
        conv = _seg_tap_matmuls(window, oh_window, cw_ref[0], taps,
                                narrow_dilation, halo, tile)
        h_scratch[rsel, pl.ds(c * tc, tc)] = _gelu(
            conv + cb_ref[0, 0].astype(jnp.float32))

    @pl.when(phase == 1)
    def _wide():
        conv = _seg_tap_matmuls(window, oh_window, cw_ref[0], taps,
                                wide_dilation, halo, tile)
        h_scratch[rsel, pl.ds(c * tc, tc)] += _gelu(
            conv + cb_ref[0, 0].astype(jnp.float32))

    @pl.when((c == c_tiles - 1) & (phase == 1))
    def _finish():
        bcast_pos = lax.dot_general(
            oh_window[halo:halo + tile], bcast_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        h32 = (h_scratch[rsel, :]
               + window[halo:halo + tile].astype(jnp.float32)
               + bcast_pos)
        out_ref[0] = _finish_row(h32, s1_ref, b1_ref,
                                 dk_ref, db_ref, s2_ref, b2_ref, dtype)


def _plan_tiled(C: int, seq_len: int, dtype,
                narrow_taps: int = 9, wide_taps: int = 9,
                wide_dilation: int = 5, resident: bool = False,
                max_segments: int = 0):
    """(c_tile, l_tile) of the widest-channel plan that fits the VMEM
    budget, or (0, 0).

    The model counts what Mosaic actually keeps resident: blocks whose
    index map varies over the grid are DOUBLE-buffered (conv weight/bias
    slices vary with (phase, c); the input row, broadcast, and output
    blocks vary with b/j), plus the fp32 scratch and the finish step's
    (tile, C) temporaries. The phase split exists exactly so the
    double-buffered conv residency is one conv, not two. A narrower L
    tile is tried before a narrower channel tile — it shrinks the
    scratch/out/finish terms without adding weight refetches.

    `resident=True` prices the weights-resident grid order (L-tile axis
    fastest, see _fused_kernel_tiled): the only difference is the fp32
    scratch covering the full (seq_len, C) row set instead of one
    (tile, C) row, so a resident plan always fits wherever it exists —
    the per-row plan is the superset and remains the support gate.

    `max_segments > 0` prices the SEGMENT variant (ISSUE 13): the
    (Lp, S) one-hot row block (lane-padded, varies with b → double-
    buffered), the (S, C) per-segment broadcast block replacing the
    (1, C) row vector, and the per-tap mask temporaries."""
    if narrow_taps != wide_taps:
        return 0, 0  # the stacked phase layout needs equal tap counts
    itemsize = jnp.dtype(dtype).itemsize
    halo = max((narrow_taps - 1) // 2, (wide_taps - 1) // 2 * wide_dilation)
    # Mosaic pads the lane dim UP to the next multiple of 128.
    lanes = -(-max_segments // _LANE) * _LANE if max_segments else 0
    for tc in (512, 256, 128):
        if C % tc:
            continue
        for tile in (_pick_tile(seq_len), 128):
            if seq_len % tile:
                continue
            conv_w = 2 * narrow_taps * C * tc * itemsize  # one conv, 2 bufs
            dense = C * C * itemsize                      # whole, 1 buffer
            row = 2 * (seq_len + 2 * halo) * C * itemsize  # varies with b
            out = 2 * tile * C * itemsize                 # varies with (b, j)
            scratch = (seq_len if resident else tile) * C * 4  # fp32 h
            finish = tile * C * (4 + 4 + 4 + itemsize)    # h32, d, h2 f32 + x1
            seg = 0
            if max_segments:
                seg = (2 * (seq_len + 2 * halo) * lanes * itemsize  # one-hot
                       + 2 * max_segments * C * itemsize            # bcast
                       + tile * lanes * 4)                          # masks
            if (conv_w + dense + row + out + scratch + finish + seg
                    <= _VMEM_BUDGET):
                return tc, tile
    return 0, 0


def _pallas_forward(
    params: Params, x: jax.Array, broadcast: jax.Array,
    narrow_dilation: int, wide_dilation: int, interpret: bool,
    prehaloed: bool = False,
) -> jax.Array:
    nk = params["narrow_conv"]["kernel"]
    wk = params["wide_conv"]["kernel"]
    narrow_taps, wide_taps = nk.shape[0], wk.shape[0]
    halo = max((narrow_taps - 1) // 2 * narrow_dilation,
               (wide_taps - 1) // 2 * wide_dilation)

    dtype = x.dtype
    if prehaloed:
        # x rows already carry `halo` rows of real neighbor context on
        # each side (sequence parallelism); output is the center.
        B, Lp, C = x.shape
        L = Lp - 2 * halo
        x_padded = x
    else:
        B, L, C = x.shape
        x_padded = jnp.pad(x, ((0, 0), (halo, halo), (0, 0)))
        Lp = L + 2 * halo

    tile = _pick_tile(L)

    def vec(p):  # (C,) fp32 vector → (1, C) activation-dtype VMEM block
        return p.reshape(1, C)

    ln1, ln2, dn = params["local_ln1"], params["local_ln2"], params["local_dense"]
    inputs = (
        x_padded,
        broadcast.astype(dtype).reshape(B, 1, C),
        nk.astype(dtype), vec(params["narrow_conv"]["bias"]),
        wk.astype(dtype), vec(params["wide_conv"]["bias"]),
        vec(ln1["scale"]), vec(ln1["bias"]),
        dn["kernel"].astype(dtype), vec(dn["bias"]),
        vec(ln2["scale"]), vec(ln2["bias"]),
    )
    flops_conv = 2 * B * L * C * C * (narrow_taps + wide_taps + 1)
    cost = pl.CostEstimate(
        flops=flops_conv,
        bytes_accessed=x.size * x.dtype.itemsize * 2,
        transcendentals=3 * B * L * C,
    )

    if C <= MAX_PALLAS_DIM:
        grid = (B, L // tile)

        row_spec = pl.BlockSpec((1, Lp, C), lambda b, j: (b, 0, 0),
                                memory_space=pltpu.VMEM)

        def whole(a):
            return pl.BlockSpec(a.shape, lambda b, j: (0,) * a.ndim,
                                memory_space=pltpu.VMEM)

        bcast_spec = pl.BlockSpec((1, 1, C), lambda b, j: (b, 0, 0),
                                  memory_space=pltpu.VMEM)

        kernel = functools.partial(
            _fused_kernel, tile=tile, halo=halo,
            narrow_taps=narrow_taps, wide_taps=wide_taps,
            narrow_dilation=narrow_dilation, wide_dilation=wide_dilation,
        )
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[row_spec, bcast_spec] + [whole(a) for a in inputs[2:]],
            out_specs=pl.BlockSpec((1, tile, C), lambda b, j: (b, j, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B, L, C), dtype),
            cost_estimate=cost,
            interpret=interpret,
        )(*inputs)

    # Channel-tiled variant for C > MAX_PALLAS_DIM (module docstring).
    # Prefer the weights-resident grid order; fall back to the per-row
    # scratch order when the full-row scratch doesn't fit (long L).
    resident = True
    tc, tile = _plan_tiled(C, L, dtype, narrow_taps, wide_taps,
                           wide_dilation, resident=True)
    if tc == 0:
        resident = False
        tc, tile = _plan_tiled(C, L, dtype, narrow_taps, wide_taps,
                               wide_dilation)
    if tc == 0:  # callers gate via pallas_supported; belt and braces
        raise ValueError(f"no VMEM plan for C={C}, L={L}")
    c_tiles = C // tc
    if resident:
        grid = (B, c_tiles, 2, L // tile)  # L tiles fastest

        def imap(f):  # block index from (c, phase, j)
            return lambda b, c, p, j: f(b, c, p, j)
    else:
        grid = (B, L // tile, c_tiles, 2)  # phase (narrow/wide) fastest

        def imap(f):
            return lambda b, j, c, p: f(b, c, p, j)

    # Both convs stacked on a leading phase axis so each grid step loads
    # ONE conv's weight slice (see _plan_tiled).
    conv_w = jnp.stack([inputs[2], inputs[4]])          # (2, taps, C, C)
    conv_b = jnp.stack([inputs[3], inputs[5]])          # (2, 1, C)

    row_spec = pl.BlockSpec((1, Lp, C), imap(lambda b, c, p, j: (b, 0, 0)),
                            memory_space=pltpu.VMEM)
    bcast_spec = pl.BlockSpec((1, 1, C), imap(lambda b, c, p, j: (b, 0, 0)),
                              memory_space=pltpu.VMEM)

    def whole4(a):
        return pl.BlockSpec(a.shape, lambda *_: (0,) * a.ndim,
                            memory_space=pltpu.VMEM)

    conv_w_spec = pl.BlockSpec((1, narrow_taps, C, tc),
                               imap(lambda b, c, p, j: (p, 0, 0, c)),
                               memory_space=pltpu.VMEM)
    conv_b_spec = pl.BlockSpec((1, 1, tc),
                               imap(lambda b, c, p, j: (p, 0, c)),
                               memory_space=pltpu.VMEM)

    in_specs = [
        row_spec, bcast_spec, conv_w_spec, conv_b_spec,
        *[whole4(a) for a in inputs[6:]],
    ]
    kernel = functools.partial(
        _fused_kernel_tiled, tile=tile, halo=halo, taps=narrow_taps,
        narrow_dilation=narrow_dilation, wide_dilation=wide_dilation,
        c_tiles=c_tiles, resident=resident,
    )
    if resident:
        # The kernel only writes output on the final (c, phase) sweep, but
        # Mosaic copies an output block to HBM on every block-index
        # CHANGE — with j fastest a plain (b, j, 0) map would stream the
        # (uninitialized) block 2·c_tiles times per row. Pinning the index
        # to (b, 0, 0) during non-finish sweeps makes it change only
        # across the finish sweep's j steps, so exactly the finished
        # blocks are written, once each.
        def out_map(b, c, p, j):
            return (b, jnp.where((c == c_tiles - 1) & (p == 1), j, 0), 0)
    else:
        out_map = imap(lambda b, c, p, j: (b, j, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, tile, C), out_map,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L, C), dtype),
        scratch_shapes=[pltpu.VMEM((L if resident else tile, C),
                                   jnp.float32)],
        cost_estimate=cost,
        interpret=interpret,
    )(*inputs[:2], conv_w, conv_b, *inputs[6:])


def _pick_tile(L: int) -> int:
    for cand in (512, 256, 128):
        if L > cand and L % cand == 0:
            return cand
    return L


def pallas_supported(
    local_dim: int, seq_len: int, dtype: str = "bfloat16",
    narrow_taps: int = 9, wide_taps: int = 9, wide_dilation: int = 5,
) -> bool:
    """Whether the fused kernel handles this shape+dtype within the VMEM
    budget (else the model falls back to the XLA path). Up to
    MAX_PALLAS_DIM the whole weight set must fit; beyond it the
    channel-tiled plan (_plan_tiled) must find a tile width. Note
    `seq_len` is the PER-SHARD length the kernel actually sees — under
    sequence parallelism a long global L divides down to supportable
    shards."""
    if not _vb.shape_prechecks(local_dim, seq_len):
        return False
    item = _vb.itemsize(dtype)
    C = local_dim
    halo = max((narrow_taps - 1) // 2, (wide_taps - 1) // 2 * wide_dilation)
    tile = _pick_tile(seq_len)
    if C > MAX_PALLAS_DIM:
        return _plan_tiled(C, seq_len, dtype, narrow_taps, wide_taps,
                           wide_dilation)[0] > 0
    weights = _vb.track_weight_bytes(C, narrow_taps, wide_taps, item)
    row = (seq_len + 2 * halo) * C * item
    temps = _vb.track_temp_bytes(tile, C)
    return _vb.fits(weights, row, temps)


# ------------------------------------------------ segment-aware kernel
# The packed fast path (ISSUE 10 tentpole). Same implicit-GEMM tap
# decomposition as _fused_kernel, with two additions folded into the
# same VMEM-resident block:
#
# - every tap's shifted operand is masked by SEGMENT-ID EQUALITY before
#   its matmul: the one-hot segment matrix rides next to the input row
#   as a (Lp, S) block, and tap t's mask is the lane reduction
#   Σ_s oh[l]·oh[l + off] — exact 0.0/1.0 (multiplication by a zero
#   mask, not a subtraction), the same semantics `_segment_conv` proves
#   bit-level isolation with in tests/test_packing.py;
# - the per-position global→local broadcast is gathered from each
#   position's OWN segment inside the kernel as the one-hot matmul
#   (TL, S) @ (S, C) on the MXU (the operator-fusion-for-inference
#   move, PAPERS.md) — the model passes the tiny per-segment (B, S, C)
#   tensor and never materialises the (B, L, C) gather on this path.
#
# Scope: C <= MAX_PALLAS_DIM runs with the whole weight set
# VMEM-resident; C > MAX_PALLAS_DIM runs the channel-tiled segment
# variant (`_fused_segment_kernel_tiled` — same one-hot operands over
# the tiled grid, ISSUE 13), so ProteinBERT-Large packed shapes no
# longer fall back with reason="segments".


def _seg_tap_matmuls(window, oh_window, kernel, taps, dilation, halo,
                     tile):
    """Σ_t (window[..] · mask_t) @ kernel[t] with mask_t[l] =
    Σ_s oh[l]·oh[l + (t-(K-1)/2)·d] (fp32 acc). `window` is
    (tile + 2·halo, C); `oh_window` the matching (tile + 2·halo, S)
    one-hot rows — all-zero at pad/halo, so masks embed the
    center-is-real check for free."""
    center = (taps - 1) // 2
    oh_center = oh_window[halo:halo + tile]
    acc = None
    for t in range(taps):
        off = halo + (t - center) * dilation
        xs = window[off:off + tile]
        same = jnp.sum(oh_center * oh_window[off:off + tile],
                       axis=-1, keepdims=True)
        part = lax.dot_general(
            xs * same.astype(xs.dtype),
            kernel[t],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = part if acc is None else acc + part
    return acc


def _fused_segment_kernel(
    x_ref, oh_ref, bcast_ref,
    nk_ref, nb_ref, wk_ref, wb_ref,
    s1_ref, b1_ref, dk_ref, db_ref, s2_ref, b2_ref,
    *rest,
    tile, halo, narrow_taps, wide_taps, narrow_dilation, wide_dilation,
    quantized=False,
):
    out_ref = rest[-1]
    j = pl.program_id(1)
    dtype = x_ref.dtype
    if quantized:
        # int8 weights + per-channel scales are VMEM-resident; the
        # per-tile dequant (q·scale in fp32, cast to the activation
        # dtype) reproduces the HLO dequant's numerics bit-for-bit
        # (ISSUE 16 second leg), but HBM ships int8 bytes.
        nks_ref, wks_ref, dks_ref = rest[0], rest[1], rest[2]
        nk = (nk_ref[:].astype(jnp.float32) * nks_ref[:]).astype(dtype)
        wk = (wk_ref[:].astype(jnp.float32) * wks_ref[:]).astype(dtype)
        dk = (dk_ref[:].astype(jnp.float32) * dks_ref[:]).astype(dtype)
    else:
        nk, wk, dk = nk_ref, wk_ref, dk_ref
    window = x_ref[0, pl.ds(j * tile, tile + 2 * halo), :]
    oh_window = oh_ref[0, pl.ds(j * tile, tile + 2 * halo), :]
    x_center = window[halo:halo + tile].astype(jnp.float32)

    narrow = _seg_tap_matmuls(window, oh_window, nk[:], narrow_taps,
                              narrow_dilation, halo, tile)
    narrow = _gelu(narrow + nb_ref[0].astype(jnp.float32))
    wide = _seg_tap_matmuls(window, oh_window, wk[:], wide_taps,
                            wide_dilation, halo, tile)
    wide = _gelu(wide + wb_ref[0].astype(jnp.float32))

    # Own-segment broadcast gather as a one-hot matmul: a pad
    # position's all-zero one-hot row receives exact 0.0.
    bcast_pos = lax.dot_general(
        oh_window[halo:halo + tile], bcast_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    h = x_center + narrow + wide + bcast_pos
    out_ref[0] = _finish_row(h, s1_ref, b1_ref, dk, db_ref,
                             s2_ref, b2_ref, dtype)


def pallas_segments_supported(
    local_dim: int, seq_len: int, max_segments: int,
    dtype: str = "bfloat16",
    narrow_taps: int = 9, wide_taps: int = 9,
    wide_dilation: int = 5, narrow_dilation: int = 1,
) -> bool:
    """Whether the SEGMENT kernel handles this packed shape+dtype
    within the VMEM budget (else fused_local_track_segments falls back
    to the XLA reference path with reason="segments"). Versus
    `pallas_supported`: taps must be odd (the symmetric-halo tap
    layout), and the budget additionally prices the (Lp, S) one-hot
    row block (lane-padded to 128 on TPU) and the (S, C) per-segment
    broadcast block. Beyond MAX_PALLAS_DIM the channel-tiled SEGMENT
    plan (`_plan_tiled(max_segments=)`, ISSUE 13) must find a tile
    width — ProteinBERT-Large C=1024 packed rows run the fast path."""
    if not _vb.shape_prechecks(local_dim, seq_len, max_segments):
        return False
    if narrow_taps % 2 == 0 or wide_taps % 2 == 0:
        return False
    if local_dim > MAX_PALLAS_DIM:
        return _plan_tiled(local_dim, seq_len, dtype, narrow_taps,
                           wide_taps, wide_dilation,
                           max_segments=max_segments)[0] > 0
    item = _vb.itemsize(dtype)
    C = local_dim
    halo = max((narrow_taps - 1) // 2 * narrow_dilation,
               (wide_taps - 1) // 2 * wide_dilation)
    tile = _pick_tile(seq_len)
    Lp = seq_len + 2 * halo
    weights = _vb.track_weight_bytes(C, narrow_taps, wide_taps, item)
    row = Lp * C * item
    # Mosaic pads the one-hot's lane dim UP to the next multiple of 128.
    oh_row = Lp * _vb.lanes(max_segments) * item
    bcast = max_segments * C * item
    temps = (_vb.track_temp_bytes(tile, C)
             + tile * _vb.lanes(max_segments) * 4)
    return _vb.fits(weights, row, oh_row, bcast, temps)


def _pallas_segments_forward(
    params: Params, x: jax.Array, broadcast_seg: jax.Array,
    seg_oh: jax.Array,
    narrow_dilation: int, wide_dilation: int, interpret: bool,
) -> jax.Array:
    nk = params["narrow_conv"]["kernel"]
    wk = params["wide_conv"]["kernel"]
    quantized = is_quant_leaf(nk)
    narrow_taps = weight_leaf(nk).shape[0]
    wide_taps = weight_leaf(wk).shape[0]
    halo = max((narrow_taps - 1) // 2 * narrow_dilation,
               (wide_taps - 1) // 2 * wide_dilation)
    B, L, C = x.shape
    S = seg_oh.shape[-1]
    dtype = x.dtype
    x_padded = jnp.pad(x, ((0, 0), (halo, halo), (0, 0)))
    oh_padded = jnp.pad(seg_oh.astype(dtype),
                        ((0, 0), (halo, halo), (0, 0)))
    Lp = L + 2 * halo
    tile = _pick_tile(L)

    def vec(p):  # (C,) fp32 vector → (1, C) activation-dtype VMEM block
        return p.reshape(1, C)

    ln1, ln2, dn = params["local_ln1"], params["local_ln2"], params["local_dense"]
    if quantized:
        # int8 weight operands ride as-is; scales are reshaped so the
        # in-kernel q·scale multiply broadcasts per output channel
        # exactly like dequantize_params' scale[..., None, :].
        nk_w, wk_w, dk_w = nk["q"], wk["q"], dn["kernel"]["q"]
        scales = (nk["scale"][:, None, :].astype(jnp.float32),
                  wk["scale"][:, None, :].astype(jnp.float32),
                  dn["kernel"]["scale"].reshape(1, C).astype(jnp.float32))
    else:
        nk_w, wk_w = nk.astype(dtype), wk.astype(dtype)
        dk_w = dn["kernel"].astype(dtype)
        scales = ()
    inputs = (
        x_padded, oh_padded, broadcast_seg.astype(dtype),
        nk_w, vec(params["narrow_conv"]["bias"]),
        wk_w, vec(params["wide_conv"]["bias"]),
        vec(ln1["scale"]), vec(ln1["bias"]),
        dk_w, vec(dn["bias"]),
        vec(ln2["scale"]), vec(ln2["bias"]),
    )
    # Masks add one (TL, S) VPU reduction per tap; the broadcast gather
    # adds one (TL, S)@(S, C) matmul — negligible next to the conv
    # FLOPs, so the cost model stays the dense kernel's.
    flops_conv = 2 * B * L * C * C * (narrow_taps + wide_taps + 1)
    cost = pl.CostEstimate(
        flops=flops_conv,
        bytes_accessed=x.size * x.dtype.itemsize * 2,
        transcendentals=3 * B * L * C,
    )
    if C <= MAX_PALLAS_DIM:
        grid = (B, L // tile)
        row_spec = pl.BlockSpec((1, Lp, C), lambda b, j: (b, 0, 0),
                                memory_space=pltpu.VMEM)
        oh_spec = pl.BlockSpec((1, Lp, S), lambda b, j: (b, 0, 0),
                               memory_space=pltpu.VMEM)
        bcast_spec = pl.BlockSpec((1, S, C), lambda b, j: (b, 0, 0),
                                  memory_space=pltpu.VMEM)

        def whole(a):
            return pl.BlockSpec(a.shape, lambda b, j: (0,) * a.ndim,
                                memory_space=pltpu.VMEM)

        kernel = functools.partial(
            _fused_segment_kernel, tile=tile, halo=halo,
            narrow_taps=narrow_taps, wide_taps=wide_taps,
            narrow_dilation=narrow_dilation, wide_dilation=wide_dilation,
            quantized=quantized,
        )
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[row_spec, oh_spec, bcast_spec]
                     + [whole(a) for a in inputs[3:]]
                     + [whole(a) for a in scales],
            out_specs=pl.BlockSpec((1, tile, C), lambda b, j: (b, j, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B, L, C), dtype),
            cost_estimate=cost,
            interpret=interpret,
        )(*inputs, *scales)

    if quantized:
        # The channel-tiled variant keeps its HLO dequant (the
        # dispatch dequantizes before reaching it, docs/serving.md).
        raise ValueError(
            f"in-kernel int8 dequant has no channel-tiled plan "
            f"(C={C} > {MAX_PALLAS_DIM}); dequantize first")

    # Channel-tiled SEGMENT variant for C > MAX_PALLAS_DIM (ISSUE 13
    # second leg — ProteinBERT-Large packed rows). Same grid orders as
    # the dense tiled kernel: prefer weights-resident, fall back to the
    # per-row scratch order when the full-row fp32 scratch doesn't fit.
    resident = True
    tc, tile = _plan_tiled(C, L, dtype, narrow_taps, wide_taps,
                           wide_dilation, resident=True, max_segments=S)
    if tc == 0:
        resident = False
        tc, tile = _plan_tiled(C, L, dtype, narrow_taps, wide_taps,
                               wide_dilation, max_segments=S)
    if tc == 0:  # callers gate via pallas_segments_supported
        raise ValueError(f"no segment VMEM plan for C={C}, L={L}, S={S}")
    c_tiles = C // tc
    if resident:
        grid = (B, c_tiles, 2, L // tile)  # L tiles fastest

        def imap(f):  # block index from (c, phase, j)
            return lambda b, c, p, j: f(b, c, p, j)
    else:
        grid = (B, L // tile, c_tiles, 2)  # phase (narrow/wide) fastest

        def imap(f):
            return lambda b, j, c, p: f(b, c, p, j)

    # Both convs stacked on a leading phase axis so each grid step
    # loads ONE conv's weight slice (see _plan_tiled).
    conv_w = jnp.stack([inputs[3], inputs[5]])          # (2, taps, C, C)
    conv_b = jnp.stack([inputs[4], inputs[6]])          # (2, 1, C)

    row_spec = pl.BlockSpec((1, Lp, C), imap(lambda b, c, p, j: (b, 0, 0)),
                            memory_space=pltpu.VMEM)
    oh_spec = pl.BlockSpec((1, Lp, S), imap(lambda b, c, p, j: (b, 0, 0)),
                           memory_space=pltpu.VMEM)
    bcast_spec = pl.BlockSpec((1, S, C), imap(lambda b, c, p, j: (b, 0, 0)),
                              memory_space=pltpu.VMEM)

    def whole4(a):
        return pl.BlockSpec(a.shape, lambda *_: (0,) * a.ndim,
                            memory_space=pltpu.VMEM)

    conv_w_spec = pl.BlockSpec((1, narrow_taps, C, tc),
                               imap(lambda b, c, p, j: (p, 0, 0, c)),
                               memory_space=pltpu.VMEM)
    conv_b_spec = pl.BlockSpec((1, 1, tc),
                               imap(lambda b, c, p, j: (p, 0, c)),
                               memory_space=pltpu.VMEM)

    in_specs = [
        row_spec, oh_spec, bcast_spec, conv_w_spec, conv_b_spec,
        *[whole4(a) for a in inputs[7:]],
    ]
    kernel = functools.partial(
        _fused_segment_kernel_tiled, tile=tile, halo=halo,
        taps=narrow_taps,
        narrow_dilation=narrow_dilation, wide_dilation=wide_dilation,
        c_tiles=c_tiles, resident=resident,
    )
    if resident:
        # Same out-map pinning as the dense tiled kernel: the output
        # block index changes only across the finish sweep's j steps,
        # so exactly the finished blocks are written, once each.
        def out_map(b, c, p, j):
            return (b, jnp.where((c == c_tiles - 1) & (p == 1), j, 0), 0)
    else:
        out_map = imap(lambda b, c, p, j: (b, j, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, tile, C), out_map,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L, C), dtype),
        scratch_shapes=[pltpu.VMEM((L if resident else tile, C),
                                   jnp.float32)],
        cost_estimate=cost,
        interpret=interpret,
    )(*inputs[:3], conv_w, conv_b, *inputs[7:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_segments(
    params: Params, x: jax.Array, broadcast_seg: jax.Array,
    seg_oh: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    interpret: bool = False,
) -> jax.Array:
    """Segment kernel under the same memory contract as
    fused_local_track: Pallas forward, rematerialised backward (the
    VJP recomputes local_track_segment_oh_reference — conv_out remat
    tag intact — saving only params, x, broadcast_seg, seg_oh)."""
    return _pallas_segments_forward(params, x, broadcast_seg, seg_oh,
                                    narrow_dilation, wide_dilation,
                                    interpret)


def _fwd_segments(params, x, broadcast_seg, seg_oh,
                  narrow_dilation, wide_dilation, interpret):
    y = _pallas_segments_forward(params, x, broadcast_seg, seg_oh,
                                 narrow_dilation, wide_dilation, interpret)
    return y, (params, x, broadcast_seg, seg_oh)


def _bwd_segments(narrow_dilation, wide_dilation, interpret, res, g):
    params, x, broadcast_seg, seg_oh = res
    _, vjp = jax.vjp(
        lambda p, xx, bb, oo: local_track_segment_oh_reference(
            p, xx, bb, oo, narrow_dilation, wide_dilation
        ),
        params, x, broadcast_seg, seg_oh,
    )
    return vjp(g)


_fused_segments.defvjp(_fwd_segments, _bwd_segments)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_local_track(
    params: Params, x: jax.Array, broadcast: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    interpret: bool = False,
) -> jax.Array:
    """Fused local-track block: Pallas forward, rematerialised backward.

    Args:
      params: the local-track subset of a block's params (narrow_conv,
        wide_conv, local_ln1, local_dense, local_ln2).
      x: (B, L, C) activations.
      broadcast: (B, C) — the already-projected global→local vector
        (gelu(dense(global)) in block_apply).
    """
    return _pallas_forward(params, x, broadcast,
                           narrow_dilation, wide_dilation, interpret)


def _fwd(params, x, broadcast, narrow_dilation, wide_dilation, interpret):
    y = _pallas_forward(params, x, broadcast,
                        narrow_dilation, wide_dilation, interpret)
    return y, (params, x, broadcast)


def _bwd(narrow_dilation, wide_dilation, interpret, res, g):
    params, x, broadcast = res
    _, vjp = jax.vjp(
        lambda p, xx, bb: local_track_reference(
            p, xx, bb, narrow_dilation, wide_dilation
        ),
        params, x, broadcast,
    )
    return vjp(g)


fused_local_track.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_local_track_valid(
    params: Params, xh: jax.Array, broadcast: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    interpret: bool = False,
) -> jax.Array:
    """Pre-haloed variant for sequence parallelism: `xh` (B, L+2·halo, C)
    carries real neighbor rows (parallel/halo.halo_exchange); returns the
    (B, L, C) center. Ground truth: local_track_valid_reference."""
    return _pallas_forward(params, xh, broadcast,
                           narrow_dilation, wide_dilation, interpret,
                           prehaloed=True)


def _fwd_valid(params, xh, broadcast, narrow_dilation, wide_dilation, interpret):
    y = _pallas_forward(params, xh, broadcast,
                        narrow_dilation, wide_dilation, interpret,
                        prehaloed=True)
    return y, (params, xh, broadcast)


def _bwd_valid(narrow_dilation, wide_dilation, interpret, res, g):
    params, xh, broadcast = res
    _, vjp = jax.vjp(
        lambda p, xx, bb: local_track_valid_reference(
            p, xx, bb, narrow_dilation, wide_dilation
        ),
        params, xh, broadcast,
    )
    return vjp(g)


fused_local_track_valid.defvjp(_fwd_valid, _bwd_valid)
