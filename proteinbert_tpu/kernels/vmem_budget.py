"""Shared VMEM pricing for the Pallas kernel guards (ISSUE 16).

`pallas_supported`, `pallas_segments_supported`,
`pallas_attention_supported` and the one-pass trunk guard
(`pallas_onepass_supported`, kernels/one_pass.py) all answer the same
question — does this shape's working set fit the per-core VMEM the
kernel is allowed to plan for — and before this module each carried its
own copy of the arithmetic (lane round-up, itemsize lookup, the weight
and temporary byte formulas). The primitives live here once; each
guard keeps its OWN composition of them, because the kernels genuinely
differ in what they hold resident (the budget test pins every guard's
decisions on the existing shape grid, tests/test_vmem_budget.py).

Conventions the formulas encode (docs/performance.md):
- Mosaic pads the lane (last) dim of a VMEM block UP to the next
  multiple of 128 (`lanes`) — a (L, 4) one-hot block occupies
  (L, 128) lanes;
- blocks whose index map varies with the batch grid axis are
  double-buffered by the pipeline (x2); whole-array weight blocks are
  single-buffered;
- fp32 temporaries price at 4 bytes regardless of activation dtype.
"""

from __future__ import annotations

import jax.numpy as jnp

# Largest feature dim whose weights fit the VMEM budget whole; larger
# dims need a channel-tiled plan (fused_block._plan_tiled).
MAX_PALLAS_DIM = 512
MAX_TILED_DIM = 2048  # upper bound for the channel-tiled variants
LANE = 128  # TPU lane width; C must be a multiple for clean tiling
VMEM_BUDGET = 13 * 1024 * 1024  # per-core VMEM the kernels plan within


def lanes(n: int) -> int:
    """Mosaic pads the lane (last) dim of a VMEM block up to the next
    multiple of 128 — a ROUND-UP, not a floor (a 192-lane block
    occupies 256 lanes)."""
    return -(-n // LANE) * LANE


def itemsize(dtype) -> int:
    return jnp.dtype(dtype).itemsize


def fits(*byte_costs: int) -> bool:
    """Whether the summed costs fit the shared per-core budget."""
    return sum(byte_costs) <= VMEM_BUDGET


def track_weight_bytes(local_dim: int, narrow_taps: int, wide_taps: int,
                       item: int) -> int:
    """Whole-resident local-track weight set: both conv stacks plus the
    (C, C) dense kernel (biases and LN vectors are noise)."""
    return (narrow_taps + wide_taps + 1) * local_dim * local_dim * item


def attention_weight_bytes(local_dim: int, global_dim: int, key_dim: int,
                           num_heads: int, item: int) -> int:
    """Whole-resident attention projections wq (H, G, k), wk (H, C, k),
    wv (H, C, v) with the lane round-up on each last dim."""
    v_dim = global_dim // num_heads
    return (num_heads * global_dim * lanes(key_dim)
            + num_heads * local_dim * lanes(key_dim)
            + num_heads * local_dim * lanes(v_dim)) * item


def attention_temp_bytes(seq_len: int, max_segments: int, global_dim: int,
                         key_dim: int, num_heads: int) -> int:
    """Live fp32 temporaries of one attention head iteration: K, V,
    scores + exp copy, plus the accumulating (S, G) output."""
    v_dim = global_dim // num_heads
    return (seq_len * lanes(key_dim) + seq_len * lanes(v_dim)
            + 2 * seq_len * lanes(max_segments)
            + max_segments * lanes(global_dim)) * 4


def track_temp_bytes(tile: int, local_dim: int) -> int:
    """Live fp32 temporaries of one local-track tile: narrow, wide and
    the accumulated residual row."""
    return 3 * tile * local_dim * 4


def shape_prechecks(local_dim: int, seq_len: int,
                    max_segments: int = 1) -> bool:
    """The structural preconditions shared by every kernel family:
    lane-aligned C within the tiled ceiling, enough rows for a Mosaic
    sublane tile, a positive segment count."""
    return not (local_dim % LANE or local_dim > MAX_TILED_DIM
                or seq_len < 8 or max_segments < 1)
