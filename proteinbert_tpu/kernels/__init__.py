"""Pallas TPU kernels (SURVEY §7 stage 8)."""

from proteinbert_tpu.kernels.fused_block import (
    FALLBACK_TOTAL,
    MAX_PALLAS_DIM,
    fused_local_track,
    fused_local_track_segments,
    fused_local_track_valid,
    local_track_reference,
    local_track_segment_reference,
    local_track_valid_reference,
    pallas_supported,
    register_fallback_observer,
    track_halo,
    unregister_fallback_observer,
)

__all__ = [
    "FALLBACK_TOTAL",
    "MAX_PALLAS_DIM",
    "fused_local_track",
    "fused_local_track_segments",
    "fused_local_track_valid",
    "local_track_reference",
    "local_track_segment_reference",
    "local_track_valid_reference",
    "pallas_supported",
    "register_fallback_observer",
    "track_halo",
    "unregister_fallback_observer",
]
