"""Pallas TPU kernels (SURVEY §7 stage 8)."""

from proteinbert_tpu.kernels.fused_block import (
    MAX_PALLAS_DIM,
    fused_local_track,
    fused_local_track_segments,
    fused_local_track_valid,
    local_track_reference,
    local_track_segment_reference,
    local_track_valid_reference,
    pallas_supported,
    track_halo,
)

__all__ = [
    "MAX_PALLAS_DIM",
    "fused_local_track",
    "fused_local_track_segments",
    "fused_local_track_valid",
    "local_track_reference",
    "local_track_segment_reference",
    "local_track_valid_reference",
    "pallas_supported",
    "track_halo",
]
