"""Pallas TPU kernels (SURVEY §7 stage 8)."""

from proteinbert_tpu.kernels.fused_block import (
    MAX_PALLAS_DIM,
    fused_local_track,
    local_track_reference,
    pallas_supported,
)

__all__ = [
    "MAX_PALLAS_DIM",
    "fused_local_track",
    "local_track_reference",
    "pallas_supported",
]
