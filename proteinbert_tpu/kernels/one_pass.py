"""Pallas TPU kernel: one-pass trunk (ISSUE 16 tentpole).

PR 12 put every supported shape on a Pallas fast path, but a
ProteinBERT layer still ran as TWO kernels — the fused local track
(kernels/fused_block.py) and the ragged global attention
(kernels/attention.py) — with the (B, L, C) local activations
round-tripping through HBM between them, and the (B, L, S) segment
one-hot materialised once per kernel. Following the
operator-fusion-for-inference direction (PAPERS.md) this kernel runs
BOTH tracks in one VMEM-resident grid program per batch row:

  window  = x row + conv halo                      (Lp, C)   VMEM
  oh      = segment one-hot + halo                 (Lp, S)   VMEM, ONCE
  local   = conv track (tap matmuls, masked by oh) + LN/dense/LN tail
  attn    = _attention_body(local, oh·real, g)     per-head chain

The inter-track activation (`local`) never leaves VMEM on its way into
the attention projections — it is written to HBM once, as the OUTPUT —
and the one-hot block is shared between the cross-segment conv masks
(`_seg_tap_matmuls`) and the attention mask (oh·real), instead of
being built twice. Cross-segment contributions stay exact +0.0 in both
tracks (multiplication by a zero mask / exp-underflow after the max
shift — the same bit-identity the two constituent kernels prove in
tests/test_packing.py and tests/test_attention_kernel.py).

The DENSE (S=1) entry (`fused_onepass_dense`) phrases unpacked rows as
the same program: unmasked taps, a (B, 1, C) broadcast row, the pad
mask as a one-column one-hot feeding ONLY the attention mask, and
`zero_empty=False` so an all-pad row keeps the reference's uniform
softmax — bucketed serving and unpacked training share the executable
shape family with packed training and ragged serving.

Backward matches the fused-block remat contract: a custom VJP whose
backward recomputes the plain-JAX composition (`onepass_oh_reference`
— the segment/dense track reference followed by
`attention_oh_reference`) and differentiates it, saving only
(params, x, broadcast, global, one-hot, real).

int8 leg: when the params carry `quantize_params` leaves
({"q": int8, "scale": fp32}), the kernel loads the int8 weights and
per-channel scales into VMEM and dequantizes per-tile INSIDE the
program (`q·scale` in fp32, cast to the activation dtype — numerics
bit-identical to the HLO dequant, int8 bytes on the HBM wire). The
quantized path is inference-only and skips the custom-VJP wrapper.

Dispatch is guarded by `pallas_onepass_supported` — the UNION working
set priced with the shared kernels/vmem_budget.py primitives. There is
deliberately NO channel-tiled one-pass variant: the attention chain
needs the full (L, C) local row resident, so beyond MAX_PALLAS_DIM the
dispatch falls back to the existing two-kernel composition (each leg
keeping its own guard, counter family and int8 handling) with a typed
reason. Every decision feeds the third KernelPathCounter family,
`ONEPASS_PATH_TOTAL` / `onepass_kernel_path_total{path=,reason=}`,
mirrored into Server.stats()["onepass_path"] and
`pbt diagnose --serve` exactly like the fused/attention families.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from proteinbert_tpu.kernels import vmem_budget as _vb
from proteinbert_tpu.kernels.attention import (
    _attention_body,
    _segment_one_hot,
    attention_oh_reference,
    fused_global_attention,
    fused_packed_attention,
)
from proteinbert_tpu.kernels.fused_block import (
    MAX_PALLAS_DIM,
    _finish_row,
    _gelu,
    _seg_tap_matmuls,
    _tap_matmuls,
    dequant_params,
    force_reference_requested,
    fused_local_track,
    fused_local_track_segments,
    is_quant_leaf,
    local_track_reference,
    local_track_segment_oh_reference,
    note_kernel_path,
    pallas_supported,
    weight_leaf,
)
from proteinbert_tpu.kernels.path_counter import KernelPathCounter

Params = Dict[str, jax.Array]

# Third two-sided fast-path family (ISSUE 16): same trace-time
# granularity and reason vocabulary as the fused block's PATH_TOTAL and
# the attention family's ATTN_PATH_TOTAL —
#   pallas/packed     — the one-pass program ran on a packed row
#   pallas/dense      — the S=1 entry (bucketed serving / unpacked)
#   reference/segments          — packed shape with no one-pass plan
#                                 (falls back to the TWO-KERNEL
#                                 composition, which counts its own
#                                 families as usual)
#   reference/unsupported_shape — dense shape with no one-pass plan
#   reference/forced            — PBT_FORCE_REFERENCE_KERNEL override
logger = logging.getLogger(__name__)

_COUNTER = KernelPathCounter("one-pass trunk kernel",
                             "onepass_kernel_path_total", log=logger)
ONEPASS_PATH_TOTAL: Dict[Tuple[str, str], int] = _COUNTER.total
# Shape-keyed one-time-warning latch (same contract as
# fused_block._FALLBACK_WARNED / attention._FALLBACK_WARNED).
_FALLBACK_WARNED: set = _COUNTER._warned


def register_onepass_path_observer(cb) -> None:
    """`cb(path, reason)` on every one-pass dispatch bump (trace time)
    — the coverage feed for `onepass_kernel_path_total`."""
    _COUNTER.register(cb)


def unregister_onepass_path_observer(cb) -> None:
    _COUNTER.unregister(cb)


def note_onepass_path(path: str, reason: str,
                      shape: Optional[tuple] = None) -> None:
    _COUNTER.note(path, reason, shape)


def pallas_onepass_supported(
    local_dim: int, global_dim: int, seq_len: int, max_segments: int,
    key_dim: int, num_heads: int, dtype: str = "bfloat16",
    narrow_taps: int = 9, wide_taps: int = 9,
    wide_dilation: int = 5, narrow_dilation: int = 1,
) -> bool:
    """Whether the one-pass program handles this shape+dtype within the
    VMEM budget. The working set is the UNION of the two constituent
    kernels' (both weight sets, the haloed row + one-hot, the full-L
    conv temporaries AND the attention temporaries, plus the resident
    local output feeding the attention chain), priced with the shared
    kernels/vmem_budget.py primitives — so shapes whose two halves
    individually fit can honestly fail here and fall back to the
    two-kernel composition.

    Structural preconditions beyond the shared `shape_prechecks`: odd
    tap counts (the symmetric-halo layout), head-divisible G, and
    sublane-aligned (multiple-of-8) key/value head widths — the fused
    program keeps the per-head fp32 partials resident next to the conv
    scratch, and a ragged head width would force a layout repack
    between the two tracks (no preset shape has one; those shapes stay
    on the two-kernel path). There is NO channel-tiled one-pass
    variant: the attention chain needs the full (L, C) local row
    resident, so C > MAX_PALLAS_DIM always defers."""
    if not _vb.shape_prechecks(local_dim, seq_len, max_segments):
        return False
    if global_dim < 1 or global_dim % num_heads:
        return False
    if narrow_taps % 2 == 0 or wide_taps % 2 == 0:
        return False
    if key_dim % 8 or (global_dim // num_heads) % 8:
        return False
    if local_dim > MAX_PALLAS_DIM:
        return False
    item = _vb.itemsize(dtype)
    C, G, L, S = local_dim, global_dim, seq_len, max_segments
    H, k = num_heads, key_dim
    halo = max((narrow_taps - 1) // 2 * narrow_dilation,
               (wide_taps - 1) // 2 * wide_dilation)
    Lp = L + 2 * halo
    # Blocks whose index map varies with b are double-buffered by the
    # pipeline; weight blocks are whole (single buffer).
    row = 2 * Lp * C * item
    oh_row = 2 * Lp * _vb.lanes(S) * item
    real_col = 2 * L * _vb.lanes(1) * item
    bcast = 2 * S * C * item
    gseg = 2 * S * _vb.lanes(G) * item
    out_local = 2 * L * C * item
    out_attn = 2 * S * _vb.lanes(G) * item
    weights = (_vb.track_weight_bytes(C, narrow_taps, wide_taps, item)
               + _vb.attention_weight_bytes(C, G, k, H, item))
    # The conv track runs untiled (tile = L: attention needs the full
    # row anyway), its output stays live into the attention chain, and
    # the tap masks add one (L, S) fp32 temporary.
    temps = (_vb.track_temp_bytes(L, C)
             + L * _vb.lanes(S) * 4
             + L * C * item
             + _vb.attention_temp_bytes(L, S, G, k, H))
    return _vb.fits(row, oh_row, real_col, bcast, gseg, out_local,
                    out_attn, weights, temps)


def onepass_oh_reference(
    track_params: Params, attn_params: Params, x: jax.Array,
    broadcast_seg: jax.Array, global_seg: jax.Array, seg_oh: jax.Array,
    real: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    seg_masked: bool = True, zero_empty: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Plain-JAX ground truth of the one-pass program, phrased in the
    one-hot form the kernel consumes: the constituent kernels' own
    references composed — segment (or dense) local track, then
    attention over `seg_oh · real` (the conv masks deliberately ignore
    `real`: serving `<pad>` spans inside a segment DO participate in
    convs, exactly like the two-kernel path). The custom VJP
    rematerialises and differentiates THIS composition."""
    if seg_masked:
        local = local_track_segment_oh_reference(
            track_params, x, broadcast_seg, seg_oh,
            narrow_dilation, wide_dilation)
    else:
        local = local_track_reference(
            track_params, x, broadcast_seg[:, 0, :],
            narrow_dilation, wide_dilation)
    attn = attention_oh_reference(
        attn_params, local, global_seg,
        seg_oh * real.astype(seg_oh.dtype), zero_empty)
    return local, attn


def _onepass_kernel(
    x_ref, oh_ref, real_ref, bcast_ref, g_ref,
    nk_ref, nb_ref, wk_ref, wb_ref,
    s1_ref, b1_ref, dk_ref, db_ref, s2_ref, b2_ref,
    wq_ref, wak_ref, wav_ref,
    *rest,
    L, halo, narrow_taps, wide_taps, narrow_dilation, wide_dilation,
    key_dim, num_heads, seg_masked, zero_empty, quantized=False,
):
    local_ref, attn_ref = rest[-2], rest[-1]
    dtype = x_ref.dtype
    if quantized:
        # int8 weights + per-channel scales are VMEM-resident; the
        # per-tile dequant (q·scale in fp32, cast to the activation
        # dtype) reproduces the HLO dequant's numerics bit-for-bit
        # (ISSUE 16 second leg), but HBM ships int8 bytes.
        nks, wks, dks, wqs, waks, wavs = rest[0:6]
        nk = (nk_ref[:].astype(jnp.float32) * nks[:]).astype(dtype)
        wk = (wk_ref[:].astype(jnp.float32) * wks[:]).astype(dtype)
        dk = (dk_ref[:].astype(jnp.float32) * dks[:]).astype(dtype)
        wq = (wq_ref[:].astype(jnp.float32) * wqs[:]).astype(dtype)
        wak = (wak_ref[:].astype(jnp.float32) * waks[:]).astype(dtype)
        wav = (wav_ref[:].astype(jnp.float32) * wavs[:]).astype(dtype)
    else:
        nk, wk, dk = nk_ref, wk_ref, dk_ref
        wq, wak, wav = wq_ref, wak_ref, wav_ref

    window = x_ref[0]          # (Lp, C)
    oh_window = oh_ref[0]      # (Lp, S) — shared by BOTH tracks
    x_center = window[halo:halo + L].astype(jnp.float32)
    oh_center = oh_window[halo:halo + L]

    if seg_masked:
        narrow = _seg_tap_matmuls(window, oh_window, nk[:], narrow_taps,
                                  narrow_dilation, halo, L)
        wide = _seg_tap_matmuls(window, oh_window, wk[:], wide_taps,
                                wide_dilation, halo, L)
        # Own-segment broadcast gather as a one-hot matmul: a pad
        # position's all-zero one-hot row receives exact 0.0.
        bcast_pos = lax.dot_general(
            oh_center, bcast_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        narrow = _tap_matmuls(window, nk[:], narrow_taps,
                              narrow_dilation, halo, L)
        wide = _tap_matmuls(window, wk[:], wide_taps,
                            wide_dilation, halo, L)
        bcast_pos = bcast_ref[0, 0].astype(jnp.float32)[None, :]
    narrow = _gelu(narrow + nb_ref[0].astype(jnp.float32))
    wide = _gelu(wide + wb_ref[0].astype(jnp.float32))

    h = x_center + narrow + wide + bcast_pos
    local_val = _finish_row(h, s1_ref, b1_ref, dk, db_ref,
                            s2_ref, b2_ref, dtype)
    local_ref[0] = local_val
    # The local activations feed the attention chain STRAIGHT from
    # VMEM — the HBM round-trip between the two kernels is the traffic
    # this program exists to eliminate. The attention mask is the same
    # one-hot block the conv masks rode, narrowed to real tokens.
    attn_oh = (oh_center * real_ref[0]).astype(dtype)
    attn_ref[0] = _attention_body(
        local_val, attn_oh, g_ref[0], wq, wak, wav,
        key_dim=key_dim, num_heads=num_heads, zero_empty=zero_empty)


def _pallas_onepass_forward(
    track_params: Params, attn_params: Params, x: jax.Array,
    broadcast_seg: jax.Array, global_seg: jax.Array, seg_oh: jax.Array,
    real: jax.Array,
    narrow_dilation: int, wide_dilation: int,
    seg_masked: bool, zero_empty: bool, interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    nk = track_params["narrow_conv"]["kernel"]
    wk = track_params["wide_conv"]["kernel"]
    quantized = is_quant_leaf(nk)
    narrow_taps = weight_leaf(nk).shape[0]
    wide_taps = weight_leaf(wk).shape[0]
    halo = max((narrow_taps - 1) // 2 * narrow_dilation,
               (wide_taps - 1) // 2 * wide_dilation)
    B, L, C = x.shape
    S, G = global_seg.shape[1], global_seg.shape[2]
    dtype = x.dtype
    x_padded = jnp.pad(x, ((0, 0), (halo, halo), (0, 0)))
    oh_padded = jnp.pad(seg_oh.astype(dtype),
                        ((0, 0), (halo, halo), (0, 0)))
    Lp = L + 2 * halo

    def vec(p):  # (C,) fp32 vector → (1, C) VMEM block
        return p.reshape(1, -1)

    ln1 = track_params["local_ln1"]
    ln2 = track_params["local_ln2"]
    dn = track_params["local_dense"]
    if quantized:
        # int8 weight operands ride as-is; scales are reshaped so the
        # in-kernel q·scale multiply broadcasts per output channel
        # exactly like dequantize_params' scale[..., None, :].
        nk_w, wk_w, dk_w = nk["q"], wk["q"], dn["kernel"]["q"]
        wq_w = attn_params["wq"]["q"]
        wak_w = attn_params["wk"]["q"]
        wav_w = attn_params["wv"]["q"]
        scales = (
            nk["scale"][:, None, :].astype(jnp.float32),
            wk["scale"][:, None, :].astype(jnp.float32),
            dn["kernel"]["scale"].reshape(1, C).astype(jnp.float32),
            attn_params["wq"]["scale"][:, None, :].astype(jnp.float32),
            attn_params["wk"]["scale"][:, None, :].astype(jnp.float32),
            attn_params["wv"]["scale"][:, None, :].astype(jnp.float32),
        )
    else:
        nk_w, wk_w = nk.astype(dtype), wk.astype(dtype)
        dk_w = dn["kernel"].astype(dtype)
        wq_w = attn_params["wq"].astype(dtype)
        wak_w = attn_params["wk"].astype(dtype)
        wav_w = attn_params["wv"].astype(dtype)
        scales = ()
    H, _, key_dim = wq_w.shape
    inputs = (
        x_padded, oh_padded, real.astype(dtype),
        broadcast_seg.astype(dtype), global_seg.astype(dtype),
        nk_w, vec(track_params["narrow_conv"]["bias"]),
        wk_w, vec(track_params["wide_conv"]["bias"]),
        vec(ln1["scale"]), vec(ln1["bias"]),
        dk_w, vec(dn["bias"]),
        vec(ln2["scale"]), vec(ln2["bias"]),
        wq_w, wak_w, wav_w,
    )

    def whole(a):
        return pl.BlockSpec(a.shape, lambda b: (0,) * a.ndim,
                            memory_space=pltpu.VMEM)

    def bmap(shape):
        return pl.BlockSpec(shape, lambda b: (b,) + (0,) * (len(shape) - 1),
                            memory_space=pltpu.VMEM)

    v_dim = G // H
    flops = (2 * B * L * C * C * (narrow_taps + wide_taps + 1)
             + 2 * B * H * (L * C * (key_dim + v_dim)
                            + S * G * key_dim
                            + L * S * (key_dim + v_dim)))
    cost = pl.CostEstimate(
        flops=flops,
        bytes_accessed=x.size * x.dtype.itemsize * 2,
        transcendentals=3 * B * L * C + B * H * L * (key_dim + v_dim + S),
    )
    kernel = functools.partial(
        _onepass_kernel, L=L, halo=halo,
        narrow_taps=narrow_taps, wide_taps=wide_taps,
        narrow_dilation=narrow_dilation, wide_dilation=wide_dilation,
        key_dim=key_dim, num_heads=H,
        seg_masked=seg_masked, zero_empty=zero_empty,
        quantized=quantized,
    )
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            bmap((1, Lp, C)), bmap((1, Lp, S)), bmap((1, L, 1)),
            bmap((1, S, C)), bmap((1, S, G)),
        ] + [whole(a) for a in inputs[5:]] + [whole(s) for s in scales],
        out_specs=[
            bmap((1, L, C)),
            bmap((1, S, G)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, L, C), dtype),
            jax.ShapeDtypeStruct((B, S, G), dtype),
        ],
        cost_estimate=cost,
        interpret=interpret,
    )(*inputs, *scales)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _fused_onepass(
    track_params: Params, attn_params: Params, x: jax.Array,
    broadcast_seg: jax.Array, global_seg: jax.Array, seg_oh: jax.Array,
    real: jax.Array,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    seg_masked: bool = True, zero_empty: bool = True,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One-pass program under the fused-block memory contract: Pallas
    forward, rematerialised backward (the VJP recomputes
    `onepass_oh_reference` — conv_out remat tag intact — and
    differentiates it, saving only params/x/broadcast/global/one-hot/
    real)."""
    return _pallas_onepass_forward(
        track_params, attn_params, x, broadcast_seg, global_seg, seg_oh,
        real, narrow_dilation, wide_dilation, seg_masked, zero_empty,
        interpret)


def _fwd_onepass(track_params, attn_params, x, broadcast_seg, global_seg,
                 seg_oh, real, narrow_dilation, wide_dilation, seg_masked,
                 zero_empty, interpret):
    y = _pallas_onepass_forward(
        track_params, attn_params, x, broadcast_seg, global_seg, seg_oh,
        real, narrow_dilation, wide_dilation, seg_masked, zero_empty,
        interpret)
    return y, (track_params, attn_params, x, broadcast_seg, global_seg,
               seg_oh, real)


def _bwd_onepass(narrow_dilation, wide_dilation, seg_masked, zero_empty,
                 interpret, res, g):
    track_params, attn_params, x, broadcast_seg, global_seg, seg_oh, real = res
    _, vjp = jax.vjp(
        lambda tp, ap, xx, bb, gg, oo, rr: onepass_oh_reference(
            tp, ap, xx, bb, gg, oo, rr, narrow_dilation, wide_dilation,
            seg_masked, zero_empty,
        ),
        track_params, attn_params, x, broadcast_seg, global_seg, seg_oh,
        real,
    )
    return vjp(g)


_fused_onepass.defvjp(_fwd_onepass, _bwd_onepass)


def fused_onepass_segments(
    track_params: Params, attn_params: Params, x: jax.Array,
    broadcast_seg: jax.Array, global_seg: jax.Array,
    segment_ids: jax.Array,
    real_mask: Optional[jax.Array] = None,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Whole packed trunk layer — local track AND per-segment global
    attention — as one dispatch (the ISSUE 16 tentpole). On supported
    shapes (`pallas_onepass_supported`) the one-pass program runs;
    otherwise (and under PBT_FORCE_REFERENCE_KERNEL) the existing
    TWO-KERNEL composition runs — `fused_local_track_segments` then
    `fused_packed_attention`, each with its own guard, counter family
    and int8 handling — so no supported shape regresses off the Pallas
    fast path when the fused plan doesn't fit.

    Args match the constituent dispatches: `broadcast_seg` (B, S, C)
    per-segment projected global vectors, `global_seg` (B, S, G),
    `segment_ids` (B, L) with 0 = pad, `real_mask` the ragged-serving
    real-token mask (None = every in-segment position is real; it
    narrows the ATTENTION mask only — `<pad>` spans inside a serving
    segment still participate in convs, both paths).

    Returns (local, attn): the (B, L, C) local track output and the
    (B, S, G) attention output. Every dispatch counts in
    `ONEPASS_PATH_TOTAL[(path, reason)]` at trace time."""
    B, L, C = x.shape
    S, G = global_seg.shape[1], global_seg.shape[2]
    H, _, key_dim = weight_leaf(attn_params["wq"]).shape
    nt = weight_leaf(track_params["narrow_conv"]["kernel"]).shape[0]
    wt = weight_leaf(track_params["wide_conv"]["kernel"]).shape[0]
    quantized = is_quant_leaf(track_params["narrow_conv"]["kernel"])
    shape_key = (B, L, C, S, G, str(jnp.dtype(x.dtype)))
    if force_reference_requested():
        reason = "forced"
    elif pallas_onepass_supported(C, G, L, S, key_dim, H, x.dtype,
                                  nt, wt, wide_dilation, narrow_dilation):
        reason = None
    else:
        reason = "segments"
    if reason is None:
        note_onepass_path("pallas", "packed", shape_key)
        # The conv one-hot must NOT fold in real_mask (serving <pad>
        # spans inside a segment participate in convs); the kernel
        # narrows the attention mask with `real` itself.
        seg_oh = _segment_one_hot(segment_ids, S, x.dtype)
        real = (jnp.ones((B, L, 1), x.dtype) if real_mask is None
                else real_mask[..., None].astype(x.dtype))
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if quantized:
            # Inference-only int8 path: in-kernel dequant, no VJP.
            return _pallas_onepass_forward(
                track_params, attn_params, x, broadcast_seg, global_seg,
                seg_oh, real, narrow_dilation, wide_dilation, True, True,
                interpret)
        return _fused_onepass(
            track_params, attn_params, x, broadcast_seg, global_seg,
            seg_oh, real, narrow_dilation, wide_dilation, True, True,
            interpret)
    note_onepass_path("reference", reason, shape_key)
    interp = (jax.default_backend() != "tpu" if interpret is None
              else interpret)
    local = fused_local_track_segments(
        track_params, x, broadcast_seg, segment_ids,
        narrow_dilation, wide_dilation, interp)
    attn = fused_packed_attention(
        attn_params, local, global_seg, segment_ids,
        real_mask=real_mask, interpret=interpret)
    return local, attn


def fused_onepass_dense(
    track_params: Params, attn_params: Params, x: jax.Array,
    broadcast: jax.Array, global_: jax.Array,
    pad_mask: Optional[jax.Array] = None,
    narrow_dilation: int = 1, wide_dilation: int = 5,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """DENSE (unpacked) trunk layer through the same one-pass program:
    the (B, G) global track is an S=1 segment set, `broadcast` (B, C)
    a one-segment broadcast row, and the pad mask a one-column one-hot
    feeding ONLY the attention mask (the convs stay unmasked, exactly
    like `local_track_reference`). All-pad rows keep the reference's
    uniform softmax (`zero_empty=False`). Fallback is the existing
    two-kernel dense composition — `fused_local_track` (or the XLA
    reference, under its own `fused_kernel_path_total` accounting,
    matching the model's pre-one-pass dispatch) then
    `fused_global_attention`.

    Returns (local, attn): (B, L, C) and (B, G)."""
    B, L, C = x.shape
    G = global_.shape[-1]
    H, _, key_dim = weight_leaf(attn_params["wq"]).shape
    nt = weight_leaf(track_params["narrow_conv"]["kernel"]).shape[0]
    wt = weight_leaf(track_params["wide_conv"]["kernel"]).shape[0]
    quantized = is_quant_leaf(track_params["narrow_conv"]["kernel"])
    shape_key = (B, L, C, 1, G, str(jnp.dtype(x.dtype)))
    forced = force_reference_requested()
    if forced:
        reason = "forced"
    elif pallas_onepass_supported(C, G, L, 1, key_dim, H, x.dtype,
                                  nt, wt, wide_dilation, narrow_dilation):
        reason = None
    else:
        reason = "unsupported_shape"
    if reason is None:
        note_onepass_path("pallas", "dense", shape_key)
        if pad_mask is None:
            oh = jnp.ones((B, L, 1), x.dtype)
        else:
            oh = pad_mask[..., None].astype(x.dtype)
        real = jnp.ones((B, L, 1), x.dtype)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if quantized:
            local, attn = _pallas_onepass_forward(
                track_params, attn_params, x, broadcast[:, None, :],
                global_[:, None, :], oh, real, narrow_dilation,
                wide_dilation, False, False, interpret)
        else:
            local, attn = _fused_onepass(
                track_params, attn_params, x, broadcast[:, None, :],
                global_[:, None, :], oh, real, narrow_dilation,
                wide_dilation, False, False, interpret)
        return local, attn.reshape(B, G)
    note_onepass_path("reference", reason, shape_key)
    # Two-kernel dense composition — the model's pre-one-pass dispatch,
    # fused_kernel_path_total accounting included.
    interp = (jax.default_backend() != "tpu" if interpret is None
              else interpret)
    tp = dequant_params(track_params) if quantized else track_params
    track_key = (B, L, C, str(jnp.dtype(x.dtype)))
    if forced:
        note_kernel_path("reference", "forced", track_key)
        local = local_track_reference(tp, x, broadcast,
                                      narrow_dilation, wide_dilation)
    elif pallas_supported(C, L, x.dtype, nt, wt, wide_dilation):
        note_kernel_path("pallas", "dense", track_key)
        local = fused_local_track(tp, x, broadcast,
                                  narrow_dilation, wide_dilation, interp)
    else:
        note_kernel_path("reference", "unsupported_shape", track_key)
        local = local_track_reference(tp, x, broadcast,
                                      narrow_dilation, wide_dilation)
    attn = fused_global_attention(attn_params, local, global_, pad_mask,
                                  interpret=interpret)
    return local, attn
