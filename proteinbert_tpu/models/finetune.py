"""Fine-tuning task heads on the pretrained dual-track trunk (SURVEY C14).

The reference's fine-tune harness is entirely commented-out code
(reference utils.py:348-493: epoch-based train()/test() with a pluggable
metric dict, never finished). This module completes the design the
TPU-native way: task heads are pure-pytree layers over `proteinbert.encode`
representations, and trunk + head params live in one tree so a single
`jax.grad` covers both (with an optax mask freezing the trunk when
task.freeze_trunk is set — the reference could not even train its attention
heads, SURVEY ledger #1).

Head shapes per task kind (TaskConfig.kind):
  token_classification    local (B, L, C)             → (B, L, num_outputs)
  sequence_classification [global ‖ masked-mean local] → (B, num_outputs)
  sequence_regression     [global ‖ masked-mean local] → (B, 1)

Sequence-level heads read BOTH tracks: the global track is the model's
own whole-protein summary; the masked mean over the local track adds
per-residue evidence the paper's benchmarks (stability, fluorescence)
depend on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from proteinbert_tpu.configs import ModelConfig, TaskConfig
from proteinbert_tpu.data.vocab import PAD_ID
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.ops.layers import dense_apply, dense_init

Params = Dict[str, Any]

KINDS = ("token_classification", "sequence_classification", "sequence_regression")


def head_in_dim(model_cfg: ModelConfig, task: TaskConfig) -> int:
    if task.kind == "token_classification":
        return model_cfg.local_dim
    return model_cfg.global_dim + model_cfg.local_dim


def head_init(key: jax.Array, model_cfg: ModelConfig, task: TaskConfig) -> Params:
    if task.kind not in KINDS:
        raise ValueError(f"unknown task kind {task.kind!r}; have {KINDS}")
    in_dim = head_in_dim(model_cfg, task)
    if task.head_hidden_dim:
        k1, k2 = jax.random.split(key)
        return {
            "hidden": dense_init(k1, in_dim, task.head_hidden_dim),
            "out": dense_init(k2, task.head_hidden_dim, task.num_outputs),
        }
    return {"out": dense_init(key, in_dim, task.num_outputs)}


def init(
    key: jax.Array,
    model_cfg: ModelConfig,
    task: TaskConfig,
    pretrained_trunk: Optional[Params] = None,
) -> Params:
    """{"trunk", "head"} param tree; trunk from a pretrain checkpoint's
    params (its pretraining heads are dropped) or freshly initialized."""
    k_trunk, k_head = jax.random.split(key)
    if pretrained_trunk is not None:
        trunk = {k: v for k, v in pretrained_trunk.items()
                 if k not in ("local_head", "global_head")}
    else:
        trunk = {k: v for k, v in proteinbert.init(k_trunk, model_cfg).items()
                 if k not in ("local_head", "global_head")}
    return {"trunk": trunk, "head": head_init(k_head, model_cfg, task)}


def _head_apply(head: Params, x: jax.Array) -> jax.Array:
    if "hidden" in head:
        x = jax.nn.gelu(dense_apply(head["hidden"], x))
    return dense_apply(head["out"], x)


def head_features(local: jax.Array, global_: jax.Array,
                  pad_mask: jax.Array, kind: str) -> jax.Array:
    """Trunk representation → the feature tensor a `kind` head consumes.
    Per-residue heads read the local track directly; sequence-level
    heads read [global ‖ masked-mean local] (see module doc). ONE
    definition shared by the monolithic `apply` below and the
    split-apply serving path (heads/apply.py), so the two surfaces
    cannot drift numerically."""
    if kind == "token_classification":
        return local
    m = pad_mask.astype(local.dtype)[..., None]
    pooled = (local * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    return jnp.concatenate([global_, pooled], axis=-1)


def apply_head(head: Params, local: jax.Array, global_: jax.Array,
               pad_mask: jax.Array, kind: str) -> jax.Array:
    """Run one task head off an already-computed trunk representation
    (`proteinbert.encode_trunk`): float32 logits/predictions, shaped by
    `kind` as in the module doc. This is the cheap per-tenant tail of
    split-apply serving — the trunk runs once per micro-batch, this
    runs once per (head, micro-batch)."""
    return _head_apply(head, head_features(local, global_, pad_mask,
                                           kind)).astype(jnp.float32)


def apply(
    params: Params,
    tokens: jax.Array,
    model_cfg: ModelConfig,
    task: TaskConfig,
    annotations: Optional[jax.Array] = None,
    pad_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Task logits/predictions in float32.

    `annotations` defaults to zeros — fine-tuning datasets normally carry
    no GO annotations, which matches the pretraining corruption's
    hide-all-annotations branch (reference data_processing.py:127-128),
    so a zero global input is in-distribution for the trunk.

    Composed as encode_trunk → apply_head, the exact decomposition the
    serving path uses (heads/apply.py) — split-apply bit-parity with
    this monolithic entry is by construction, not by test luck
    (tests/test_heads.py proves it anyway).
    """
    trunk_out = proteinbert.encode_trunk(
        params["trunk"], tokens, model_cfg, annotations, pad_mask)
    return apply_head(params["head"], trunk_out["local"],
                      trunk_out["global"], trunk_out["pad_mask"],
                      task.kind)
