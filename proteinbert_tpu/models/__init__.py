from proteinbert_tpu.models import proteinbert

__all__ = ["proteinbert"]
