from proteinbert_tpu.models import finetune, proteinbert

__all__ = ["finetune", "proteinbert"]
