"""ProteinBERT dual-track model (reference C11/C12, TPU-native).

Functional pytree implementation of the dual-track (local sequence /
global annotation) ProteinBERT trunk (Brandes et al. 2022; reference
ProteinBERT/modules.py:95-304), with the paper-correct semantics the
reference gets wrong (SURVEY ledger #1-#4):

- every parameter is a pytree leaf (optimizer sees the attention heads);
- attention softmax is over the sequence axis, padding masked out;
- LayerNorm is per-position over features only → the model is
  shape-parametric in L (one set of weights serves any sequence length);
- output heads emit LOGITS; probabilities never enter the loss (the
  reference applies Softmax/Sigmoid in the model and then feeds
  CrossEntropyLoss, reference modules.py:277-293 + utils.py:293).

TPU mapping:
- activations run in bfloat16 (cfg.dtype), parameters in float32;
- the N identical blocks are stacked on a leading axis and driven by
  `lax.scan` (cfg.scan_blocks) → one compiled block body instead of N
  unrolled copies, cutting compile time and enabling `jax.checkpoint`
  rematerialisation per scan step (cfg.remat) for long-context configs;
- layout is feature-last (B, L, C) throughout so the L axis can carry a
  `seq` mesh axis (sequence parallelism) and convs lower to MXU implicit
  GEMMs (see ops/layers.py).

Block dataflow (reference modules.py:201-231, shapes in SURVEY §3.4):
  local:  x = LN(x + narrow_conv(x)·gelu + wide_conv(x)·gelu
                 + broadcast(gelu(dense(g))))
          x = LN(x + gelu(dense(x)))
  global: g = LN(g + gelu(dense(g)) + attention(x, g))
          g = LN(g + gelu(dense(g)))
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.data.vocab import PAD_ID
from proteinbert_tpu.ops.attention import (
    global_attention_apply,
    global_attention_init,
    packed_global_attention_apply,
)
from proteinbert_tpu.ops.layers import (
    conv1d_init,
    dense_apply,
    dense_init,
    embedding_apply,
    embedding_init,
    layer_norm_apply,
    layer_norm_init,
)

Params = Dict[str, Any]


def remat_wrap(body, cfg: ModelConfig):
    """Apply cfg's rematerialisation choice to a block body — the single
    policy-dispatch point shared by the jit path here and the explicit
    sequence-parallel path (parallel/seq_parallel.py).

    "full" recomputes the whole block in backward; "convs" keeps the two
    conv outputs (the FLOPs-heavy ~85% of a block, tagged "conv_out" in
    ops/layers.conv1d_apply and the seq-parallel valid-conv variant) and
    recomputes only the cheap dense/LN/attention tail: ~3.15× forward
    FLOPs per step instead of full remat's 4×, for 2·(B,L,C) bf16 extra
    residency per block (measured +8% throughput, BASELINE.md). Under
    use_pallas the kernel's custom VJP hides its internals either way, so
    both policies degenerate to recompute-everything there.
    """
    if cfg.remat_policy not in ("full", "convs"):
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r}; have 'full', 'convs'"
        )
    if not cfg.remat:
        return body
    if cfg.remat_policy == "convs":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names("conv_out"),
        )
    return jax.checkpoint(body)


def block_init(key: jax.Array, cfg: ModelConfig) -> Params:
    """One dual-track block's parameters (reference modules.py:95-199)."""
    C, G = cfg.local_dim, cfg.global_dim
    ks = jax.random.split(key, 7)
    return {
        "narrow_conv": conv1d_init(ks[0], cfg.narrow_kernel, C, C),
        "wide_conv": conv1d_init(ks[1], cfg.wide_kernel, C, C),
        "global_to_local": dense_init(ks[2], G, C),
        "local_ln1": layer_norm_init(C),
        "local_dense": dense_init(ks[3], C, C),
        "local_ln2": layer_norm_init(C),
        "global_dense1": dense_init(ks[4], G, G),
        "attention": global_attention_init(ks[5], C, G, cfg.key_dim, cfg.num_heads),
        "global_ln1": layer_norm_init(G),
        "global_dense2": dense_init(ks[6], G, G),
        "global_ln2": layer_norm_init(G),
    }


def block_apply(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    pad_mask: Optional[jax.Array],
    cfg: ModelConfig,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Apply one block. local (B,L,C), global (B,G), pad_mask (B,L) bool.

    PACKED rows (data/packing.py): pass `segment_ids` (B,L) and a
    per-SEGMENT global track (B,S,G). The local convs are boundary-
    masked, the global→local broadcast is gathered per position from the
    position's own segment, and attention/annotation state run per
    segment — a packed row is numerically a batch of independent
    proteins (tests/test_packing.py asserts bit-level isolation)."""
    packed = segment_ids is not None
    # Local track (reference modules.py:201-217).
    broadcast = jax.nn.gelu(dense_apply(params["global_to_local"], global_))
    from proteinbert_tpu.kernels import (
        gather_segment_broadcast, local_track_reference,
        local_track_segment_reference,
    )

    track_params = {k: params[k] for k in ("narrow_conv", "wide_conv",
                                           "local_ln1", "local_dense",
                                           "local_ln2")}
    # Under use_pallas BOTH tracks route through the one-pass trunk
    # dispatch (kernels/one_pass.py, ISSUE 16): on supported shapes the
    # local conv track and the global attention run as ONE VMEM-resident
    # grid program (the inter-track activations never round-trip through
    # HBM, and the segment one-hot is built once for both masks);
    # otherwise the dispatch falls back to the existing two-kernel
    # composition, each leg with its own guard + counter family. Every
    # decision is counted in onepass_kernel_path_total{path=,reason=}.
    # `attn` comes back alongside `local`; it attends over the NEW local
    # track with the OLD global track, exactly like the split path.
    if cfg.use_pallas:
        from proteinbert_tpu.kernels import (
            fused_onepass_dense, fused_onepass_segments,
        )

        interp = jax.default_backend() != "tpu"
        if packed:
            # pad_mask is the REAL-token mask: for training packs it
            # equals segment_ids > 0 (segments hold no pad); the ragged
            # serving path packs bucket-quantized spans with <pad>
            # tails, which are excluded from the attention softmax but
            # DO participate in the convs (two-kernel semantics).
            local, attn = fused_onepass_segments(
                track_params, params["attention"], local, broadcast,
                global_, segment_ids, real_mask=pad_mask,
                narrow_dilation=1, wide_dilation=cfg.wide_dilation,
                interpret=interp,
            )
        else:
            local, attn = fused_onepass_dense(
                track_params, params["attention"], local, broadcast,
                global_, pad_mask=pad_mask,
                narrow_dilation=1, wide_dilation=cfg.wide_dilation,
                interpret=interp,
            )
    elif packed:
        # Gather each position's own segment's broadcast vector:
        # (B, S, C) → (B, L, C), zero at pad so nothing row-wide
        # leaks into the masked conv taps.
        local = local_track_segment_reference(
            track_params, local,
            gather_segment_broadcast(broadcast, segment_ids),
            segment_ids, 1, cfg.wide_dilation,
        )
        attn = packed_global_attention_apply(
            params["attention"], local, global_, segment_ids,
            real_mask=pad_mask)
    else:
        local = local_track_reference(
            track_params, local, broadcast, 1, cfg.wide_dilation
        )
        attn = global_attention_apply(
            params["attention"], local, global_, pad_mask)

    # Global track (reference modules.py:219-229) — per segment when
    # packed: every dense/LN is feature-last and shape-agnostic over the
    # leading (B, S) axes; `attn` was computed above against the OLD
    # global track.
    dense1 = jax.nn.gelu(dense_apply(params["global_dense1"], global_))
    global_ = layer_norm_apply(params["global_ln1"], global_ + dense1 + attn)
    global_ = layer_norm_apply(
        params["global_ln2"],
        global_ + jax.nn.gelu(dense_apply(params["global_dense2"], global_)),
    )
    return local, global_


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    """Full-model parameter pytree (reference modules.py:234-293)."""
    k_embed, k_gin, k_blocks, k_lh, k_gh = jax.random.split(key, 5)
    block_keys = jax.random.split(k_blocks, cfg.num_blocks)
    blocks = [block_init(k, cfg) for k in block_keys]
    if cfg.scan_blocks:
        blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    return {
        "embedding": embedding_init(k_embed, cfg.vocab_size, cfg.local_dim),
        "global_in": dense_init(k_gin, cfg.num_annotations, cfg.global_dim),
        "blocks": blocks,
        "local_head": dense_init(k_lh, cfg.local_dim, cfg.vocab_size),
        "global_head": dense_init(k_gh, cfg.global_dim, cfg.num_annotations),
    }


_LN_NAMES = ("local_ln1", "local_ln2", "global_ln1", "global_ln2")


def _cast_blocks(blocks: Params, dtype) -> Params:
    """Cast the scanned block stack to the compute dtype ONCE, outside the
    scan. Every non-LN leaf is consumed at activation dtype anyway
    (`.astype(x.dtype)` in ops/layers.py), but casting per-use INSIDE the
    scan makes autodiff stash the per-block bf16 copies into a stacked
    loop-carried buffer whose forward/backward shardings the SPMD
    partitioner cannot reconcile on fsdp-bearing meshes ("Involuntary
    full rematerialization", VERDICT r2 Weak #3). Hoisting the cast means
    the scan xs ARE the bf16 tensors — nothing new is saved per step, the
    warning disappears, and the f32→bf16 convert runs once per step
    instead of once per block. LN leaves stay f32: layer_norm_apply
    consumes them in f32 statistics space. int8 quant leaves
    ({"q", "scale"} from parallel/quant.partial_dequantize_params, the
    in-kernel-dequant serving arm) pass through untouched — the kernels
    consume the int8 weights + fp32 scales directly."""
    def cast(path, leaf):
        if any(getattr(p, "key", None) in _LN_NAMES + ("q", "scale")
               for p in path):
            return leaf
        return leaf.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, blocks)


def encode(
    params: Params,
    tokens: jax.Array,
    annotations: jax.Array,
    cfg: ModelConfig,
    pad_mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Trunk forward: embeddings + N dual-track blocks, no output heads.

    Returns (local (B, L, C), global (B, G)) representations — the input
    to the pretraining heads here and to fine-tuning task heads
    (models/finetune.py), which the reference only sketched in
    commented-out code (reference utils.py:348-493, SURVEY C14).

    PACKED rows: pass `segment_ids` (B, L) with annotations shaped
    (B, S, A) per segment; the global representation comes back
    per-segment as (B, S, G) and every cross-position op is segment-
    masked (see block_apply).
    """
    dtype = jnp.dtype(cfg.dtype)
    if pad_mask is None:
        pad_mask = (segment_ids > 0 if segment_ids is not None
                    else tokens != PAD_ID)

    local = embedding_apply(params["embedding"], tokens, dtype)
    global_ = jax.nn.gelu(
        dense_apply(params["global_in"], annotations.astype(dtype))
    )

    body = remat_wrap(
        partial(block_apply, cfg=cfg, segment_ids=segment_ids), cfg)

    if cfg.scan_blocks:
        def scan_body(carry, blk):
            l, g = carry
            l, g = body(blk, l, g, pad_mask)
            return (l, g), None

        (local, global_), _ = lax.scan(
            scan_body, (local, global_), _cast_blocks(params["blocks"], dtype),
            unroll=cfg.scan_unroll,
            _split_transpose=cfg.scan_split_transpose,
        )
    else:
        for blk in params["blocks"]:
            local, global_ = body(blk, local, global_, pad_mask)
    return local, global_


def encode_trunk(
    params: Params,
    tokens: jax.Array,
    cfg: ModelConfig,
    annotations: Optional[jax.Array] = None,
    pad_mask: Optional[jax.Array] = None,
) -> Dict[str, jax.Array]:
    """The SHARED representation every task head consumes (ISSUE 8).

    One forward through the trunk, packaged for split-apply serving:
    `{"local": (B, L, C), "global": (B, G), "pad_mask": (B, L) bool}`.
    Any registered head (heads/apply.py) — and the monolithic
    models/finetune.apply — runs off exactly this dict, so the
    expensive computation is executed once per micro-batch and the
    cheap per-head tails are appended (the operator-fusion-for-
    inference batching shape, PAPERS.md).

    `annotations` defaults to the all-zero "no annotations known"
    input (the same convention as models/finetune.apply — it is the
    trained hide-all-annotations branch, so a zero global input is
    in-distribution for the trunk). Extra keys in `params` (a pretrain
    checkpoint's `local_head`/`global_head`) are ignored: pretrain
    params and a stripped finetune trunk encode identically.
    """
    if pad_mask is None:
        pad_mask = tokens != PAD_ID
    if annotations is None:
        annotations = jnp.zeros(
            (tokens.shape[0], cfg.num_annotations), jnp.float32)
    local, global_ = encode(params, tokens, annotations, cfg, pad_mask)
    return {"local": local, "global": global_, "pad_mask": pad_mask}


def apply(
    params: Params,
    tokens: jax.Array,
    annotations: jax.Array,
    cfg: ModelConfig,
    pad_mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Forward pass.

    Args:
      tokens: (B, L) int token ids (the corrupted "local" input).
      annotations: (B, A) float annotation vector (the corrupted "global"
        input; reference input contract at modules.py:295-304) — or
        (B, S, A) per-segment vectors when `segment_ids` is passed.
      pad_mask: (B, L) bool, True at real positions; derived from tokens
        (or segment_ids) if omitted.
      segment_ids: optional (B, L) int segment map for PACKED rows
        (data/packing.py); 0 = pad, 1..S = packed protein index.
    Returns:
      (local_logits (B, L, V), global_logits (B, A)) — LOGITS, in
      float32; global_logits is (B, S, A) when packed.
    """
    local, global_ = encode(params, tokens, annotations, cfg, pad_mask,
                            segment_ids)
    local_logits = dense_apply(params["local_head"], local).astype(jnp.float32)
    global_logits = dense_apply(params["global_head"], global_).astype(jnp.float32)
    return local_logits, global_logits


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
