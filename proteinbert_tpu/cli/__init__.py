"""CLI entry points (reference C15/C16, rebuilt as one console)."""

from proteinbert_tpu.cli.main import build_parser, main

__all__ = ["build_parser", "main"]
