"""Unified CLI: `python -m proteinbert_tpu <command>`.

The reference ships two argparse ETL scripts — one of which crashes at
parser construction from `est=`/`ype=` typos (reference
create_uniref_db.py:23,33; SURVEY ledger #9) — and NO training CLI (its
README promises one "Soon(TM)", reference README.md:5-6). Here everything
is one console with subcommands:

  create-uniref-db   UniRef90 XML(.gz) + GO OBO → SQLite (+ meta CSV)
  merge-uniref-dbs   combine task-array shard DBs (sums aggregates)
  create-h5          SQLite + FASTA + meta CSV → HDF5 training dataset
  pretrain           denoising pretrain from an HDF5 file or synthetic data
  smoke              the dummy_tests-equivalent end-to-end sanity run
  finetune           supervised task head on a (pretrained) trunk
                     (--register-head saves it into a head registry)
  eval-heads         score registered heads on labeled/synthetic data
                     (downstream eval harness; head_eval events)
  convert-torch      reference torch checkpoint → orbax run dir (migration)
  export-weights     orbax run dir → flat NPZ of named arrays (portability)
  import-weights     flat NPZ → orbax run dir (the export round trip)
  evaluate           score a checkpoint on a dataset (loss/acc/AUROC/p@k)
  diagnose           summarize a run's telemetry events (+ flight dump)
  data-bench         host input-pipeline throughput probe (batches/s)
  embed              trunk representations for sequences → HDF5/NPZ
  predict-go         GO-annotation probabilities from sequence alone
  predict-residues   fill '?'-masked residues, report per-position probs
  serve              online JSON/HTTP inference server (continuous
                     micro-batching over length buckets, docs/serving.md)
  map                resumable sharded batch inference: corpus → content-
                     addressed embedding store with checkpointed shard
                     cursors (--verify audits it; docs/mapping.md)

Cluster sharding (reference C17 parity): create-uniref-db reads
--task-index/--task-count or SLURM array env vars (utils/sharding.py) and
writes a per-shard DB that merge-uniref-dbs combines.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
import time
from typing import List, Optional

from proteinbert_tpu.utils.logging import log, start_log


# ------------------------------------------------------------------ types

def existing_file(path: str) -> str:
    """Validated argparse type (reference shared_utils/util.py:387-408)."""
    if not os.path.isfile(path):
        raise argparse.ArgumentTypeError(f"not a file: {path}")
    return path


def creatable_path(path: str) -> str:
    parent = os.path.dirname(os.path.abspath(path))
    if not os.path.isdir(parent):
        raise argparse.ArgumentTypeError(f"parent dir missing: {path}")
    return path


# -------------------------------------------------------------- config CLI

def apply_overrides(cfg, overrides: List[str]):
    """`--set model.local_dim=256` dotted-path overrides on the frozen
    dataclass config tree (the reference has no config system at all)."""
    for ov in overrides:
        if "=" not in ov:
            raise SystemExit(f"--set expects path=value, got {ov!r}")
        path, raw = ov.split("=", 1)
        keys = path.split(".")
        node_path = []
        node = cfg
        for k in keys[:-1]:
            if not hasattr(node, k):
                raise SystemExit(f"unknown config path {path!r}")
            node_path.append((node, k))
            node = getattr(node, k)
        leaf = keys[-1]
        if not hasattr(node, leaf):
            raise SystemExit(f"unknown config path {path!r}")
        current = getattr(node, leaf)
        value = _parse_value(raw, current)
        node = dataclasses.replace(node, **{leaf: value})
        for parent, k in reversed(node_path):
            node = dataclasses.replace(parent, **{k: node})
        cfg = node
    return cfg


def _parse_value(raw: str, current):
    if isinstance(current, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(current, int):
        return int(raw)
    if isinstance(current, float):
        return float(raw)
    if current is None or isinstance(current, tuple):
        try:
            value = json.loads(raw)
        except json.JSONDecodeError:
            if current is None:
                return raw  # string-valued optional fields
            raise SystemExit(
                f"expected a JSON list (e.g. [512,1024]) or null, got {raw!r}")
        # Configs must stay hashable (they are jit-static args).
        return tuple(value) if isinstance(value, list) else value
    return type(current)(raw)


# ------------------------------------------------------------- subcommands

def cmd_create_uniref_db(args) -> int:
    from proteinbert_tpu.etl import (
        UnirefToSqliteParser, parse_obo, save_meta_csv,
    )
    from proteinbert_tpu.utils.sharding import shard_file_name, task_identity

    task_index, task_count = task_identity(args.task_index, args.task_count)
    db_path = shard_file_name(args.output_db, task_index, task_count)
    log(f"parsing {args.uniref_xml} (shard {task_index}/{task_count}) → {db_path}")
    onto = parse_obo(args.go_meta)
    parser = UnirefToSqliteParser(
        args.uniref_xml, onto, db_path,
        shard_index=task_index, num_shards=task_count,
        max_entries=args.records_limit,
    )
    parser.parse()
    if args.go_meta_csv and task_count == 1:
        save_meta_csv(onto, args.go_meta_csv, counts=parser.go_record_counts,
                      total_records=parser.n_records_with_any_go)
        log(f"wrote GO meta CSV → {args.go_meta_csv}")
    elif args.go_meta_csv:
        log("sharded run: write the meta CSV from merge-uniref-dbs instead")
    return 0


def cmd_merge_uniref_dbs(args) -> int:
    from proteinbert_tpu.etl import merge_shard_dbs, parse_obo, read_aggregates, save_meta_csv
    from proteinbert_tpu.utils.sharding import all_shard_file_names

    if not args.shards and args.num_shards is None:
        raise SystemExit("merge-uniref-dbs needs --shards or --num-shards")
    shards = args.shards or all_shard_file_names(args.output_db, args.num_shards)
    missing = [s for s in shards if not os.path.isfile(s)]
    if missing:
        raise SystemExit(f"missing shard files: {missing}")
    n = merge_shard_dbs(shards, args.output_db)
    log(f"merged {len(shards)} shards ({n} rows) → {args.output_db}")
    if args.go_meta_csv:
        if not args.go_meta:
            raise SystemExit("--go-meta is required with --go-meta-csv")
        counts, n_any = read_aggregates(args.output_db)
        save_meta_csv(parse_obo(args.go_meta), args.go_meta_csv,
                      counts=counts, total_records=n_any)
        log(f"wrote merged GO meta CSV → {args.go_meta_csv}")
    return 0


def cmd_create_h5(args) -> int:
    from proteinbert_tpu.etl import create_h5_dataset

    n = create_h5_dataset(
        args.db, args.fasta, args.go_meta_csv, args.output,
        shuffle=not args.no_shuffle,
        min_records_to_keep_annotation=args.min_records,
        records_limit=args.records_limit,
    )
    log(f"created {args.output} with {n} rows")
    return 0


def _build_config(args):
    from proteinbert_tpu.configs import get_preset

    cfg = get_preset(args.preset)
    if args.max_steps is not None:
        cfg = cfg.replace(train=dataclasses.replace(
            cfg.train, max_steps=args.max_steps))
    if args.checkpoint_dir is not None:
        cfg = cfg.replace(checkpoint=dataclasses.replace(
            cfg.checkpoint, directory=args.checkpoint_dir))
    return apply_overrides(cfg, args.set or [])


def cmd_pretrain(args) -> int:
    import jax
    import numpy as np

    from proteinbert_tpu.data.dataset import (
        HDF5PretrainingDataset, make_pretrain_iterator,
    )
    from proteinbert_tpu.parallel import (
        make_mesh, maybe_initialize_distributed,
    )
    from proteinbert_tpu.train import Checkpointer, pretrain

    if getattr(args, "multihost", False):
        maybe_initialize_distributed(required=True)

    cfg = _build_config(args)

    if args.data is not None:
        ds = HDF5PretrainingDataset(
            args.data, cfg.data.seq_len, crop_seed=cfg.train.seed + 1)
        n_ann = ds.num_annotations
        if n_ann != cfg.model.num_annotations:
            log(f"setting model.num_annotations={n_ann} from {args.data}")
            cfg = cfg.replace(model=dataclasses.replace(
                cfg.model, num_annotations=n_ann))
    else:
        ds = _synthetic_dataset(cfg, n_min=256)
        log("no --data given: pretraining on synthetic random proteins")

    eval_batches = None
    if args.eval_frac:
        from proteinbert_tpu.data.dataset import train_eval_split

        ds, eval_ds = train_eval_split(ds, args.eval_frac,
                                       seed=cfg.train.seed)
        if cfg.train.eval_every == 0:
            cfg = cfg.replace(train=dataclasses.replace(
                cfg.train, eval_every=max(cfg.checkpoint.every_steps, 100)))
        # A small holdout evals at its own (smaller) batch size rather
        # than crashing the run at the first eval; zero per-host rows is
        # a config error surfaced NOW, not at step eval_every.
        eval_bs = min(cfg.data.batch_size,
                      len(eval_ds) // jax.process_count())
        if eval_bs == 0:
            raise SystemExit(
                f"--eval-frac {args.eval_frac} holds out {len(eval_ds)} "
                f"rows across {jax.process_count()} hosts — not enough "
                "for one eval batch; raise --eval-frac or the dataset size")
        eval_batches = lambda: make_pretrain_iterator(  # noqa: E731
            eval_ds, eval_bs, shuffle=False, num_epochs=1,
            process_index=jax.process_index(),
            process_count=jax.process_count())
        log(f"held-out eval: {len(eval_ds)} rows (batch {eval_bs}), every "
            f"{cfg.train.eval_every} steps")

    mesh = None
    if cfg.mesh.num_devices > 1:
        mesh = make_mesh(cfg.mesh)
        log(f"mesh: {dict(mesh.shape)} over {mesh.size} devices")

    # `tele` is assigned below; the factories read it at CALL time
    # (inside pretrain), so the pad_fraction/dropped-row metrics land in
    # the run's own registry when --events-jsonl telemetry is on.
    reg = lambda: tele.metrics if tele is not None else None  # noqa: E731
    if cfg.data.packing and cfg.data.buckets:
        raise SystemExit("data.packing and data.buckets are mutually "
                         "exclusive — pick one padding strategy")
    if cfg.data.packing:
        from proteinbert_tpu.data.packing import make_packed_iterator

        log(f"segment-aware packing: up to {cfg.data.pack_max_segments} "
            f"proteins per {cfg.data.seq_len}-token row")

        factory = lambda skip: make_packed_iterator(  # noqa: E731
            ds, cfg.data.batch_size, seed=cfg.train.seed,
            num_epochs=cfg.data.num_epochs,
            process_index=jax.process_index(),
            process_count=jax.process_count(), skip_batches=skip,
            max_segments=cfg.data.pack_max_segments,
            max_open=cfg.data.pack_open_bins, metrics=reg())
    elif cfg.data.buckets:
        from proteinbert_tpu.data.dataset import make_bucketed_iterator

        log(f"length bucketing: {cfg.data.buckets}")

        factory = lambda skip: make_bucketed_iterator(  # noqa: E731
            ds, cfg.data.batch_size, cfg.data.buckets, seed=cfg.train.seed,
            num_epochs=cfg.data.num_epochs,
            process_index=jax.process_index(),
            process_count=jax.process_count(), skip_batches=skip,
            metrics=reg())
    else:
        factory = lambda skip: make_pretrain_iterator(  # noqa: E731
            ds, cfg.data.batch_size, seed=cfg.train.seed,
            num_epochs=cfg.data.num_epochs,
            process_index=jax.process_index(),
            process_count=jax.process_count(), skip_batches=skip)
    ck = Checkpointer(cfg.checkpoint.directory,
                      max_to_keep=cfg.checkpoint.max_to_keep,
                      async_save=cfg.checkpoint.async_save)
    if jax.process_index() == 0:
        # Downstream --pretrained commands reconstruct the exact run
        # config from this file, no repeated --pretrained-set flags.
        _save_run_config(cfg, cfg.checkpoint.directory)
    tele = None
    # Only host 0 writes (every process would append duplicate, possibly
    # torn, lines to a shared file under --multihost; flight dumps are
    # pid-stamped but one forensics stream is what diagnose wants).
    if getattr(args, "events_jsonl", None) and jax.process_index() == 0:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
        tele.flight.install_excepthook()  # unhandled exception → dump
    log_fn = None
    mf = None
    # Only host 0 writes (every process would append duplicate, possibly
    # torn, lines to a shared file under --multihost).
    if args.metrics_jsonl and jax.process_index() == 0:
        mf = open(args.metrics_jsonl, "a", buffering=1)

        def log_fn(step, metrics):
            clean = {k: (v if isinstance(v, str) or math.isfinite(v)
                         else None)
                     for k, v in metrics.items()}
            # Wall-clock stamp: lets a slow window in the stream be
            # correlated offline with checkpoint/eval cadence and with
            # external events (tunnel flaps) — the r3 sustained run's
            # collapse was unattributable without it.
            mf.write(json.dumps({"step": step, "t": round(time.time(), 2),
                                 **clean}) + "\n")

    try:
        if args.profile_dir:
            from proteinbert_tpu.utils.profiling import device_trace

            with device_trace(args.profile_dir):
                out = pretrain(cfg, factory, checkpointer=ck, mesh=mesh,
                               eval_batches=eval_batches, log_fn=log_fn,
                               telemetry=tele)
            log(f"jax profiler trace → {args.profile_dir} "
                "(view in TensorBoard/Perfetto)")
        else:
            out = pretrain(cfg, factory, checkpointer=ck, mesh=mesh,
                           eval_batches=eval_batches, log_fn=log_fn,
                           telemetry=tele)
    finally:
        # Always await in-flight async checkpoint saves — a halt (e.g.
        # NonFiniteLossError) must not abandon a half-written checkpoint.
        ck.close()
        if mf is not None:
            mf.close()
        if tele is not None:
            _export_metrics(tele)
            tele.close()
    perf = out["perf"]
    if perf:
        log(f"done: {perf.get('residues_per_sec_per_chip', 0):.0f} "
            f"residues/s/chip, MFU {perf.get('mfu', 0):.3f}")
    if args.history_json:
        with open(args.history_json, "w") as f:
            json.dump(out["history"], f, indent=2)
    if out.get("preempted"):
        # EX_TEMPFAIL: tells orchestrators "not done — requeue me".
        log("run was preempted; exiting 75 so a supervisor requeues it")
        return 75
    if out.get("early_stopped"):
        # A deliberate, checkpointed stop (eval stalled past
        # train.early_stop_patience) — done, NOT a requeue case.
        log("run early-stopped on a stalled eval; final state is "
            "checkpointed")
    return 0


def cmd_finetune(args) -> int:
    """Fine-tune a task head on a pretrained trunk (SURVEY C14, completed —
    the reference's fine-tune harness is commented-out code, reference
    utils.py:348-493). --data/--eval-data read the TSV format of
    data/finetune_data.py; without --data, synthetic labeled batches
    (data/synthetic.make_task_batches) serve as the smoke path."""
    import jax
    import numpy as np

    from proteinbert_tpu.configs import (
        FinetuneConfig, TaskConfig, get_preset,
    )
    from proteinbert_tpu.data.finetune_data import batch_task_data, load_task_tsv
    from proteinbert_tpu.data.synthetic import make_task_batches
    from proteinbert_tpu.train import (
        Checkpointer, create_train_state, finetune,
    )

    base = get_preset(args.preset)
    cfg = FinetuneConfig(
        model=base.model,
        data=base.data,
        task=TaskConfig(kind=args.task, num_outputs=args.num_outputs,
                        epochs=args.epochs, freeze_trunk=args.freeze_trunk),
    )
    if args.checkpoint_dir:
        cfg = cfg.replace(checkpoint=dataclasses.replace(
            cfg.checkpoint, directory=args.checkpoint_dir))
    cfg = apply_overrides(cfg, args.set or [])

    trunk = None
    if args.pretrained and (os.path.abspath(args.pretrained)
                            == os.path.abspath(cfg.checkpoint.directory)):
        # Sharing the dir would interleave fine-tune epochs with pretrain
        # steps in one orbax manager and clobber the pretrain run's
        # config.json with a FinetuneConfig.
        raise SystemExit(
            "--checkpoint-dir must differ from --pretrained "
            f"({args.pretrained}): fine-tune epochs get their own run dir")
    if args.pretrained:
        # Rebuild the pretrain-time state template — from the run dir's
        # config.json when present, else the preset. Only model.* of the
        # fine-tune --set overrides leak in (optimizer/train overrides
        # meant for the FINE-TUNE run would change the template's
        # opt_state structure and break the orbax restore); anything the
        # pretrain run itself customized beyond config.json goes through
        # --pretrained-set.
        pre_cfg = _pretrain_run_config(
            args.pretrained, args.preset,
            [ov for ov in (args.set or []) if ov.startswith("model.")]
            + (args.pretrained_set or []))
        template = create_train_state(
            jax.random.PRNGKey(pre_cfg.train.seed), pre_cfg)
        ck = Checkpointer(args.pretrained, async_save=False)
        state, _ = ck.restore(template)
        ck.close()
        if state is None:
            raise SystemExit(f"no checkpoint found in {args.pretrained}")
        trunk = state.params
        log(f"loaded pretrained trunk from {args.pretrained} "
            f"(step {int(state.step)})")
        # The fine-tune model geometry must BE the trunk's geometry —
        # pre_cfg carries it (config.json / overrides), the preset may not.
        cfg = cfg.replace(model=pre_cfg.model)

    rng = np.random.default_rng(cfg.train.seed)
    if args.data:
        tokens, labels = load_task_tsv(args.data, cfg.task.kind,
                                       cfg.data.seq_len)
        train_batches = lambda epoch: iter(batch_task_data(  # noqa: E731
            tokens, labels, cfg.data.batch_size,
            np.random.default_rng(cfg.train.seed + epoch)))
        n_train = len(tokens) // cfg.data.batch_size
        if args.eval_data:
            ev_tokens, ev_labels = load_task_tsv(
                args.eval_data, cfg.task.kind, cfg.data.seq_len)
            eval_batches = lambda: iter(batch_task_data(  # noqa: E731
                ev_tokens, ev_labels, cfg.data.batch_size))
        else:
            eval_batches = None
    else:
        log("no --data given: fine-tuning on synthetic labeled batches")
        n = max(8 * cfg.data.batch_size, 64)
        train_b = make_task_batches(n, rng, cfg.task.kind,
                                    cfg.task.num_outputs,
                                    cfg.data.seq_len, cfg.data.batch_size)
        eval_b = make_task_batches(n // 4, rng, cfg.task.kind,
                                   cfg.task.num_outputs, cfg.data.seq_len,
                                   cfg.data.batch_size)
        train_batches = lambda epoch: iter(train_b)  # noqa: E731
        eval_batches = lambda: iter(eval_b)  # noqa: E731
        n_train = len(train_b)

    log(f"finetune {cfg.task.kind}: {n_train} train batches/epoch, "
        f"{cfg.task.epochs} epochs → checkpoints in "
        f"{cfg.checkpoint.directory}")
    ck = Checkpointer(cfg.checkpoint.directory,
                      max_to_keep=cfg.checkpoint.max_to_keep,
                      async_save=cfg.checkpoint.async_save)
    # Provenance: record the resolved FinetuneConfig beside the epochs
    # (same convention — and the same host-0 guard — as pretrain run dirs).
    if jax.process_index() == 0:
        _save_run_config(cfg, cfg.checkpoint.directory)
    tele = None
    if getattr(args, "events_jsonl", None) and jax.process_index() == 0:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
        tele.flight.install_excepthook()  # unhandled exception → dump
    registry = None
    if args.register_head:
        from proteinbert_tpu.heads import HeadRegistry

        registry = HeadRegistry(args.register_head)
        log(f"will register the trained head into {registry.directory}")
    try:
        out = finetune(cfg, train_batches, eval_batches=eval_batches,
                       pretrained_trunk=trunk, checkpointer=ck,
                       telemetry=tele, registry=registry,
                       register_name=args.head_name)
    finally:
        ck.close()
        if tele is not None:
            _export_metrics(tele)
            tele.close()
    best = out["best"]
    log(f"best epoch {best['epoch']}: score {best['score']:.4f}")
    if out.get("head_id"):
        log(f"registered head {out['head_id']} "
            f"({cfg.task.kind}) — serve it with: pbt serve --registry "
            f"{args.register_head} --heads {out['head_id']}")
    if args.history_json:
        with open(args.history_json, "w") as f:
            json.dump(out["history"], f, indent=2)
    return 0


def cmd_smoke(args) -> int:
    """dummy_tests.main() equivalent (reference dummy_tests.py:96-155):
    synthetic proteins → tiny config by default → loss must decrease.
    --preset/--data are honored if given (the smoke subparser defaults
    preset to tiny; pretrain defaults it to base)."""
    if args.max_steps is None:
        args.max_steps = 250
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        if args.checkpoint_dir is None:
            args.checkpoint_dir = os.path.join(d, "ck")
        rc = cmd_pretrain(args)
    return rc


def _read_named_seqs(args) -> tuple:
    """(ids, seqs) from --fasta, --seqs-file (id<TAB>seq or bare seq per
    line), or positional sequences — shared by the inference commands."""
    if getattr(args, "fasta", None):
        from proteinbert_tpu.etl.fasta import iter_fasta

        pairs = list(iter_fasta(args.fasta))  # name = first header word
        return [name for name, _ in pairs], [s for _, s in pairs]
    if getattr(args, "seqs_file", None):
        ids, seqs = [], []
        with open(args.seqs_file) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                if "\t" in line:
                    name, seq = line.split("\t", 1)
                else:
                    name, seq = f"seq{i}", line
                ids.append(name)
                seqs.append(seq)
        return ids, seqs
    if getattr(args, "seqs", None):
        return [f"seq{i}" for i in range(len(args.seqs))], list(args.seqs)
    raise SystemExit("provide --fasta, --seqs-file, or positional sequences")


def _export_metrics(tele) -> None:
    """Persist the run's metrics registry beside the events stream: one
    appended JSONL snapshot (`<events>.metrics.jsonl`, a time series
    across requeues) plus the Prometheus textfile (`<events>.prom`,
    last-run-wins for a textfile collector). Best-effort — the run's
    outcome must never depend on a metrics sink."""
    if tele.events is None:
        return
    base = tele.events.path
    try:
        tele.metrics.write_snapshot(base + ".metrics.jsonl")
        tele.metrics.write_prometheus(base + ".prom")
    except OSError as e:
        log(f"could not export telemetry metrics: {e}")


def _save_run_config(cfg, directory: str) -> None:
    """Record the resolved config beside a run's checkpoints (the file
    _pretrain_run_config and the --pretrained consumers read back)."""
    from proteinbert_tpu.configs import save_config

    os.makedirs(directory, exist_ok=True)
    save_config(cfg, os.path.join(os.path.abspath(directory), "config.json"))


def _synthetic_dataset(cfg, n_min: int):
    """Synthetic random-protein fallback dataset shared by pretrain /
    evaluate / data-bench when no --data is given."""
    import numpy as np

    from proteinbert_tpu.data.dataset import InMemoryPretrainingDataset
    from proteinbert_tpu.data.synthetic import make_random_proteins

    rng = np.random.default_rng(cfg.train.seed)
    seqs, ann = make_random_proteins(
        max(4 * cfg.data.batch_size, n_min), rng,
        num_annotations=cfg.model.num_annotations)
    return InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)


def _pretrain_run_config(pretrained: str, preset: str, overrides):
    """The config describing a pretrain run dir: its saved config.json
    when present (every run dir this framework writes carries one), else
    the named preset; --pretrained-set overrides apply on top either way."""
    from proteinbert_tpu.configs import get_preset, load_config

    path = os.path.join(pretrained, "config.json")
    if os.path.isfile(path):
        try:
            cfg = load_config(path)
        except (ValueError, TypeError, OSError) as e:
            raise SystemExit(
                f"corrupt config.json in {pretrained} ({e}); delete it and "
                "pass --preset/--pretrained-set describing the run instead")
    else:
        cfg = get_preset(preset)
    return apply_overrides(cfg, overrides or [])


def _load_inference_trunk(args):
    """(params, cfg) for the inference commands: recover the pretrain-run
    config (config.json, or --preset + --pretrained-set) and load the
    latest checkpoint."""
    from proteinbert_tpu import inference

    cfg = _pretrain_run_config(args.pretrained, args.preset,
                               args.pretrained_set)
    params, step = inference.load_trunk(args.pretrained, cfg)
    log(f"loaded trunk from {args.pretrained} (step {step})")
    return params, cfg


def _write_run_dir(cfg, params, step: int, output: str) -> None:
    """Seed an orbax run directory from imported params (shared by
    convert-torch and import-weights): fresh TrainState carrying the
    given params and iteration counter, saved synchronously."""
    import jax

    from proteinbert_tpu.train import Checkpointer, create_train_state

    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    state = state.replace(
        params=params, step=jax.numpy.asarray(step, jax.numpy.int32))
    ck = Checkpointer(output, async_save=False)
    ck.save(step, state, {"batches_consumed": step})
    ck.close()
    _save_run_config(cfg, output)


def cmd_convert_torch(args) -> int:
    """Reference torch checkpoint → an orbax run directory this
    framework's --pretrained / resume flags consume (interop.py). The
    optimizer state starts fresh: the reference's Adam moments live in
    torch layout and its attention params were never trained anyway
    (SURVEY ledger #1)."""
    import jax

    from proteinbert_tpu import interop
    from proteinbert_tpu.configs import get_preset

    cfg = apply_overrides(get_preset(args.preset), args.set or [])
    params, ckpt_step = interop.load_reference_checkpoint(
        args.torch_ckpt, cfg.model,
        init_key=jax.random.PRNGKey(cfg.train.seed))
    step = args.step if args.step is not None else ckpt_step
    _write_run_dir(cfg, params, step, args.output)
    log(f"converted {args.torch_ckpt} → {args.output} (step {step})")
    return 0


def cmd_evaluate(args) -> int:
    """Standalone held-out evaluation on any checkpoint + dataset —
    shares train/trainer.evaluate_batches with the pretrain loop's
    periodic eval and covers EVERY row (smaller tail batch, row-weighted
    mean). Prints one JSON object (loss, local/global terms, accuracy,
    GO ranking metrics).

    --like-step derives the corruption keys the way the training run's
    eval at that history step did. The values match exactly when the
    batches match — holdout divisible by the eval batch size (training's
    iterator drops tail batches; this command keeps them) and no
    sequence over seq_len-2 (training re-crops long rows from a shared
    RNG stream; this command head-truncates deterministically)."""
    import jax
    import numpy as np

    from proteinbert_tpu import inference
    from proteinbert_tpu.train.trainer import eval_base_key, evaluate_batches

    cfg = _pretrain_run_config(args.pretrained, args.preset,
                               args.pretrained_set)

    if args.data:
        from proteinbert_tpu.data.dataset import HDF5PretrainingDataset

        ds = HDF5PretrainingDataset(args.data, cfg.data.seq_len)
        n_ann = ds.num_annotations
        if n_ann != cfg.model.num_annotations:
            # A value from --pretrained-set OR the run dir's config.json
            # states what the checkpoint was trained with — silently
            # "adapting" to the dataset would just move the failure into
            # an opaque orbax restore mismatch.
            authoritative = any(
                "num_annotations" in ov for ov in (args.pretrained_set or [])
            ) or os.path.isfile(
                os.path.join(args.pretrained, "config.json"))
            if authoritative:
                raise SystemExit(
                    f"{args.data} has {n_ann} annotation columns but the "
                    f"checkpoint was trained with "
                    f"{cfg.model.num_annotations} — these must match")
            log(f"setting model.num_annotations={n_ann} from {args.data}")
            cfg = cfg.replace(model=dataclasses.replace(
                cfg.model, num_annotations=n_ann))
    else:
        ds = _synthetic_dataset(cfg, n_min=128)
        log("no --data given: evaluating on synthetic random proteins")

    if len(ds) == 0:
        raise SystemExit("dataset is empty")

    state, step = inference.load_state(args.pretrained, cfg)
    log(f"loaded checkpoint from {args.pretrained} (step {step})")

    bs = min(cfg.data.batch_size, len(ds))

    def batches():  # ordered, exact coverage; the tail batch is smaller
        for start in range(0, len(ds), bs):
            yield ds.get_batch(np.arange(start, min(start + bs, len(ds))))

    base_key = (eval_base_key(cfg, args.like_step)
                if args.like_step is not None
                else jax.random.PRNGKey(args.seed))
    metrics, n, rows = evaluate_batches(
        state, batches(), lambda b: b, cfg, base_key, prefix="",
        max_batches=args.max_batches)
    # Valid JSON even for degenerate inputs: non-finite → null (same
    # sanitation as the pretrain --metrics-jsonl path).
    result = {"step": step, "batches": n, "rows": rows,
              **{k: (round(v, 6) if math.isfinite(v) else None)
                 for k, v in metrics.items()}}
    print(json.dumps(result))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=2)
    return 0


def cmd_eval_heads(args) -> int:
    """Downstream eval harness (ISSUE 8): score registered task heads
    against the resident trunk — per-residue accuracy / accuracy +
    AUC proxy / Spearman by task kind (heads/eval.py) — emitting one
    schema-versioned `head_eval` event per head so finetune-quality
    regressions gate through the bench-trajectory sentinel like perf
    does. One JSON line per head on stdout."""
    import numpy as np

    from proteinbert_tpu.heads import HeadRegistry, trunk_fingerprint
    from proteinbert_tpu.heads.eval import evaluate_heads

    params, cfg = _load_inference_trunk(args)
    registry = HeadRegistry(args.registry)
    fp = None if args.no_trunk_check else trunk_fingerprint(params)
    if args.heads and args.heads != "all":
        # Explicit ids are strict (clean exit on mismatch/corruption);
        # implicit "all" below skips unservable artifacts with a
        # warning — a registry normally accumulates heads across
        # re-pretrains and one stale entry must not block the rest.
        from proteinbert_tpu.heads import HeadRegistryError

        try:
            heads = [registry.load(h, trunk_fp=fp)
                     for h in args.heads.split(",") if h]
        except HeadRegistryError as e:
            raise SystemExit(f"--heads: {e}")
    else:
        from proteinbert_tpu.heads import HeadRegistryError

        heads = []
        for m in registry.list_heads():
            try:
                heads.append(registry.load(m["head_id"], trunk_fp=fp))
            except HeadRegistryError as e:
                log(f"skipping head {m['head_id']} ({m.get('name')}): {e}")
    if not heads:
        raise SystemExit(
            f"no evaluable heads in {registry.directory}")

    if args.data:
        from proteinbert_tpu.data.finetune_data import (
            batch_task_data, load_task_tsv,
        )

        kinds = sorted({h.task.kind for h in heads})
        if len(kinds) > 1:
            raise SystemExit(
                f"--data is a single-task TSV but the selected heads "
                f"span {kinds}; select heads of one kind")
        tokens, labels = load_task_tsv(args.data, kinds[0],
                                       cfg.data.seq_len)
        bs = min(args.batch_size, len(tokens))
        batches_for = lambda head: batch_task_data(  # noqa: E731
            tokens, labels, bs)
    else:
        log("no --data given: evaluating on synthetic labeled batches")
        from proteinbert_tpu.data.synthetic import make_task_batches

        batches_for = lambda head: make_task_batches(  # noqa: E731
            max(4 * args.batch_size, 32),
            np.random.default_rng(args.seed), head.task.kind,
            head.task.num_outputs, cfg.data.seq_len, args.batch_size)

    tele = None
    if args.events_jsonl:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
    try:
        results = evaluate_heads(params, cfg.model, heads, batches_for,
                                 telemetry=tele)
    finally:
        if tele is not None:
            tele.close()
    for hid, m in results.items():
        print(json.dumps({"head_id": hid, **m}))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=2)
    return 0


def cmd_diagnose(args) -> int:
    """Summarize a telemetry events JSONL (+ optional flight-recorder
    dump): step-rate trend, stall top-list, boundary overlap ratio, and
    the last events before death — the one-artifact post-mortem the
    obs subsystem exists for. No jax import: runs anywhere the
    artifacts can be copied."""
    from proteinbert_tpu.obs import read_events, validate_flight_dump
    from proteinbert_tpu.obs.diagnose import (
        render, render_fleet, render_map, render_serve, summarize,
        summarize_fleet, summarize_map, summarize_serve,
    )

    records = read_events(args.events)
    if not records:
        raise SystemExit(f"no valid event records in {args.events}")
    flight = None
    if args.flight:
        with open(args.flight) as f:
            flight = json.load(f)
        try:
            validate_flight_dump(flight)
        except ValueError as e:
            raise SystemExit(f"{args.flight} is not a valid flight dump: {e}")
    # The serve/map/fleet sections render when asked for (--serve /
    # --map / --fleet) or when the stream carries their records (a
    # mixed stream — e.g. the fleet's MERGED stream — shows all).
    has_serve = any(r["event"].startswith("serve_") for r in records)
    if args.serve and not has_serve:
        raise SystemExit(f"--serve: no serve_* records in {args.events}")
    has_map = any(r["event"].startswith("map_") for r in records)
    if args.map and not has_map:
        raise SystemExit(f"--map: no map_* records in {args.events}")
    has_fleet = any(r["event"].startswith("fleet_") for r in records)
    if args.fleet and not has_fleet:
        raise SystemExit(f"--fleet: no fleet_* records in {args.events}")
    if args.trace_id and not args.fleet:
        raise SystemExit("--trace-id requires --fleet (it selects one "
                         "causal chain from the merged fleet stream)")
    serve_summary = (summarize_serve(records, slow_top=args.slow_top)
                     if has_serve else None)
    map_summary = summarize_map(records) if has_map else None
    fleet_summary = (summarize_fleet(records, trace_id=args.trace_id,
                                     slow_top=args.slow_top)
                     if has_fleet else None)
    if args.trace_perfetto:
        # Cross-process lanes (router + one per replica attempt) from
        # the merged stream — the fleet counterpart of the per-request
        # lanes `pbt serve --trace-perfetto` exports live.
        from proteinbert_tpu.obs.diagnose import export_fleet_spans
        from proteinbert_tpu.obs.tracing import SpanCollector

        if not has_fleet:
            raise SystemExit(f"--trace-perfetto: no fleet_* records in "
                             f"{args.events}")
        collector = SpanCollector()
        n = export_fleet_spans(records, collector,
                               trace_id=args.trace_id)
        collector.dump(args.trace_perfetto)
        print(f"wrote {n} fleet trace lane group(s) to "
              f"{args.trace_perfetto}")
    summary = summarize(records, flight=flight,
                        slow_top=args.slow_top, last=args.last)
    if serve_summary is not None:
        summary["serve"] = serve_summary
    if map_summary is not None:
        summary["map"] = map_summary
    if fleet_summary is not None:
        summary["fleet"] = fleet_summary
    if args.json:
        print(json.dumps(summary))
    elif args.fleet:
        print(render_fleet(fleet_summary))
    elif args.serve:
        print(render_serve(serve_summary))
    elif args.map:
        print(render_map(map_summary))
    else:
        print(render(summary))
        if serve_summary is not None:
            print(render_serve(serve_summary))
        if fleet_summary is not None:
            print(render_fleet(fleet_summary))
        if map_summary is not None:
            print(render_map(map_summary))
    return 0


def cmd_data_bench(args) -> int:
    """Measure the HOST side of the input pipeline in isolation — is the
    chip going to starve? The reference's version of this probe never
    varied what it claimed to sweep (reference utils.py:30-68, SURVEY
    ledger #11); this one times the real iterator (tokenization, HDF5
    block reads, shuffling) with and without the prefetch thread and
    prints one JSON line per variant."""
    import time

    import numpy as np

    from proteinbert_tpu.configs import get_preset

    cfg = apply_overrides(get_preset(args.preset), args.set or [])

    def make_ds():
        # Fresh dataset per timed variant: sharing one would let the
        # second variant ride the block cache the first just warmed, and
        # the comparison would measure cache reuse instead of prefetch.
        if args.data:
            from proteinbert_tpu.data.dataset import HDF5PretrainingDataset

            # Same construction as cmd_pretrain (incl. re-crop seed): the
            # probe must time the pipeline training actually runs.
            return HDF5PretrainingDataset(
                args.data, cfg.data.seq_len, crop_seed=cfg.train.seed + 1)
        return _synthetic_dataset(cfg, n_min=8 * cfg.data.batch_size)

    if not args.data:
        log("no --data given: probing on synthetic random proteins")

    n = args.batches
    variants = [("direct", 0)]
    if cfg.data.prefetch_depth > 0:
        variants.append(("prefetch", cfg.data.prefetch_depth))
    else:
        log("data.prefetch_depth=0: prefetch variant skipped")

    def run(prefetch_depth):
        ds = make_ds()
        if len(ds) == 0:
            raise SystemExit("dataset is empty")
        bs = min(cfg.data.batch_size, len(ds))
        if cfg.data.buckets:  # the iterator the `long` preset trains with
            from proteinbert_tpu.data.dataset import make_bucketed_iterator

            it = make_bucketed_iterator(ds, bs, cfg.data.buckets,
                                        seed=cfg.train.seed)
        else:
            from proteinbert_tpu.data.dataset import make_pretrain_iterator

            it = make_pretrain_iterator(ds, bs, seed=cfg.train.seed)
        if prefetch_depth:
            from proteinbert_tpu.data.prefetch import prefetch

            it = prefetch(it, prefetch_depth)
        next(it)  # warm caches / start the thread
        t0 = time.perf_counter()
        got = 0
        positions = 0
        for _ in range(n):
            try:
                batch = next(it)
            except StopIteration:
                break
            got += 1
            # tokens.size, not rows·seq_len: bucketed batches are sliced
            # to their bucket width and must not be counted at full L.
            positions += batch["tokens"].size
        return got, positions, time.perf_counter() - t0

    for name, depth in variants:
        got, positions, dt = run(depth)
        if not got:
            raise SystemExit("dataset too small for one timed batch")
        print(json.dumps({
            "variant": name,
            "batches_per_sec": round(got / dt, 2),
            "residues_per_sec": round(positions / dt, 1),
            "batch_ms": round(1000 * dt / got, 3),
            "batches": got,
        }))
    return 0


def cmd_export_weights(args) -> int:
    """Trained params → flat NPZ (export.py): slash-joined pytree paths,
    per-block entries, fp32 — readable by any numpy consumer with no
    dependency on this codebase (unlike the reference's pickled-module
    save, reference utils.py:339-343)."""
    from proteinbert_tpu import export

    params, cfg = _load_inference_trunk(args)
    n = export.export_params(params, args.output)
    log(f"wrote {n} arrays → {args.output}")
    return 0


def cmd_import_weights(args) -> int:
    """Flat NPZ (export-weights format, or produced by any numpy-speaking
    tool) → an orbax run directory the --pretrained / resume flags
    consume. Optimizer state starts fresh, like convert-torch."""
    import jax

    from proteinbert_tpu import export
    from proteinbert_tpu.configs import get_preset
    from proteinbert_tpu.train import create_train_state

    cfg = apply_overrides(get_preset(args.preset), args.set or [])
    try:
        params = export.import_params(args.weights,
                                      scan_blocks=cfg.model.scan_blocks)
    except ValueError as e:
        # Inconsistent block subtrees / ragged shapes / non-integer block
        # keys all surface as ValueError from the tree rebuild.
        raise SystemExit(
            f"{args.weights} is not a well-formed export-weights NPZ: {e}")
    template = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    want = jax.tree.map(lambda a: (a.shape, str(a.dtype)), template.params)
    got = jax.tree.map(lambda a: (a.shape, str(a.dtype)), params)
    if want != got:
        raise SystemExit(
            f"{args.weights} does not match the configured model geometry "
            "(run with the same --preset/--set the weights were trained "
            "with)")
    _write_run_dir(cfg, params, args.step, args.output)
    log(f"imported {args.weights} → {args.output} (step {args.step})")
    return 0


def cmd_embed(args) -> int:
    """Write trunk representations for downstream models — the pretrained
    encoder's raison d'être per the paper the reference replicates
    (reference README.md:9), absent there because no inference path
    exists (reference README.md:5-6)."""
    import numpy as np

    from proteinbert_tpu import inference

    params, cfg = _load_inference_trunk(args)
    ids, seqs = _read_named_seqs(args)
    if args.output.endswith(".npz"):
        # NPZ cannot be appended to — in-memory path (fine for small N).
        out = inference.embed(params, cfg, seqs, batch_size=args.batch_size,
                              per_residue=args.per_residue)
        np.savez(args.output, ids=np.array(ids), **out)
    else:
        # HDF5 streams batch-by-batch: host memory stays O(batch) no
        # matter how many sequences the FASTA holds.
        import h5py

        with h5py.File(args.output, "w") as h5f:
            h5f.create_dataset("ids", data=[i.encode() for i in ids],
                               dtype=h5py.string_dtype())
            dsets = {}
            n = 0
            for out in inference.embed_batches(
                params, cfg, seqs, batch_size=args.batch_size,
                per_residue=args.per_residue,
            ):
                rows = len(next(iter(out.values())))
                for k, v in out.items():
                    if k not in dsets:
                        dsets[k] = h5f.create_dataset(
                            k, shape=(0,) + v.shape[1:],
                            maxshape=(None,) + v.shape[1:], dtype=v.dtype,
                            chunks=(max(args.batch_size, 1),) + v.shape[1:])
                    dsets[k].resize(n + rows, axis=0)
                    dsets[k][n : n + rows] = v
                n += rows
    log(f"embedded {len(seqs)} sequences → {args.output}")
    return 0


def cmd_predict_go(args) -> int:
    """Predict GO annotations from sequence alone (TSV to --output or
    stdout: id, annotation column index, GO id if known, name if known,
    probability)."""
    from proteinbert_tpu import inference

    params, cfg = _load_inference_trunk(args)
    ids, seqs = _read_named_seqs(args)

    go_ids = None
    if args.data:  # annotation column → GO id, from the training dataset
        import h5py

        with h5py.File(args.data, "r") as h5f:
            go_ids = [g.decode() if isinstance(g, bytes) else g
                      for g in h5f["included_annotations"][:]]
    names = {}
    if args.go_meta_csv:
        from proteinbert_tpu.etl.go_ontology import load_meta_csv

        names = {r["id"]: r["name"] for r in load_meta_csv(args.go_meta_csv)}

    top = inference.predict_go(params, cfg, seqs,
                               batch_size=args.batch_size, top_k=args.top_k)
    sink = open(args.output, "w") if args.output else sys.stdout
    try:
        for name, row in zip(ids, top):
            for col, prob in row:
                gid = go_ids[col] if go_ids and col < len(go_ids) else ""
                sink.write(f"{name}\t{col}\t{gid}\t{names.get(gid, '')}\t"
                           f"{prob:.4f}\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    return 0


def cmd_predict_residues(args) -> int:
    """Fill '?'-masked residues (the denoising task run as inference)."""
    from proteinbert_tpu import inference

    params, cfg = _load_inference_trunk(args)
    ids, seqs = _read_named_seqs(args)
    filled, _ = inference.predict_residues(params, cfg, seqs,
                                           batch_size=args.batch_size)
    sink = open(args.output, "w") if args.output else sys.stdout
    try:
        for name, seq in zip(ids, filled):
            sink.write(f"{name}\t{seq}\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    return 0


def cmd_serve(args) -> int:
    """Online inference server (ISSUE 5 tentpole): the serving subsystem
    of proteinbert_tpu/serve/ behind a stdlib HTTP JSON endpoint.
    Continuous micro-batching over the run's length buckets
    (cfg.data.buckets, else one full-length bucket), bounded queue with
    typed rejections, LRU result cache, graceful drain on SIGTERM/
    SIGINT (in-flight batches finish; new work gets 503)."""
    import threading
    import time as _time

    from proteinbert_tpu.heads import TrunkMismatchError
    from proteinbert_tpu.serve import Server
    from proteinbert_tpu.serve.http import make_http_server
    from proteinbert_tpu.train.resilience import GracefulShutdown

    if args.compile_cache_dir:
        # Must be armed before the first compile (the trunk load below
        # jits): restarted/new replicas deserialize warm executables
        # instead of re-paying per-kind warmup (fleet boot path).
        from proteinbert_tpu.utils.compat import configure_compile_cache

        configure_compile_cache(args.compile_cache_dir)
        log(f"persistent compilation cache: {args.compile_cache_dir}")

    params, cfg = _load_inference_trunk(args)

    def _candidate_loader(source: str):
        """Rollout candidate arm (ISSUE 20): load a second trunk from
        another run directory under the SAME model config — the
        blue-green flip swaps weights, never executable shapes."""
        from proteinbert_tpu import inference

        cand, step = inference.load_trunk(source, cfg)
        log(f"rollout candidate trunk loaded from {source} (step {step})")
        return cand

    # Resolve the effective quant arm (flag > run config) up front so
    # an impossible combination is a clean operator-facing exit, not a
    # construction traceback from deep inside the dispatcher.
    effective_quant = args.quant or getattr(
        getattr(cfg, "serve", None), "quant", "fp32")
    if args.serve_mode == "ragged" and effective_quant == "int8_act":
        raise SystemExit(
            "--quant int8_act is a bucketed-mode option: the packed "
            "executables have no activation fake-quant variant — use "
            "--quant int8 for weight-only quantized ragged serving "
            "(docs/serving.md, int8 arm)")

    mesh = None
    if args.mesh:
        from proteinbert_tpu.parallel import make_mesh

        mesh = make_mesh(cfg.mesh)
        log(f"serving with batch-dim sharding over {dict(mesh.shape)} "
            f"({mesh.size} devices)")

    tele = None
    if args.events_jsonl or args.trace_perfetto or args.slo:
        from proteinbert_tpu.obs import Telemetry

        # spans=True arms the host SpanCollector the request traces
        # replay into; --events-jsonl may be absent (spans/SLO-only
        # runs still get the flight ring + metrics registry).
        tele = Telemetry(events_path=args.events_jsonl,
                         spans=bool(args.trace_perfetto))
        tele.flight.install_excepthook()

    slos = []
    if args.slo:
        from proteinbert_tpu.obs.slo import parse_slos

        slos = parse_slos(args.slo)
        log("slo objectives: " + ", ".join(
            f"{o.name} ({o.kind}, target {o.target:g}, "
            f"window {o.window_s:g}s)" for o in slos))

    registry = None
    head_ids = []
    if args.registry:
        from proteinbert_tpu.heads import (
            HeadRegistry, HeadRegistryError, TrunkMismatchError,
            trunk_fingerprint,
        )

        registry = HeadRegistry(args.registry)
        if args.heads and args.heads != "all":
            # Explicitly named heads are STRICT: a mismatch/corruption
            # is a config error the operator must see (clean exit, not
            # a traceback).
            try:
                head_ids = [h for h in args.heads.split(",") if h]
                fp = trunk_fingerprint(params)
                for h in head_ids:
                    registry.load(h, trunk_fp=fp)
            except HeadRegistryError as e:
                raise SystemExit(f"--heads: {e}")
        else:
            # Implicit "all" tolerates an imperfect store (a registry
            # normally accumulates heads across re-pretrains): serve
            # every trunk-compatible head, skip the rest with a
            # warning — one stale artifact must not take the whole
            # multi-tenant server down.
            fp = trunk_fingerprint(params)
            for m in registry.list_heads():
                try:
                    registry.load(m["head_id"], trunk_fp=fp)
                except (TrunkMismatchError, HeadRegistryError) as e:
                    log(f"skipping head {m['head_id']} "
                        f"({m.get('name')}): {e}")
                    continue
                head_ids.append(m["head_id"])
        if not head_ids:
            log(f"registry {registry.directory} holds no servable heads "
                "yet; add them live via POST /v1/heads/add")
    elif args.heads:
        raise SystemExit("--heads requires --registry")

    index = None
    if args.index:
        from proteinbert_tpu.index.scorer import NeighborIndex
        from proteinbert_tpu.mapper import StoreError

        try:
            index = NeighborIndex.load(args.index)
        except StoreError as e:
            raise SystemExit(f"--index: {e}")
        log(f"neighbor index: {index.num_vectors} vector(s), "
            f"{index.centroids.shape[0]} centroid(s), dim {index.dim}, "
            f"identity {index.digest[:16]}… (nprobe {args.nprobe}) — "
            "serving /v1/neighbors")
    elif args.nprobe != 8:
        raise SystemExit("--nprobe requires --index")

    try:
        server = Server(
            params, cfg,
            max_batch=args.max_batch,
            max_wait_s=args.max_wait_ms / 1000.0,
            queue_depth=args.queue_depth,
            cache_size=args.cache_size,
            default_deadline_s=(args.deadline_ms / 1000.0
                                if args.deadline_ms is not None else None),
            on_long=args.on_long,
            mesh=mesh,
            telemetry=tele,
            trace_sample_rate=args.trace_sample_rate,
            slos=slos,
            slo_profile_dir=args.slo_profile_dir,
            registry=registry,
            heads=head_ids,
            serve_mode=args.serve_mode,
            pack_max_segments=args.pack_max_segments,
            quant=args.quant,
            quant_parity_every=args.quant_parity_every,
            pipeline_depth=args.pipeline_depth,
            index=index,
            nprobe=args.nprobe,
            replica_id=args.replica_id,
            candidate_loader=_candidate_loader,
        )
    except TrunkMismatchError as e:
        # The index pins the trunk its embeddings came from; serving it
        # over a different trunk would answer with garbage neighbors.
        raise SystemExit(f"--index: {e}")
    if server.quant != "fp32":
        qr = server.dispatcher.quant_report
        log(f"quantized executable arm: {server.quant} — trunk weights "
            f"{qr['weight_bytes_quant']} bytes vs "
            f"{qr['weight_bytes_fp32']} fp32 "
            f"({qr['weight_bytes_ratio']:.2f}x)"
            + (f", fp32 parity shadow every "
               f"{server.dispatcher.quant_parity_every} batch(es)"
               if server.dispatcher.quant_parity_every else ""))
    if head_ids:
        # Trunk-compat was enforced per head at load (TrunkMismatchError
        # would have exited above); one micro-batch now mixes requests
        # for any of these heads through the shared trunk executable.
        log(f"serving {len(head_ids)} registered head(s) over the "
            f"shared trunk: {', '.join(head_ids)}")
    if args.serve_mode == "ragged":
        log(f"ragged packed serving: one ({args.max_batch}, "
            f"{cfg.data.seq_len}) executable per request kind; spans "
            f"quantized to buckets={list(server.dispatcher.buckets)}, "
            f"up to {args.pack_max_segments} requests per row")
    else:
        log(f"warming {len(server.dispatcher.buckets)} bucket(s) x "
            f"{len(server.dispatcher.batch_classes)} batch class(es): "
            f"buckets={list(server.dispatcher.buckets)}")
    server.start()
    # Warm-boot accounting (mirrored in serve_warmup_seconds_total):
    # with --compile-cache-dir, a restarted replica's number here is
    # cache-load time, not compile time — the fleet's fast-boot claim.
    log(f"warmup: {server.dispatcher.warmup_seconds_total:.2f}s over "
        f"{server.dispatcher.executable_count} warm executable(s)")
    httpd = make_http_server(server, args.host, args.port)
    port = httpd.server_address[1]
    if args.port_file:
        with open(args.port_file, "w") as f:
            f.write(str(port))
    log(f"serving on http://{args.host}:{port} "
        f"(max_batch={args.max_batch}, max_wait={args.max_wait_ms}ms, "
        f"queue_depth={args.queue_depth})")
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    try:
        with GracefulShutdown() as stop:
            http_thread.start()
            while not stop.requested:
                _time.sleep(0.05)
                if args.max_requests and (
                        server.completed_total + server.cache_hit_returns
                        + sum(server.rejected_total.values())
                        >= args.max_requests):
                    log(f"--max-requests {args.max_requests} reached")
                    break
    finally:
        # Graceful drain: stop accepting HTTP, finish queued/in-flight
        # batches, then emit serve_end + export metrics.
        httpd.shutdown()
        httpd.server_close()
        server.drain(timeout=60)
        if tele is not None:
            if args.trace_perfetto and tele.spans is not None:
                try:
                    tele.spans.dump(args.trace_perfetto)
                    log(f"wrote {len(tele.spans)} request-trace spans "
                        f"to {args.trace_perfetto} (load in "
                        "ui.perfetto.dev)")
                except OSError as e:
                    log(f"could not write trace dump: {e}")
            _export_metrics(tele)
            tele.close()
    stats = server.stats()
    log(f"served {stats['completed']} requests "
        f"({stats['cache_hit_returns']} cache hits, "
        f"{sum(stats['rejected'].values())} rejected); "
        f"p50 {stats['latency']['p50_s']}s p99 {stats['latency']['p99_s']}s")
    for name, st in (stats.get("slo") or {}).items():
        log(f"slo {name}: burn {st['burn_rate']:g} "
            f"({st['bad']}/{st['total']} bad in window"
            + (f", {st['breaches_total']} breach(es)"
               if st["breaches_total"] else "") + ")")
    return 0


def cmd_map(args) -> int:
    """Resumable sharded batch inference (ISSUE 14 tentpole): stream a
    corpus through the ragged packed trunk into a content-addressed,
    integrity-verified embedding store (proteinbert_tpu/mapper/).
    Kill-anywhere semantics: every shard has a crash-safe cursor
    advanced only after its block is durably on disk, so a SIGKILL
    resumes with at most one block of re-work per shard. `--verify`
    recomputes every block digest and reports corruption/holes — it
    needs only the store, no model or jax. docs/mapping.md has the
    run/resume/verify lifecycle and the failure matrix."""
    from proteinbert_tpu.mapper import (
        StoreConfigError, StoreError, verify_store,
    )

    if args.verify:
        try:
            report = verify_store(args.store)
        except StoreConfigError as e:
            raise SystemExit(f"--verify: {e}")
        print(json.dumps(report))
        if not report["ok"]:
            problems = []
            for rec in report["corrupt"]:
                problems.append(
                    f"corrupt block shard {rec['shard']} block "
                    f"{rec['block']} ({rec['reason']}, "
                    f"{rec['digest'][:16]}…)")
            for rec in report["holes"]:
                problems.append(
                    f"hole: shard {rec['shard']} block {rec['block']} "
                    f"object {rec['digest'][:16]}… is missing")
            problems.extend(report["coverage_errors"])
            log("store FAILED verification: " + "; ".join(problems))
            return 1
        log(f"store OK: {report['blocks_checked']} block(s) verified, "
            f"{report['embedded']} embedded, "
            f"{report['quarantined']} quarantined"
            + ("" if report["complete"] else " (mapping incomplete)"))
        return 0

    if not args.pretrained:
        raise SystemExit("pbt map needs --pretrained (or --verify to "
                         "audit an existing store)")
    from proteinbert_tpu.mapper.engine import run_map

    params, cfg = _load_inference_trunk(args)
    ids, seqs = _read_named_seqs(args)
    buckets = None
    if args.buckets:
        try:
            buckets = tuple(json.loads(args.buckets))
        except (ValueError, TypeError):
            raise SystemExit(f"--buckets expects a JSON list, got "
                             f"{args.buckets!r}")
    tele = None
    if args.events_jsonl:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
        tele.flight.install_excepthook()
    log(f"mapping {len(seqs)} sequence(s) over {args.num_shards} "
        f"shard(s) (block {args.block_size}, {args.rows_per_batch} "
        f"packed rows x {cfg.data.seq_len}, up to {args.max_segments} "
        f"seqs/row) → {args.store}")
    try:
        out = run_map(
            params, cfg, ids, seqs, args.store,
            num_shards=args.num_shards, block_size=args.block_size,
            rows_per_batch=args.rows_per_batch,
            max_segments=args.max_segments, buckets=buckets,
            telemetry=tele, max_blocks=args.max_blocks,
            pipeline=not args.no_pipeline)
    except (StoreError, ValueError) as e:
        raise SystemExit(f"map failed: {e}")
    finally:
        if tele is not None:
            _export_metrics(tele)
            tele.close()
    log(f"map {out['outcome']}: {out['blocks']} block(s), "
        f"{out['seqs']} sequence(s) at {out['seqs_per_s']:.1f} seqs/s, "
        f"{out['quarantined']} quarantined, {out['retries']} "
        f"retry(ies), {out['rework']} re-worked block(s)")
    if out["outcome"] == "preempted":
        # EX_TEMPFAIL, same contract as pretrain: not done — requeue;
        # the cursors make the requeue cost at most one block per shard.
        log("mapping preempted; exiting 75 so a supervisor requeues it")
        return 75
    if out["outcome"] in ("halted", "error"):
        log(f"mapping {out['outcome']}: halted_shards="
            f"{out['halted_shards']} failed_shards="
            f"{out['failed_shards']}")
        return 1
    return 0


def cmd_index(args) -> int:
    """Neighbor-index construction (ISSUE 17 tentpole): coarse k-means
    centroids + per-block int8-quantized vectors over a COMPLETED
    embedding store, built shard-by-shard through the mapper's
    crash-safe cursor protocol — kill-anywhere, a resume loses at most
    one block per shard, and re-runs converge on byte-identical
    objects. `--verify` audits an existing index (digests, geometry,
    coverage) and needs only the index directory — no model, no jax.
    docs/neighbors.md has the format and lifecycle."""
    from proteinbert_tpu.index import build_index, verify_index
    from proteinbert_tpu.mapper import StoreConfigError, StoreError

    if args.verify:
        try:
            report = verify_index(args.index)
        except StoreConfigError as e:
            raise SystemExit(f"--verify: {e}")
        print(json.dumps(report))
        if not report["ok"]:
            problems = []
            for rec in report["corrupt"]:
                where = (f"shard {rec['shard']} block {rec['block']}"
                         if "shard" in rec else rec.get("kind", "?"))
                problems.append(f"corrupt {where} ({rec['reason']}, "
                                f"{str(rec['digest'])[:16]}…)")
            for rec in report["holes"]:
                where = (f"shard {rec['shard']} block {rec['block']}"
                         if "shard" in rec else rec.get("kind", "?"))
                problems.append(f"hole: {where} object "
                                f"{str(rec['digest'])[:16]}… is missing")
            problems.extend(report["coverage_errors"])
            log("index FAILED verification: " + "; ".join(problems))
            return 1
        log(f"index OK: {report['blocks_checked']} block(s) verified, "
            f"{report['vectors']} vector(s)"
            + ("" if report["complete"] else " (build incomplete)"))
        return 0

    if not args.store:
        raise SystemExit("pbt index needs --store (or --verify to "
                         "audit an existing index)")
    from proteinbert_tpu.train.resilience import GracefulShutdown

    tele = None
    if args.events_jsonl:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
        tele.flight.install_excepthook()
    try:
        with GracefulShutdown() as stop:
            stats = build_index(
                args.store, args.index,
                num_centroids=args.centroids,
                block_size=args.block_size,
                seed=args.seed, kmeans_iters=args.kmeans_iters,
                sample_cap=args.sample_cap, max_blocks=args.max_blocks,
                stop_flag=lambda: stop.requested, telemetry=tele)
    except (StoreError, ValueError) as e:
        raise SystemExit(f"index build failed: {e}")
    finally:
        if tele is not None:
            _export_metrics(tele)
            tele.close()
    if args.json:
        print(json.dumps(stats))
    log(f"index {stats['outcome']}: {stats['vectors']} vector(s) in "
        f"{stats['blocks']} block(s) over {stats['shards']} shard(s), "
        f"{stats['reworked_blocks']} re-worked; int8 index is "
        f"{stats['bytes_ratio']:.3f}x the fp32 vector bytes")
    if stats["outcome"] == "preempted":
        # EX_TEMPFAIL, same contract as map/pretrain: not done —
        # requeue; the cursors bound the requeue cost at one block
        # per shard.
        log("index build preempted; exiting 75 so a supervisor "
            "requeues it")
        return 75
    return 0


def cmd_reshard(args) -> int:
    """Mesh-agnostic checkpoint resharding (ISSUE 11 tentpole): restore
    a run directory's checkpoint onto a NEW mesh layout and save it into
    a fresh run directory whose config.json records the new topology —
    a 4×2 run resumes on 1 chip or a 64-chip pod and back. Round-trip
    byte parity is verified by default; the redistribution's collective
    schedule wire bytes are counted from the compiled HLO
    (parallel/reshard.py) and land on the `reshard` event."""
    from proteinbert_tpu.parallel.reshard import (
        parse_mesh_spec, reshard_checkpoint,
    )

    cfg = _pretrain_run_config(args.src, args.preset, args.pretrained_set)
    target = None
    if args.target_mesh:
        try:
            target = parse_mesh_spec(args.target_mesh)
        except ValueError as e:
            raise SystemExit(f"--target-mesh: {e}")
    tele = None
    if args.events_jsonl:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
    try:
        summary = reshard_checkpoint(
            args.src, args.output, cfg=cfg, target_mesh_cfg=target,
            zero_update=args.zero_update, step=args.step,
            telemetry=tele, verify=not args.no_verify)
    except (FileNotFoundError, ValueError, RuntimeError) as e:
        raise SystemExit(f"reshard failed: {e}")
    finally:
        if tele is not None:
            _export_metrics(tele)
            tele.close()
    print(json.dumps(summary))
    log(f"resharded {args.src} step {summary['step']} → {args.output} "
        f"(mesh {summary['target_mesh']}, {summary['schedule']} "
        f"schedule, {summary['wire_bytes'].get('total', 0)} wire bytes"
        + (", parity verified" if summary["parity"] else "") + ")")
    return 0


def cmd_check(args) -> int:
    """Project-invariant static analyzer (ISSUE 15 tentpole): six
    stdlib-ast rules derived from the repo's own contracts — jit
    purity, lock discipline, durability protocol, event-schema call
    sites, obs-doc drift, dead exports — with a checked-in suppression
    baseline. Same runner as the jax-free tier-1 entry
    (tools/pbt_check.py); exit 0 = clean, 1 = findings, 2 = config
    error. docs/analysis.md is the rule catalog."""
    from proteinbert_tpu.analysis.runner import main as check_main

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    argv = []
    if args.json:
        argv.append("--json")
    if args.json_artifact:
        argv.extend(["--json-artifact", args.json_artifact])
    for rule in args.rule or ():
        argv.extend(["--rule", rule])
    if args.events_jsonl:
        argv.extend(["--events-jsonl", args.events_jsonl])
    if args.baseline:
        argv.extend(["--baseline", args.baseline])
    if args.root:
        argv.extend(["--root", args.root])
    if args.write_baseline:
        argv.append("--write-baseline")
    return check_main(argv, repo_root=repo_root)


def cmd_fleet(args) -> int:
    """Fault-tolerant serve fleet (ISSUE 11 tentpole): N `pbt serve`
    replica subprocesses behind the FleetRouter (serve/fleet.py) —
    health-checked via /healthz + SLO burn, idempotent-retry with
    capped backoff and a retry budget, typed load shedding, drain/
    re-admit via POST /fleet/{drain,admit}, and a shared content-
    addressed result cache so failover does not re-pay warm
    embeddings. Replace a replica by draining it, restarting the
    process (warm via --compile-cache-dir), and re-admitting
    (docs/serving.md, fleet runbook)."""
    import signal
    import subprocess
    import tempfile
    import threading
    import time as _time

    from proteinbert_tpu.serve.fleet import (
        FleetCollector, FleetRouter, make_fleet_http_server,
    )
    from proteinbert_tpu.train.resilience import GracefulShutdown

    workdir = tempfile.mkdtemp(prefix="pbt_fleet_")
    base = [sys.executable, "-m", "proteinbert_tpu"]
    if args.platform:
        base += ["--platform", args.platform]
    base += ["serve", "--pretrained", args.pretrained,
             "--preset", args.preset, "--host", "127.0.0.1", "--port", "0",
             "--serve-mode", args.serve_mode,
             "--max-batch", str(args.max_batch),
             "--max-wait-ms", str(args.max_wait_ms),
             "--queue-depth", str(args.queue_depth),
             "--cache-size", str(args.cache_size),
             "--on-long", args.on_long]
    for ov in args.pretrained_set or []:
        base += ["--pretrained-set", ov]
    for spec in args.slo or []:
        base += ["--slo", spec]
    if args.deadline_ms is not None:
        base += ["--deadline-ms", str(args.deadline_ms)]
    if args.compile_cache_dir:
        base += ["--compile-cache-dir", args.compile_cache_dir]

    tele = None
    if args.events_jsonl:
        from proteinbert_tpu.obs import Telemetry

        tele = Telemetry(events_path=args.events_jsonl)
        tele.flight.install_excepthook()

    procs = []
    logs = []
    port_files = []

    def _shutdown_replicas():
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)  # replica-side drain
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
        for lf in logs:
            lf.close()

    # procs/logs grow incrementally, so _shutdown_replicas cleans up a
    # PARTIAL spawn too (e.g. Popen k failing after k-1 started).
    try:
        for i in range(args.replicas):
            pf = os.path.join(workdir, f"replica{i}.port")
            lf = open(os.path.join(workdir, f"replica{i}.log"), "ab")
            logs.append(lf)
            # Explicit fleet identity (ISSUE 18): every replica stamps
            # its serve_* events with this name, so the merged stream
            # joins on identity, never on ports.
            cmd = list(base) + ["--port-file", pf,
                                "--replica-id", f"r{i}"]
            if args.events_jsonl:
                cmd += ["--events-jsonl",
                        os.path.join(workdir, f"replica{i}.events.jsonl")]
            procs.append(subprocess.Popen(cmd, stdout=lf, stderr=lf))
            port_files.append(pf)
    except BaseException:
        _shutdown_replicas()
        raise
    log(f"spawned {args.replicas} replica(s); logs in {workdir}")

    urls = []
    deadline = _time.monotonic() + args.boot_timeout_s
    try:
        for i, pf in enumerate(port_files):
            while not os.path.exists(pf) or not open(pf).read().strip():
                if procs[i].poll() is not None:
                    raise SystemExit(
                        f"replica {i} died during boot; see "
                        f"{workdir}/replica{i}.log")
                if _time.monotonic() > deadline:
                    raise SystemExit(
                        f"replica {i} did not boot within "
                        f"{args.boot_timeout_s}s; see {workdir}")
                _time.sleep(0.2)
            urls.append((f"r{i}",
                         f"http://127.0.0.1:{open(pf).read().strip()}"))
    except BaseException:
        _shutdown_replicas()
        raise

    # A SIGKILLed replica's flight-recorder ring dumps into its
    # telemetry dir (= the tmp workdir): tell the router where each
    # will land so the fleet_replica death event can point at it, and
    # collect the dumps out of the tmpdir before it vanishes.
    flight_paths = {}
    if args.events_jsonl:
        from proteinbert_tpu.obs import flight_path

        flight_paths = {f"r{i}": flight_path(workdir, procs[i].pid)
                        for i in range(len(procs))}

    def _collect_flight_dumps():
        """Copy any replica flight dumps beside --events-jsonl (the
        artifact that survives this run) — a dead replica's last-N
        forensic ring must not die with the tmpdir."""
        import shutil

        saved = []
        dest_dir = os.path.dirname(os.path.abspath(args.events_jsonl))
        for name, src in sorted(flight_paths.items()):
            if os.path.exists(src):
                dst = os.path.join(dest_dir,
                                   f"fleet_{name}_flight.json")
                try:
                    shutil.copyfile(src, dst)
                    saved.append(dst)
                except OSError as e:
                    log(f"could not save {name} flight dump: {e}")
        return saved

    try:
        router = FleetRouter(
            urls, telemetry=tele,
            health_interval_s=args.health_interval_ms / 1000.0,
            max_retries=args.max_retries,
            retry_budget_ratio=args.retry_budget_ratio,
            cache_size=args.fleet_cache_size,
            flight_paths=flight_paths,
        ).start()
        if args.events_jsonl:
            # The fleet event funnel: router + replica streams merge
            # post-hoc into one seq-ordered file `pbt diagnose --fleet`
            # reconstructs causal chains from.
            collector = FleetCollector({"router": args.events_jsonl})
            for i in range(len(procs)):
                collector.add_source(
                    f"r{i}",
                    os.path.join(workdir, f"replica{i}.events.jsonl"))
            router.attach_collector(collector)
        # Bind can fail (EADDRINUSE on the fixed default port) — the
        # replicas must not be orphaned by a router that never served.
        httpd = make_fleet_http_server(router, args.host, args.port)
    except BaseException:
        _shutdown_replicas()
        raise
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    try:
        # Anything from here on (port-file write included — disk full,
        # parent dir vanished) fails into the finally below, which
        # tears the whole fleet down; no path leaves replicas orphaned.
        port = httpd.server_address[1]
        if args.port_file:
            with open(args.port_file, "w") as f:
                f.write(str(port))
        log(f"fleet router on http://{args.host}:{port} over "
            f"{len(urls)} replica(s): "
            + ", ".join(f"{n}={u}" for n, u in urls))
        with GracefulShutdown() as stop:
            http_thread.start()
            while not stop.requested:
                _time.sleep(0.05)
                if any(p.poll() is not None for p in procs) \
                        and args.exit_on_replica_death:
                    log("a replica process exited; shutting the fleet "
                        "down (--exit-on-replica-death)")
                    break
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.drain()
        _shutdown_replicas()
        if tele is not None:
            _export_metrics(tele)
            tele.close()
            for p in _collect_flight_dumps():
                log(f"saved replica flight dump: {p}")
            if router.collector is not None:
                # Merge AFTER every writer is closed: the router's
                # stream is flushed and each replica stream is as
                # complete as its exit allowed (a torn final line is
                # tolerated by the reader).
                merged = args.events_jsonl + ".merged.jsonl"
                try:
                    n = router.collector.write(merged)
                    log(f"merged fleet stream: {n} event(s) → {merged}")
                except OSError as e:
                    log(f"could not write merged fleet stream: {e}")
    stats = router.stats()
    log(f"fleet drained: {stats['accepted']} accepted, "
        f"{stats['sealed']} sealed, outcomes {stats['outcomes']}, "
        f"{stats['retries_spent']} retries")
    return 0 if stats["accepted"] == stats["sealed"] else 1


def cmd_rollout(args) -> int:
    """Blue-green rollout control plane (ISSUE 20): drive a running
    fleet router's /rollout/* verbs — start shadowing a candidate
    trunk, watch the gate windows, promote the flip, or abort."""
    import json as _json
    import urllib.error
    import urllib.request

    url = args.url.rstrip("/")

    def _call(method, path, body=None):
        data = None
        headers = {}
        if body is not None:
            data = _json.dumps(body).encode("utf-8")
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(url + path, data=data,
                                     headers=headers, method=method)
        try:
            with urllib.request.urlopen(req,
                                        timeout=args.timeout_s) as resp:
                return resp.getcode(), _json.loads(
                    resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                payload = _json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                payload = {"error": "unparseable_reply",
                           "detail": raw[:200].decode("utf-8", "replace")}
            return e.code, payload
        except (urllib.error.URLError, OSError) as e:
            raise SystemExit(f"router unreachable at {url}: {e}")

    if args.verb == "start":
        if not args.source:
            raise SystemExit("rollout start requires --source "
                             "(candidate trunk run directory)")
        spec = {
            "source": args.source,
            "sample_every": args.sample_every,
            "window_requests": args.window_requests,
            "windows_required": args.windows,
            "shadow_parity_max": args.parity_max,
            "slo_burn_delta_max": args.burn_delta_max,
            "auto_promote": not args.no_auto_promote,
        }
        if args.hbm_budget_bytes is not None:
            spec["hbm_budget_bytes"] = args.hbm_budget_bytes
        status, out = _call("POST", "/rollout/start", spec)
    elif args.verb == "status":
        status, out = _call("GET", "/rollout/status")
    elif args.verb == "promote":
        status, out = _call("POST", "/rollout/promote")
    else:
        status, out = _call("POST", "/rollout/abort")

    if args.json:
        print(_json.dumps(out, indent=2, sort_keys=True))
    elif status != 200:
        log(f"rollout {args.verb} failed (HTTP {status}): "
            f"{out.get('error', '?')} — {out.get('detail', '')}")
    elif args.verb == "status":
        ro = out.get("rollout")
        if ro is None:
            log("no rollout attached; fleet is "
                f"{out.get('fleet_state', '?')}")
        else:
            log(f"rollout [{ro['state']}] source={ro.get('source')} "
                f"candidate={str(ro.get('candidate_fingerprint'))[:12]} "
                f"windows {ro['windows_green']}/{ro['windows_required']} "
                f"green, shadows {ro['shadow_ok']} ok / "
                f"{ro['shadow_failed']} failed "
                f"({ro['dropped']} dropped)")
        log(f"fleet {out.get('fleet_state', '?')}: " + ", ".join(
            f"{n}={str(fp)[:12]}"
            for n, fp in sorted((out.get("fingerprints") or {}).items()))
            or "no routable fingerprints yet")
    else:
        log(f"rollout {args.verb}: ok — "
            + ", ".join(f"{k}={v}" for k, v in sorted(out.items())
                        if k != "ok"))
    return 0 if status == 200 else 1


# ------------------------------------------------------------------ parser

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="proteinbert_tpu",
        description="TPU-native ProteinBERT: ETL + pretraining CLI",
    )
    p.add_argument(
        "--platform", choices=("cpu", "tpu", "axon"),
        default=os.environ.get("PB_PLATFORM") or None,
        help="force the JAX backend (goes BEFORE the subcommand): cpu, "
             "tpu (local libtpu), or axon (tunneled TPU plugin). Needed "
             "when the accelerator is unreachable: images whose "
             "sitecustomize pins JAX_PLATFORMS ignore the env var, and a "
             "dead TPU tunnel then hangs every command at device init — "
             "--platform cpu keeps the whole CLI usable. Defaults to the "
             "PB_PLATFORM environment variable (the examples' knob) when "
             "set",
    )
    sub = p.add_subparsers(dest="command", required=True)

    db = sub.add_parser("create-uniref-db", help="UniRef XML → SQLite")
    db.add_argument("--uniref-xml", type=existing_file, required=True)
    db.add_argument("--go-meta", type=existing_file, required=True,
                    help="GO OBO-style file (CAFA go.txt)")
    db.add_argument("--output-db", type=creatable_path, required=True)
    db.add_argument("--go-meta-csv", type=creatable_path)
    db.add_argument("--records-limit", type=int)
    db.add_argument("--task-index", type=int)
    db.add_argument("--task-count", type=int)
    db.set_defaults(fn=cmd_create_uniref_db)

    mg = sub.add_parser("merge-uniref-dbs", help="merge task-array shard DBs")
    mg.add_argument("--output-db", type=creatable_path, required=True)
    mg.add_argument("--num-shards", type=int)
    mg.add_argument("--shards", nargs="*")
    mg.add_argument("--go-meta", type=existing_file)
    mg.add_argument("--go-meta-csv", type=creatable_path)
    mg.set_defaults(fn=cmd_merge_uniref_dbs)

    h5 = sub.add_parser("create-h5", help="SQLite + FASTA → HDF5 dataset")
    h5.add_argument("--db", type=existing_file, required=True)
    h5.add_argument("--fasta", type=existing_file, required=True)
    h5.add_argument("--go-meta-csv", type=existing_file, required=True)
    h5.add_argument("--output", type=creatable_path, required=True)
    h5.add_argument("--min-records", type=int, default=100)
    h5.add_argument("--records-limit", type=int)
    h5.add_argument("--no-shuffle", action="store_true")
    h5.set_defaults(fn=cmd_create_h5)

    def add_train_args(sp, default_preset="base"):
        sp.add_argument("--preset", default=default_preset,
                        choices=["tiny", "base", "long", "large"])
        sp.add_argument("--data", type=existing_file,
                        help="HDF5 dataset from create-h5 (default: synthetic)")
        sp.add_argument("--max-steps", type=int)
        sp.add_argument("--multihost", action="store_true",
                        help="jax.distributed.initialize from env/TPU-pod "
                             "metadata before building the mesh")
        sp.add_argument("--eval-frac", type=float, default=0.0,
                        help="hold out this fraction for periodic eval "
                             "(reference's unused train/test split, C8)")
        sp.add_argument("--checkpoint-dir")
        sp.add_argument("--history-json", type=creatable_path)
        sp.add_argument("--metrics-jsonl", type=creatable_path,
                        help="append one JSON line per logged/eval step")
        sp.add_argument("--events-jsonl", type=creatable_path,
                        help="unified telemetry: append schema-versioned "
                             "run events here (run_start/step/ckpt_stage/"
                             "eval/requeue/nan_halt/run_end); also arms "
                             "the flight recorder, which dumps "
                             "flight_<pid>.json beside this file on "
                             "SIGTERM/NaN/crash (docs/observability.md)")
        sp.add_argument("--profile-dir",
                        help="capture a jax.profiler device trace here")
        sp.add_argument("--set", action="append", metavar="PATH=VALUE",
                        help="config override, e.g. --set model.local_dim=256")

    tr = sub.add_parser("pretrain", help="denoising pretraining")
    add_train_args(tr)
    tr.set_defaults(fn=cmd_pretrain)

    sm = sub.add_parser("smoke", help="end-to-end sanity run (tiny preset)")
    add_train_args(sm, default_preset="tiny")
    sm.set_defaults(fn=cmd_smoke)

    ftp = sub.add_parser("finetune", help="fine-tune a task head on a trunk")
    ftp.add_argument("--preset", default="tiny",
                     choices=["tiny", "base", "long", "large"])
    ftp.add_argument("--task", default="token_classification",
                     choices=["token_classification",
                              "sequence_classification",
                              "sequence_regression"])
    ftp.add_argument("--num-outputs", type=int, default=8)
    ftp.add_argument("--epochs", type=int, default=3)
    ftp.add_argument("--freeze-trunk", action="store_true")
    ftp.add_argument("--pretrained", help="pretrain checkpoint dir for the trunk")
    ftp.add_argument("--pretrained-set", action="append", metavar="PATH=VALUE",
                     help="config override the PRETRAIN run was made with "
                          "(rebuilds its state template for restore)")
    ftp.add_argument("--data", type=existing_file,
                     help="labeled TSV (data/finetune_data.py format); "
                          "default: synthetic smoke batches")
    ftp.add_argument("--eval-data", type=existing_file)
    ftp.add_argument("--checkpoint-dir")
    ftp.add_argument("--history-json", type=creatable_path)
    ftp.add_argument("--events-jsonl", type=creatable_path,
                     help="unified telemetry events stream "
                          "(docs/observability.md)")
    ftp.add_argument("--register-head", metavar="REGISTRY_DIR",
                     help="save the trained head into this head "
                          "registry (content-addressed artifact with "
                          "trunk fingerprint + eval metrics; serve it "
                          "with `pbt serve --registry` — "
                          "docs/finetuning.md)")
    ftp.add_argument("--head-name",
                     help="human-readable name recorded on the "
                          "registered head artifact")
    ftp.add_argument("--set", action="append", metavar="PATH=VALUE")
    ftp.set_defaults(fn=cmd_finetune)

    eh = sub.add_parser("eval-heads",
                        help="score registered task heads against a "
                             "trunk (downstream eval harness)")
    eh.add_argument("--registry", required=True,
                    help="head registry directory (pbt finetune "
                         "--register-head)")
    eh.add_argument("--pretrained", required=True,
                    help="pretrain checkpoint dir for the resident trunk")
    eh.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    eh.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE",
                    help="config override the pretrain run was made with")
    eh.add_argument("--heads", default="all",
                    help="comma-separated head ids, or 'all' (default)")
    eh.add_argument("--data", type=existing_file,
                    help="labeled TSV (data/finetune_data.py format; "
                         "single task kind); default: synthetic "
                         "labeled batches")
    eh.add_argument("--batch-size", type=int, default=16)
    eh.add_argument("--seed", type=int, default=0,
                    help="synthetic eval data seed")
    eh.add_argument("--no-trunk-check", action="store_true",
                    help="skip the trunk-fingerprint compatibility "
                         "check (scores then describe a mismatched "
                         "pairing — debugging only)")
    eh.add_argument("--events-jsonl", type=creatable_path,
                    help="append head_eval events to this JSONL stream")
    eh.add_argument("--output", type=creatable_path,
                    help="also write all results as one JSON object")
    eh.set_defaults(fn=cmd_eval_heads)

    def add_infer_args(sp, output_required=False):
        sp.add_argument("--pretrained", required=True,
                        help="pretrain checkpoint dir for the trunk")
        sp.add_argument("--preset", default="tiny",
                        choices=["tiny", "base", "long", "large"])
        sp.add_argument("--pretrained-set", action="append",
                        metavar="PATH=VALUE",
                        help="config override the pretrain run was made with")
        sp.add_argument("--fasta", type=existing_file)
        sp.add_argument("--seqs-file", type=existing_file,
                        help="one sequence per line, optionally id<TAB>seq")
        sp.add_argument("seqs", nargs="*", help="literal AA sequences")
        sp.add_argument("--batch-size", type=int, default=32)
        sp.add_argument("--output", type=creatable_path,
                        required=output_required)

    cv = sub.add_parser("convert-torch",
                        help="reference torch checkpoint → orbax run dir")
    cv.add_argument("--torch-ckpt", type=existing_file, required=True,
                    help="reference checkpoint .pt (periodic dict, bare "
                         "state_dict, or pickled module)")
    cv.add_argument("--output", type=creatable_path, required=True,
                    help="orbax run dir to create")
    cv.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    cv.add_argument("--step", type=int,
                    help="override the recorded iteration counter")
    cv.add_argument("--set", action="append", metavar="PATH=VALUE",
                    help="config matching the torch model's geometry")
    cv.set_defaults(fn=cmd_convert_torch)

    ev = sub.add_parser("evaluate",
                        help="score a checkpoint on a dataset")
    ev.add_argument("--pretrained", required=True,
                    help="pretrain checkpoint dir")
    ev.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    ev.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE",
                    help="config override the pretrain run was made with")
    ev.add_argument("--data", type=existing_file,
                    help="HDF5 dataset (default: synthetic)")
    ev.add_argument("--max-batches", type=int, default=0,
                    help="cap evaluated batches (0 = whole dataset)")
    ev.add_argument("--seed", type=int, default=1,
                    help="corruption key seed (fixed → reproducible)")
    ev.add_argument("--like-step", type=int,
                    help="derive corruption keys as the training run's "
                         "eval at this history step did (matches its "
                         "eval_* values when the holdout divides the "
                         "batch size and no row exceeds the crop window)")
    ev.add_argument("--output", type=creatable_path,
                    help="also write the JSON result here")
    ev.set_defaults(fn=cmd_evaluate)

    dg = sub.add_parser("diagnose",
                        help="summarize a telemetry events JSONL "
                             "(+ flight-recorder dump)")
    dg.add_argument("events", type=existing_file,
                    help="events JSONL from --events-jsonl")
    dg.add_argument("--flight", type=existing_file,
                    help="flight_<pid>.json dump from a dead run")
    dg.add_argument("--last", type=int, default=10,
                    help="how many trailing events to list")
    dg.add_argument("--slow-top", type=int, default=5,
                    help="size of the slowest-windows list")
    dg.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of the report")
    dg.add_argument("--serve", action="store_true",
                    help="render only the serving section (request "
                         "outcomes, stage attribution, SLO breaches); "
                         "a stream with serve_* records shows it "
                         "automatically after the training report")
    dg.add_argument("--map", action="store_true",
                    help="render only the offline-mapping section "
                         "(per-shard progress, block throughput, "
                         "re-work across incarnations, quarantines); "
                         "a stream with map_* records shows it "
                         "automatically after the training report")
    dg.add_argument("--fleet", action="store_true",
                    help="render only the fleet section (causal chains "
                         "across router attempts and replicas — feed "
                         "the merged stream pbt fleet writes); a stream "
                         "with fleet_* records shows it automatically")
    dg.add_argument("--trace-id", default=None,
                    help="with --fleet: reconstruct ONE request's "
                         "causal chain (admission → attempts → sealed) "
                         "by its fleet id (the X-PBT-Request-Id header)")
    dg.add_argument("--trace-perfetto", type=creatable_path, default=None,
                    help="with --fleet: write cross-process Perfetto "
                         "lanes (router tid + one tid per replica "
                         "attempt) reconstructed from the merged stream")
    dg.set_defaults(fn=cmd_diagnose)

    dbench = sub.add_parser("data-bench",
                            help="host input-pipeline throughput probe")
    dbench.add_argument("--preset", default="base",
                        choices=["tiny", "base", "long", "large"])
    dbench.add_argument("--data", type=existing_file,
                        help="HDF5 dataset (default: synthetic)")
    dbench.add_argument("--batches", type=int, default=50)
    dbench.add_argument("--set", action="append", metavar="PATH=VALUE")
    dbench.set_defaults(fn=cmd_data_bench)

    ex = sub.add_parser("export-weights",
                        help="trained params → flat NPZ of named arrays")
    ex.add_argument("--pretrained", required=True,
                    help="pretrain checkpoint dir")
    ex.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    ex.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE",
                    help="config override the pretrain run was made with")
    ex.add_argument("--output", type=creatable_path, required=True)
    ex.set_defaults(fn=cmd_export_weights)

    im = sub.add_parser("import-weights",
                        help="flat NPZ → orbax run dir")
    im.add_argument("--weights", type=existing_file, required=True,
                    help="NPZ in the export-weights format")
    im.add_argument("--output", type=creatable_path, required=True,
                    help="orbax run dir to create")
    im.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    im.add_argument("--step", type=int, default=0,
                    help="iteration counter to record")
    im.add_argument("--set", action="append", metavar="PATH=VALUE",
                    help="config matching the weights' geometry")
    im.set_defaults(fn=cmd_import_weights)

    em = sub.add_parser("embed", help="trunk representations → HDF5/NPZ")
    add_infer_args(em, output_required=True)
    em.add_argument("--per-residue", action="store_true",
                    help="also write per-residue local track (N, L, C)")
    em.set_defaults(fn=cmd_embed)

    pg = sub.add_parser("predict-go",
                        help="GO annotation probabilities from sequence")
    add_infer_args(pg)
    pg.add_argument("--top-k", type=int, default=10)
    pg.add_argument("--data", type=existing_file,
                    help="training HDF5: maps annotation columns → GO ids")
    pg.add_argument("--go-meta-csv", type=existing_file,
                    help="GO meta CSV: adds term names to the output")
    pg.set_defaults(fn=cmd_predict_go)

    pr = sub.add_parser("predict-residues",
                        help="fill '?'-masked residues via the local head")
    add_infer_args(pr)
    pr.set_defaults(fn=cmd_predict_residues)

    sv = sub.add_parser("serve",
                        help="online JSON/HTTP inference server "
                             "(continuous micro-batching)")
    sv.add_argument("--pretrained", required=True,
                    help="pretrain checkpoint dir for the trunk")
    sv.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    sv.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE",
                    help="config override the pretrain run was made with")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8476,
                    help="0 = ephemeral (read it back via --port-file)")
    sv.add_argument("--port-file", type=creatable_path,
                    help="write the bound port here once listening")
    sv.add_argument("--serve-mode", default="bucketed",
                    choices=["bucketed", "ragged"],
                    help="bucketed: one warm executable per "
                         "(bucket, batch class); ragged: pack "
                         "heterogeneous requests into fixed-shape "
                         "(max_batch, seq_len) rows — one executable "
                         "per request kind, outputs matching bucketed "
                         "within jitted tolerance (docs/serving.md, "
                         "ragged batching)")
    sv.add_argument("--pack-max-segments", type=int, default=8,
                    help="ragged mode: max requests packed into one "
                         "row (a batch carries up to max_batch x this "
                         "many requests)")
    sv.add_argument("--max-batch", type=int, default=8,
                    help="micro-batch size cap (dispatch when a "
                         "(kind, bucket) group reaches it); in ragged "
                         "mode, the packed ROW count per executable")
    sv.add_argument("--max-wait-ms", type=float, default=10.0,
                    help="max queueing delay before an under-full "
                         "batch dispatches anyway")
    sv.add_argument("--queue-depth", type=int, default=64,
                    help="admission-control bound; overflow evicts the "
                         "oldest queued request with a 429")
    sv.add_argument("--cache-size", type=int, default=1024,
                    help="LRU result-cache entries (0 disables)")
    sv.add_argument("--replica-id", default=None,
                    help="fleet identity stamped on every serve_request/"
                         "serve_batch event (pbt fleet passes r0..rN-1 "
                         "at spawn); lets the merged fleet stream "
                         "attribute replica work to router attempts")
    sv.add_argument("--deadline-ms", type=float,
                    help="default per-request deadline (504 when missed)")
    sv.add_argument("--on-long", default="truncate",
                    choices=["truncate", "reject"],
                    help="over-window sequences: truncate-and-count or "
                         "reject with 400")
    sv.add_argument("--mesh", action="store_true",
                    help="shard served batches over the device mesh "
                         "batch dim (both serve modes: bucketed micro-"
                         "batches and ragged packed rows)")
    sv.add_argument("--compile-cache-dir", type=creatable_path,
                    help="persistent XLA compilation cache: restarted/"
                         "new replicas deserialize warm executables "
                         "instead of re-paying per-kind warmup "
                         "(docs/serving.md, fleet section)")
    sv.add_argument("--max-requests", type=int,
                    help="exit after this many requests (smoke tests)")
    sv.add_argument("--events-jsonl", type=creatable_path,
                    help="append serve_* run events to this JSONL stream")
    sv.add_argument("--trace-sample-rate", type=float, default=1.0,
                    help="fraction of requests whose serve_request "
                         "event + spans are emitted (errors/rejections "
                         "always emit; every request is traced "
                         "cheaply regardless)")
    sv.add_argument("--trace-perfetto", type=creatable_path,
                    help="dump request-trace spans here at drain "
                         "(Perfetto traceEvents JSON, .gz ok)")
    sv.add_argument("--slo", action="append", metavar="SPEC",
                    help="declarative objective, repeatable: e.g. "
                         "'kind=latency,threshold_ms=250,target=0.99,"
                         "window_s=300' or 'kind=error_rate,"
                         "target=0.999' (docs/observability.md)")
    sv.add_argument("--slo-profile-dir", type=creatable_path,
                    help="on an SLO breach, capture an on-demand "
                         "jax.profiler device trace here (cooldown-"
                         "limited)")
    sv.add_argument("--registry",
                    help="head registry directory: serve registered "
                         "finetuned heads over the shared trunk "
                         "(predict_task requests for different heads "
                         "batch together — docs/serving.md multi-"
                         "tenant section)")
    sv.add_argument("--heads", default=None,
                    help="comma-separated head ids to load at start, "
                         "or 'all' (default: all); requires --registry. "
                         "Heads can also be added/removed live via "
                         "POST /v1/heads/{add,remove}")
    sv.add_argument("--quant", default=None,
                    choices=["fp32", "int8", "int8_act"],
                    help="executable arm (docs/serving.md, int8 arm): "
                         "int8 = symmetric per-channel int8 WEIGHTS, "
                         "dequantized in-executable (~4x smaller "
                         "resident trunk); int8_act adds dynamic int8 "
                         "fake-quant of the trunk's output activations "
                         "(bucketed mode only). Default: the run "
                         "config's serve.quant (fp32 unless set)")
    sv.add_argument("--quant-parity-every", type=int, default=None,
                    metavar="N",
                    help="with a quantized arm: every Nth batch also "
                         "runs the fp32 executables and records the "
                         "worst per-request deviation "
                         "(serve_quant_parity_max gauge, "
                         "stats()['quant'], serve_batch events). "
                         "0 disables. Default: the run config's "
                         "serve.quant_parity_every")
    sv.add_argument("--pipeline-depth", type=int, default=None,
                    metavar="N",
                    help="bounded in-flight dispatch window (ISSUE 19, "
                         "docs/serving.md Pipelined dispatch): up to N "
                         "batches submitted before the scheduler blocks; "
                         "a completer thread resolves device results "
                         "while the next batch forms. 1 = serial "
                         "(pre-pipeline) dispatch. Default: the run "
                         "config's serve.pipeline_depth (2 unless set)")
    sv.add_argument("--index",
                    help="neighbor-index directory (pbt index) to "
                         "serve /v1/neighbors from: query sequences "
                         "embed through the trunk, then probe the "
                         "int8 IVF index (docs/neighbors.md). The "
                         "index must have been built from THIS "
                         "trunk's embedding store (fingerprint "
                         "enforced)")
    sv.add_argument("--nprobe", type=int, default=8,
                    help="with --index: centroid lists probed per "
                         "query — the recall/latency dial (recall "
                         "gate: bench.py --neighbors)")
    sv.set_defaults(fn=cmd_serve)

    mp = sub.add_parser("map",
                        help="resumable sharded batch inference: embed "
                             "a corpus through the packed trunk into a "
                             "content-addressed, integrity-verified "
                             "embedding store (docs/mapping.md)")
    mp.add_argument("--store", required=True,
                    help="embedding-store directory (created on first "
                         "run; an existing store RESUMES from its "
                         "shard cursors)")
    mp.add_argument("--verify", action="store_true",
                    help="audit an existing store instead of mapping: "
                         "recompute every block sha256, report "
                         "corruption and holes (typed, nonzero exit), "
                         "audit shard coverage. Needs only --store")
    mp.add_argument("--pretrained",
                    help="pretrain checkpoint dir for the trunk "
                         "(required unless --verify)")
    mp.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    mp.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE",
                    help="config override the pretrain run was made with")
    mp.add_argument("--fasta", type=existing_file)
    mp.add_argument("--seqs-file", type=existing_file,
                    help="one sequence per line, optionally id<TAB>seq")
    mp.add_argument("seqs", nargs="*", help="literal AA sequences")
    mp.add_argument("--num-shards", type=int, default=1,
                    help="deterministic contiguous corpus shards, each "
                         "with its own crash-safe cursor (re-work "
                         "after a kill is bounded per shard)")
    mp.add_argument("--block-size", type=int, default=64,
                    help="sequences per durably-committed block (the "
                         "re-work unit: a kill loses at most one "
                         "in-flight block per shard)")
    mp.add_argument("--rows-per-batch", type=int, default=8,
                    help="packed rows per executable dispatch (one "
                         "warm (rows, seq_len) executable serves the "
                         "whole run)")
    mp.add_argument("--max-segments", type=int, default=8,
                    help="max sequences packed into one row")
    mp.add_argument("--buckets",
                    help="span-quantization ladder as a JSON list "
                         "(e.g. [64,128,512]; ascending, last == "
                         "seq_len). Denser ladders pack tighter; the "
                         "default (the run config's data.buckets, else "
                         "the single full-length bucket) keeps store "
                         "numbers within jitted tolerance of pbt "
                         "embed. Pinned in the store manifest")
    mp.add_argument("--max-blocks", type=int,
                    help="stop (resumably, exit 75) after this many "
                         "blocks this invocation — smoke/drill knob")
    mp.add_argument("--no-pipeline", action="store_true",
                    help="disable pipelined dispatch (ISSUE 19): run "
                         "device compute → host fetch → commit strictly "
                         "serially per block instead of keeping one "
                         "block in flight. Same bytes either way — this "
                         "is the A/B knob, not a safety valve")
    mp.add_argument("--events-jsonl", type=creatable_path,
                    help="append map_start/map_shard/map_block/map_end "
                         "events here (pbt diagnose --map reads them); "
                         "also arms the flight recorder for NaN halts")
    mp.set_defaults(fn=cmd_map)

    ix = sub.add_parser("index",
                        help="build an int8 IVF neighbor index over a "
                             "completed embedding store (resumable, "
                             "kill-anywhere; serves /v1/neighbors — "
                             "docs/neighbors.md)")
    ix.add_argument("--index", required=True,
                    help="index directory (created on first run; an "
                         "existing one RESUMES from its shard cursors)")
    ix.add_argument("--store",
                    help="COMPLETED embedding store (pbt map) to "
                         "index; required unless --verify")
    ix.add_argument("--verify", action="store_true",
                    help="audit an existing index instead of building: "
                         "recompute every referenced sha256, audit "
                         "block geometry/coverage and the centroids "
                         "pin (typed, nonzero exit). Needs only "
                         "--index — no model, no jax")
    ix.add_argument("--centroids", type=int, default=64,
                    help="coarse k-means centroid count (clamped to "
                         "the corpus size; pinned in the manifest)")
    ix.add_argument("--block-size", type=int, default=256,
                    help="vectors per durably-committed index block "
                         "(the re-work unit: a kill loses at most one "
                         "in-flight block per shard)")
    ix.add_argument("--seed", type=int, default=0,
                    help="k-means seed — same store + same knobs → "
                         "byte-identical index (pinned in the manifest)")
    ix.add_argument("--kmeans-iters", type=int, default=8,
                    help="Lloyd iterations for the coarse centroids")
    ix.add_argument("--sample-cap", type=int, default=4096,
                    help="deterministic strided sample size the "
                         "centroids are fit on")
    ix.add_argument("--max-blocks", type=int,
                    help="stop (resumably, exit 75) after this many "
                         "blocks this invocation — smoke/drill knob")
    ix.add_argument("--json", action="store_true",
                    help="print the terminal build stats as one JSON "
                         "line (drill/script consumption)")
    ix.add_argument("--events-jsonl", type=creatable_path,
                    help="append index_build/index_shard events here "
                         "(pbt diagnose reads them); also arms the "
                         "flight recorder")
    ix.set_defaults(fn=cmd_index)

    rs = sub.add_parser("reshard",
                        help="restore a checkpoint onto a new mesh "
                             "layout and re-save it (mesh-agnostic "
                             "resharding, docs/distributed.md)")
    rs.add_argument("--src", required=True,
                    help="source run directory (checkpoints + "
                         "config.json)")
    rs.add_argument("--output", type=creatable_path, required=True,
                    help="run directory to create at the target layout")
    rs.add_argument("--target-mesh",
                    help="target topology: '4x2' (data x fsdp), "
                         "'8x1x1x1' (data x fsdp x model x seq), '1' "
                         "(single device), or 'data=4,fsdp=2'; "
                         "default: the source config's mesh")
    rs.add_argument("--step", type=int,
                    help="checkpoint step to reshard (default: latest; "
                         "explicit steps are strict — no torn-tail "
                         "fallback)")
    rs.add_argument("--zero-update", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="lay the optimizer state out ZeRO-1-sharded "
                         "on the target (--no-zero-update forces the "
                         "replicated layout; default: the source "
                         "config's parallel.zero_update)")
    rs.add_argument("--no-verify", action="store_true",
                    help="skip the round-trip byte-parity check "
                         "(verification re-reads the written "
                         "checkpoint)")
    rs.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    rs.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE",
                    help="config override the source run was made with "
                         "(when it lacks a config.json)")
    rs.add_argument("--events-jsonl", type=creatable_path,
                    help="append the reshard event (+ wire-bytes "
                         "metrics) to this JSONL stream")
    rs.set_defaults(fn=cmd_reshard)

    fl = sub.add_parser("fleet",
                        help="N serve replicas behind a self-healing "
                             "router (health checks, retries, load "
                             "shedding, shared result cache)")
    fl.add_argument("--pretrained", required=True,
                    help="pretrain checkpoint dir for the trunk")
    fl.add_argument("--preset", default="tiny",
                    choices=["tiny", "base", "long", "large"])
    fl.add_argument("--pretrained-set", action="append",
                    metavar="PATH=VALUE")
    fl.add_argument("--replicas", type=int, default=2,
                    help="serve replica subprocesses to spawn")
    fl.add_argument("--host", default="127.0.0.1")
    fl.add_argument("--port", type=int, default=8475,
                    help="router port; 0 = ephemeral (read it back via "
                         "--port-file)")
    fl.add_argument("--port-file", type=creatable_path)
    fl.add_argument("--serve-mode", default="bucketed",
                    choices=["bucketed", "ragged"])
    fl.add_argument("--max-batch", type=int, default=8)
    fl.add_argument("--max-wait-ms", type=float, default=10.0)
    fl.add_argument("--queue-depth", type=int, default=64)
    fl.add_argument("--cache-size", type=int, default=1024,
                    help="per-replica result-cache entries")
    fl.add_argument("--fleet-cache-size", type=int, default=2048,
                    help="router-level shared result-cache entries "
                         "(0 disables)")
    fl.add_argument("--deadline-ms", type=float)
    fl.add_argument("--on-long", default="truncate",
                    choices=["truncate", "reject"])
    fl.add_argument("--slo", action="append", metavar="SPEC",
                    help="passed through to every replica; burn rates "
                         "feed the router's degraded state")
    fl.add_argument("--compile-cache-dir", type=creatable_path,
                    help="shared persistent compilation cache so "
                         "replacement replicas boot warm")
    fl.add_argument("--health-interval-ms", type=float, default=500.0)
    fl.add_argument("--max-retries", type=int, default=2)
    fl.add_argument("--retry-budget-ratio", type=float, default=0.2)
    fl.add_argument("--boot-timeout-s", type=float, default=300.0)
    fl.add_argument("--exit-on-replica-death", action="store_true",
                    help="shut the fleet down when any replica process "
                         "exits (default: keep serving on the "
                         "survivors — the self-healing mode)")
    fl.add_argument("--events-jsonl", type=creatable_path,
                    help="append fleet_* router events here (each "
                         "replica writes its own stream beside its "
                         "log)")
    fl.set_defaults(fn=cmd_fleet)

    ck = sub.add_parser(
        "check",
        help="project-invariant static analyzer (jit purity, lock "
             "discipline, durability protocol, event schema, doc "
             "drift, dead exports) — docs/analysis.md")
    ck.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ck.add_argument("--json-artifact", type=creatable_path,
                    help="also write the JSON report here (the "
                         "bench-trajectory check_findings_total input)")
    ck.add_argument("--rule", action="append", metavar="NAME",
                    help="run only this rule (repeatable)")
    ck.add_argument("--events-jsonl", type=creatable_path,
                    help="mirror the counts as a note(kind="
                         "check_capture) event — the trajectory "
                         "sentinel's suppression-creep series")
    ck.add_argument("--baseline",
                    help="suppression baseline JSON (default: "
                         "tools/check_baseline.json)")
    ck.add_argument("--root", help="tree to analyze (default: the "
                                   "installed repo root)")
    ck.add_argument("--write-baseline", action="store_true",
                    help="record current findings as suppressions for "
                         "human review")
    ck.set_defaults(fn=cmd_check)

    ro = sub.add_parser(
        "rollout",
        help="blue-green trunk rollout against a running fleet router: "
             "shadow a candidate trunk on live traffic, gate on "
             "parity/SLO/heads-eval windows, promote atomically, "
             "abort/roll back instantly (docs/serving.md)")
    ro.add_argument("verb",
                    choices=["start", "status", "promote", "abort"],
                    help="start: load + shadow a candidate; status: "
                         "gate windows + fleet fingerprint coherence; "
                         "promote: atomic flip (requires the green "
                         "streak); abort: unload, or roll a promoted "
                         "flip back")
    ro.add_argument("--url", default="http://127.0.0.1:8475",
                    help="fleet router base URL")
    ro.add_argument("--source",
                    help="candidate trunk run directory, resolved by "
                         "each replica's own loader (start only)")
    ro.add_argument("--sample-every", type=int, default=2,
                    help="mirror every Nth live request to the shadow "
                         "arm (1 = all traffic)")
    ro.add_argument("--window-requests", type=int, default=8,
                    help="shadow responses per gate window")
    ro.add_argument("--windows", type=int, default=2,
                    help="consecutive green windows required before "
                         "promotion")
    ro.add_argument("--parity-max", type=float, default=1e-3,
                    help="max |live − shadow| over shared numeric "
                         "response leaves")
    ro.add_argument("--burn-delta-max", type=float, default=0.5,
                    help="max fleet SLO burn-rate rise vs the "
                         "pre-rollout baseline")
    ro.add_argument("--hbm-budget-bytes", type=int,
                    help="per-replica HBM budget for the two-trunk "
                         "residency check (default: replica-side "
                         "detection)")
    ro.add_argument("--no-auto-promote", action="store_true",
                    help="stop at the green streak and wait for an "
                         "explicit `pbt rollout promote`")
    ro.add_argument("--timeout-s", type=float, default=120.0,
                    help="HTTP timeout per control verb (start blocks "
                         "on candidate load + warmup fleet-wide)")
    ro.add_argument("--json", action="store_true",
                    help="raw router reply on stdout")
    ro.set_defaults(fn=cmd_rollout)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    start_log()
    self_destruct = int(os.environ.get("PBT_SELF_DESTRUCT_SECS", "0"))
    if self_destruct > 0:
        # Opt-in hard deadline for harness-driven runs (experiment
        # scripts set it to their phase timeout + margin): if the
        # harness is killed while this process hangs at tunneled-TPU
        # device init or compile, the orphan would hold the single
        # chip's PJRT client forever. No handler is installed, so
        # SIGALRM's default action terminates even inside native code.
        import signal

        signal.alarm(self_destruct)
    args = build_parser().parse_args(argv)
    if args.platform:
        # Must land before the first backend use anywhere in the process;
        # command handlers import jax lazily, so this is early enough.
        import jax

        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
