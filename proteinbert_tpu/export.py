"""Export trained parameters to portable flat files (NPZ).

The inverse direction of `interop.py`: that module brings reference torch
checkpoints IN; this one gets trained weights OUT of the orbax run
directory into a dependency-free format (a flat NPZ of slash-joined
pytree paths) that any numpy-speaking consumer — including a PyTorch
user going back the other way — can read. The reference's only export is
a pickled `nn.Module` (reference utils.py:339-343), unreadable without
the exact class code on the unpickling side; a flat array map has no such
coupling.

Round-trip: `export_params` → `import_params` reproduces the pytree
exactly (tests/test_export.py), and the stacked scan-blocks layout is
unstacked to per-block entries (`blocks/0/...`) so the file is
self-describing regardless of cfg.scan_blocks.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

Params = Dict[str, Any]


def flatten_params(params: Params, unstack_blocks: bool = True) -> Dict[str, np.ndarray]:
    """Pytree → {"embedding/embedding": array, "blocks/0/narrow_conv/kernel":
    array, ...} with fp32 numpy leaves."""
    flat: Dict[str, np.ndarray] = {}

    def walk(node, path):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], path + (str(k),))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, path + (str(i),))
        else:
            flat["/".join(path)] = np.asarray(node)

    p = dict(params)
    blocks = p.pop("blocks", None)
    walk(p, ())
    if blocks is None:
        return flat
    if isinstance(blocks, dict) and unstack_blocks:
        # Stacked scan layout: every leaf has a leading num_blocks axis.
        # One device→host transfer of the whole stack, then host slicing.
        blocks_np = jax.tree.map(np.asarray, blocks)
        n = jax.tree.leaves(blocks_np)[0].shape[0]
        for i in range(n):
            walk(jax.tree.map(lambda a: a[i], blocks_np),
                 ("blocks", str(i)))
    else:
        walk(blocks, ("blocks",))
    return flat


def unflatten_params(flat: Dict[str, np.ndarray],
                     scan_blocks: bool = True) -> Params:
    """Inverse of flatten_params; restacks `blocks/<i>/...` entries when
    `scan_blocks` (the framework's default layout)."""
    tree: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = np.asarray(value)

    blocks = tree.pop("blocks", None)
    if blocks is not None:
        per_block = [blocks[k] for k in sorted(blocks, key=int)]
        if scan_blocks:
            tree["blocks"] = jax.tree.map(
                lambda *xs: np.stack(xs), *per_block)
        else:
            tree["blocks"] = per_block
    return tree


def export_params(params: Params, path: str) -> int:
    """Write the pytree as a flat NPZ; returns the number of arrays."""
    flat = flatten_params(params)
    np.savez(path, **flat)
    return len(flat)


def import_params(path: str, scan_blocks: bool = True) -> Params:
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    return unflatten_params(flat, scan_blocks=scan_blocks)
