"""Migrate reference (PyTorch) checkpoints into this framework.

A user of the reference repo holds `torch.save`d artifacts in one of two
forms: the periodic pretraining checkpoint dict (key `model_state_dict`,
reference utils.py:326-337) or the final pickled `nn.Module` (reference
utils.py:339-343). This module converts either into this framework's
parameter pytree so pretraining can be resumed — or fine-tuning/inference
run — on TPU.

Layout translation (torch state_dict key → pytree path), per the
reference's module tree (reference modules.py:234-304):

  local_embedding.weight                  → embedding.embedding      (V, C)
  global_linear_layer.0.{weight,bias}     → global_in                (A→G, .T)
  proteinBERT_blocks.{i}.
    local_narrow_conv_layer.0.*           → blocks.narrow_conv       (Cout,Cin,K)→(K,Cin,Cout)
    local_wide_conv_layer.0.*             → blocks.wide_conv         ditto
    global_to_local_linear_layer.0.*      → blocks.global_to_local   (.T)
    local_linear_layer.0.*                → blocks.local_dense       (.T)
    global_linear_layer_1.0.*             → blocks.global_dense1     (.T)
    global_linear_layer_2.0.*             → blocks.global_dense2     (.T)
    local_norm_{1,2}.*                    → blocks.local_ln{1,2}     (see below)
    global_norm_{1,2}.*                   → blocks.global_ln{1,2}
  pretraining_local_output.0.*            → local_head               (.T)
  pretraining_global_output.0.*           → global_head              (.T)

Two reference quirks force documented conversion decisions:

1. The reference's local LayerNorms normalize jointly over (seq_len,
   local_dim) with a per-(position, feature) affine (reference
   modules.py:148-151,161-164); this framework uses per-feature LN so the
   model is shape-parametric in L (SURVEY ledger #4). The (L, C) affine
   is reduced to (C,) by averaging over positions — exact when the torch
   affine is position-independent (e.g. still at its ones/zeros init),
   the closest L2 projection otherwise.
2. The reference's attention-head projections are invisible to
   `state_dict` (plain Python list, never trained OR saved — reference
   modules.py:73-81, SURVEY ledger #1), so there is nothing to convert:
   converted models keep this framework's fresh attention init, which is
   also exactly what a resumed reference run would have done.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax
import numpy as np

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.models import proteinbert

Params = Dict[str, Any]

_BLOCK_PREFIX = "proteinBERT_blocks."  # reference modules.py:264

# torch Sequential submodule → our block param name, with the transform
# each weight needs ("linear" transposes, "conv" goes (Cout,Cin,K)→(K,Cin,Cout)).
_BLOCK_MAP = {
    "local_narrow_conv_layer.0": ("narrow_conv", "conv"),
    "local_wide_conv_layer.0": ("wide_conv", "conv"),
    "global_to_local_linear_layer.0": ("global_to_local", "linear"),
    "local_linear_layer.0": ("local_dense", "linear"),
    "global_linear_layer_1.0": ("global_dense1", "linear"),
    "global_linear_layer_2.0": ("global_dense2", "linear"),
    "local_norm_1": ("local_ln1", "norm"),
    "local_norm_2": ("local_ln2", "norm"),
    "global_norm_1": ("global_ln1", "norm"),
    "global_norm_2": ("global_ln2", "norm"),
}


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor without importing torch here
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _weight(kind: str, w: np.ndarray) -> np.ndarray:
    if kind == "linear":  # torch (out, in) → (in, out)
        return w.T
    if kind == "conv":  # torch (Cout, Cin, K) → (K, Cin, Cout)
        return w.transpose(2, 1, 0)
    return w


def _norm_affine(w: np.ndarray) -> np.ndarray:
    """(L, C) joint-LN affine → per-feature (C,) (module docstring #1)."""
    return w.mean(axis=0) if w.ndim == 2 else w


def convert_reference_state_dict(
    state_dict: Mapping[str, Any], cfg: ModelConfig,
    init_key: jax.Array | None = None,
) -> Params:
    """Reference `model_state_dict` → this framework's param pytree.

    `cfg` must match the torch model's geometry (local/global dims,
    blocks, annotations); mismatched shapes raise. Parameters the
    reference never saved (attention heads, docstring #2) keep the fresh
    init from `init_key`.
    """
    params = jax.tree.map(
        np.asarray,
        proteinbert.init(init_key if init_key is not None else
                         jax.random.PRNGKey(0), cfg),
    )
    sd = {k: _to_numpy(v) for k, v in state_dict.items()}
    consumed = set()

    def take(key: str, target: np.ndarray, transform=lambda w: w) -> np.ndarray:
        if key not in sd:
            raise ValueError(
                f"torch state_dict is missing {key!r} (config mismatch? "
                f"e.g. more blocks configured than the checkpoint has)")
        w = transform(sd[key])
        if w.shape != target.shape:
            raise ValueError(
                f"{key}: converted shape {w.shape} != expected {target.shape} "
                f"(config mismatch?)")
        consumed.add(key)
        return w.astype(np.float32)

    params["embedding"]["embedding"] = take(
        "local_embedding.weight", params["embedding"]["embedding"])
    for name, tkey in (("global_in", "global_linear_layer.0"),
                       ("local_head", "pretraining_local_output.0"),
                       ("global_head", "pretraining_global_output.0")):
        params[name]["kernel"] = take(
            f"{tkey}.weight", params[name]["kernel"], lambda w: w.T)
        params[name]["bias"] = take(f"{tkey}.bias", params[name]["bias"])

    blocks = [dict() for _ in range(cfg.num_blocks)]
    stacked = params["blocks"]
    for i in range(cfg.num_blocks):
        if cfg.scan_blocks:
            tmpl = jax.tree.map(lambda a: a[i], stacked)
        else:
            tmpl = stacked[i]
        blk = jax.tree.map(np.asarray, tmpl)
        for sub, (ours, kind) in _BLOCK_MAP.items():
            wkey = f"{_BLOCK_PREFIX}{i}.{sub}.weight"
            bkey = f"{_BLOCK_PREFIX}{i}.{sub}.bias"
            if kind == "norm":
                blk[ours]["scale"] = take(wkey, blk[ours]["scale"], _norm_affine)
                blk[ours]["bias"] = take(bkey, blk[ours]["bias"], _norm_affine)
            else:
                blk[ours]["kernel"] = take(
                    wkey, blk[ours]["kernel"], lambda w, k=kind: _weight(k, w))
                blk[ours]["bias"] = take(bkey, blk[ours]["bias"])
        # global_attention_layer.W_parameter is the reference's learned
        # k-dim contraction for its tiled-query scheme (reference
        # modules.py:82-92); this architecture has one query per head
        # (ops/attention.py) so there is no counterpart — skip it.
        consumed.add(f"{_BLOCK_PREFIX}{i}.global_attention_layer.W_parameter")
        blocks[i] = blk

    if cfg.scan_blocks:
        params["blocks"] = jax.tree.map(
            lambda *xs: np.stack(xs), *blocks)
    else:
        params["blocks"] = blocks

    leftover = set(sd) - consumed
    if leftover:
        raise ValueError(
            f"unrecognized torch keys (wrong architecture?): "
            f"{sorted(leftover)[:5]}{'...' if len(leftover) > 5 else ''}")
    return jax.tree.map(lambda a: np.asarray(a, np.float32), params)


def load_reference_checkpoint(
    path: str, cfg: ModelConfig, init_key: jax.Array | None = None,
) -> tuple[Params, int]:
    """Load a reference torch artifact (checkpoint dict, bare state_dict,
    or pickled module — all three forms the reference produces) and
    convert it. Returns (params, step) where step is the periodic
    checkpoint's iteration counter (`current_batch_iteration`, reference
    utils.py:326-337) or 0 for the other forms. Requires torch (CPU ok).
    """
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=False)
    step = 0
    if hasattr(obj, "state_dict"):  # final whole-module save (utils.py:339-343)
        sd = obj.state_dict()
    elif isinstance(obj, Mapping) and "model_state_dict" in obj:
        sd = obj["model_state_dict"]  # periodic checkpoint (utils.py:326-337)
        step = int(obj.get("current_batch_iteration", 0))
    elif isinstance(obj, Mapping):
        sd = obj
    else:
        raise ValueError(f"unrecognized torch artifact in {path}: {type(obj)}")
    return convert_reference_state_dict(sd, cfg, init_key), step
