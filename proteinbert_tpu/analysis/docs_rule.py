"""Rule 5 — `obs-doc-drift`: code and docs/observability.md in lockstep.

docs/observability.md is the operator contract: the event-schema table
and the metric-name catalog. Both halves have drifted in past reviews
(a new event lands, the table lags a PR). This rule makes the doc a
checked artifact:

- **events, both directions**: the backticked first-column names of
  the table rows in the "## Event schema" section must equal the keys
  of `EVENT_FIELDS` exactly — an undocumented event and a documented
  ghost both fail.
- **metrics, both directions**: every LITERAL instrument name
  registered in `proteinbert_tpu/` (`counter/gauge/histogram/
  quantile_window/timer("name", ...)`, plus the `KernelPathCounter`
  shim's metric-name argument) must appear in the doc (as itself or
  inside a `{a,b,c}` brace set); and every backticked token in the
  "## Metric names" section that both LOOKS like a metric (snake_case,
  `{label=…}` stripped, brace sets expanded) and carries a Prometheus
  family suffix (`_total`, `_seconds`, `_bytes`, …) must be a
  registered name. The suffix requirement is what keeps event payload
  fields mentioned in the same prose (`bad_step`, `overlap_s`) from
  reading as ghost metrics. Names that are documented-as-removed
  history live in `cfg.docs_allow`.

Dynamic names (f-strings, `prefix + k`) are skipped — the rule checks
what it can prove, and the runtime registry remains the backstop.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from proteinbert_tpu.analysis.context import CheckContext, dotted
from proteinbert_tpu.analysis.findings import Finding
from proteinbert_tpu.analysis.schema_rule import (
    SchemaExtractionError, extract_event_fields,
)

RULE = "obs-doc-drift"

_REGISTRY_METHODS = {"counter", "gauge", "histogram", "quantile_window",
                     "timer"}
# One backticked token: `serve_batch` / `slo_burn_rate{objective=}` /
# `serve_cache_{hits,misses}_total`.
_BACKTICK_RE = re.compile(r"`([^`\s]+)`")
_TABLE_EVENT_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|")
_METRIC_TOKEN_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# Words that match the metric shape but are prose, not instruments.
_METRIC_STOPWORDS = {"snake_case", "pbt_", "label"}
# A doc token only counts as a metric CLAIM (reverse direction) when
# it carries a Prometheus-style family suffix; prose mentions of event
# payload fields share the snake_case shape but not the suffix.
_METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_rate", "_count",
                    "_depth", "_occupancy", "_progress", "_hit_rate")


def _section(text: str, heading: str) -> str:
    """The body of one `## heading` section (to the next `## `)."""
    lines = text.splitlines()
    out: List[str] = []
    inside = False
    for ln in lines:
        if ln.startswith("## "):
            inside = ln[3:].strip().lower().startswith(heading.lower())
            continue
        if inside:
            out.append(ln)
    return "\n".join(out)


def _doc_events(text: str) -> Set[str]:
    out: Set[str] = set()
    for ln in _section(text, "Event schema").splitlines():
        m = _TABLE_EVENT_RE.match(ln.strip())
        if m:
            out.add(m.group(1))
    return out


def _expand_braces(token: str) -> Optional[List[str]]:
    """`a_{x,y}_b` → [a_x_b, a_y_b]; `a{label=…}` → [a]; plain → [a];
    None when the token is not metric-shaped after expansion."""
    m = re.match(r"^([a-z0-9_]*)\{([^{}]*)\}([a-z0-9_]*)$", token)
    if m:
        pre, inner, post = m.groups()
        if "=" in inner:          # label spec: strip it
            token = pre + post if (pre + post) else pre
            candidates = [token.rstrip("_")] if token else []
        else:                     # {a,b,c} expansion
            candidates = [pre + part + post
                          for part in inner.split(",") if part]
    else:
        candidates = [token]
    ok = [c for c in candidates if _METRIC_TOKEN_RE.match(c)
          and "_" in c and c not in _METRIC_STOPWORDS]
    return ok or None


def _doc_metrics(text: str) -> Dict[str, str]:
    """{metric name: the raw token it came from} over the Metric names
    section."""
    out: Dict[str, str] = {}
    for raw in _BACKTICK_RE.findall(_section(text, "Metric names")):
        expanded = _expand_braces(raw)
        if expanded is None:
            continue
        for name in expanded:
            out.setdefault(name, raw)
    return out


def _registered_metrics(ctx: CheckContext) -> Dict[str, Tuple[str, int]]:
    """{literal instrument name: (file, line)} across the scanned
    PACKAGE roots (tools/bench are deliberately excluded — their
    ad-hoc instruments are capture plumbing, not operator surface):
    registry-method calls plus the KernelPathCounter shim's
    metric-name argument."""
    pkg_roots = tuple(r.rstrip("/") + "/" for r in ctx.cfg.scan_roots
                      if not r.endswith(".py") and r != "tools")
    out: Dict[str, Tuple[str, int]] = {}
    for pf in ctx.files:
        if pf.tree is None or not pf.path.startswith(pkg_roots):
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            head = dotted(node.func)
            if head is None:
                continue
            tail = head.rsplit(".", 1)[-1]
            if tail in _REGISTRY_METHODS:
                arg_idx = 0
            elif tail == "KernelPathCounter":
                # The shared path-counter shim registers its metric
                # name dynamically; the literal lives at arg 1.
                arg_idx = 1
            else:
                continue
            if len(node.args) <= arg_idx or not isinstance(
                    node.args[arg_idx], ast.Constant):
                continue
            name = node.args[arg_idx].value
            if isinstance(name, str) and _METRIC_TOKEN_RE.match(name) \
                    and "_" in name:
                out.setdefault(name, (pf.path, node.lineno))
    return out


def check(ctx: CheckContext) -> List[Finding]:
    doc = ctx.read_text(ctx.cfg.docs_md)
    if doc is None:
        ctx.errors.append(f"{ctx.cfg.docs_md}: missing — obs-doc-drift "
                          "rule cannot run")
        return []
    events_pf = ctx.load(ctx.cfg.events_py)
    findings: List[Finding] = []
    allow = set(ctx.cfg.docs_allow)

    # ---- events, both directions -----------------------------------
    schema_events: Set[str] = set()
    if events_pf is not None and events_pf.tree is not None:
        try:
            schema_events = set(extract_event_fields(
                events_pf.source, events_pf.path))
        except SchemaExtractionError as e:
            ctx.errors.append(str(e))
    doc_events = _doc_events(doc)
    for ev in sorted(schema_events - doc_events):
        findings.append(Finding(
            rule=RULE, path=ctx.cfg.events_py,
            line=_line_of(events_pf, f'"{ev}"'),
            symbol=f"event-undocumented:{ev}",
            message=(f"event type {ev!r} is in EVENT_FIELDS but has no "
                     f"row in {ctx.cfg.docs_md}'s Event schema table"),
        ))
    for ev in sorted(doc_events - schema_events):
        findings.append(Finding(
            rule=RULE, path=ctx.cfg.docs_md, line=1,
            symbol=f"event-ghost:{ev}",
            message=(f"{ctx.cfg.docs_md} documents event {ev!r} which "
                     "is not in EVENT_FIELDS — stale doc or missing "
                     "schema entry"),
        ))

    # ---- metrics, both directions ----------------------------------
    registered = _registered_metrics(ctx)
    doc_metrics = _doc_metrics(doc)
    for name, (path, line) in sorted(registered.items()):
        if name in allow:
            continue
        # A plain substring anywhere in the doc counts, and so does
        # membership in a brace-expanded token
        # (`serve_cache_{hits,misses,evictions}_total`).
        if name not in doc and name not in doc_metrics:
            findings.append(Finding(
                rule=RULE, path=path, line=line,
                symbol=f"metric-undocumented:{name}",
                message=(f"metric {name!r} is registered in code but "
                         f"never mentioned in {ctx.cfg.docs_md}"),
            ))
    documented_names = set(registered)
    for name, raw in sorted(doc_metrics.items()):
        if name in allow or name in documented_names:
            continue
        if not name.endswith(_METRIC_SUFFIXES):
            continue  # prose/payload-field mention, not a metric claim
        # A documented family name may be a prefix of registered
        # series (e.g. `serve_latency` → serve_latency_seconds) or a
        # suffix variant exported by the registry (`_p50_s`, `_count`);
        # only flag names with no registered relative at all.
        if any(r.startswith(name) or name.startswith(r)
               for r in documented_names):
            continue
        findings.append(Finding(
            rule=RULE, path=ctx.cfg.docs_md, line=1,
            symbol=f"metric-ghost:{name}",
            message=(f"{ctx.cfg.docs_md} mentions metric {name!r} "
                     f"(token `{raw}`) which matches no registered "
                     "instrument name — stale doc, or register/allow "
                     "it"),
        ))
    return findings


def _line_of(pf, needle: str) -> int:
    if pf is None:
        return 1
    for i, ln in enumerate(pf.lines, start=1):
        if needle in ln:
            return i
    return 1
