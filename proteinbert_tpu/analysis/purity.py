"""Rule 1 — `jit-purity`: no host nondeterminism at trace time.

The two nastiest bugs in this repo's history were trace-time invariant
violations: the PR 1 donation bug and the PR 11 "XLA hoists the dequant
converts" bug both came from host state leaking into a traced function.
This rule makes the invariant mechanical: any function reachable (via
module-local calls) from a `jax.jit` / `shard_map` / `pallas_call`
entry point must not

- read a host clock (`time.time` / `perf_counter` / `monotonic`),
- draw host randomness (`random.*`, `np.random.*` — `jax.random` is of
  course fine: it's traced),
- read the environment (`os.environ` / `os.getenv`) outside the
  sanctioned trace-time readers (`cfg.sanctioned_env_readers`, e.g.
  `force_reference_requested`, the documented
  `PBT_FORCE_REFERENCE_KERNEL` reader), or
- declare `global` (mutating a captured module global from inside a
  trace runs once per TRACE, not per step — a silent cache-keyed bug).

Reachability is intra-module and name-based: `f(x)` resolves to a
module-level `def f`, `self.m()` to a method of the lexically
enclosing class. Cross-module reachability is deliberately out of
scope (documented in docs/analysis.md) — the high-value sites (kernel
dispatch, train-step factories) keep their helpers module-local.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from proteinbert_tpu.analysis.context import (
    CheckContext, ParsedFile, dotted,
)
from proteinbert_tpu.analysis.findings import Finding

RULE = "jit-purity"

# Call heads that make their function argument a trace root. Matched on
# the final attribute (or bare imported name), so `jax.jit`, `pl.jit`…
# all hit; `pallas_call`'s kernel and `shard_map`'s f are arg 0 too.
_TRACE_ENTRY_HEADS = {"jit", "shard_map", "pallas_call"}
_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.monotonic_ns", "time.time_ns",
                "time.perf_counter_ns"}


def _head(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


class _FnInfo:
    """One function/method definition and where it lives."""

    def __init__(self, node: ast.AST, cls: Optional[str],
                 qual: str) -> None:
        self.node = node
        self.cls = cls      # enclosing class name, if a method
        self.qual = qual    # "Class.method" or "func" (nesting flattened)


def _collect_functions(tree: ast.AST) -> List[_FnInfo]:
    out: List[_FnInfo] = []

    def visit(node: ast.AST, cls: Optional[str], prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                out.append(_FnInfo(child, cls, qual))
                # Nested defs keep the class scope of their enclosing
                # method (self.x inside them still binds that class).
                visit(child, cls, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, child.name, f"{child.name}.")
            else:
                visit(child, cls, prefix)

    visit(tree, None, "")
    return out


def _trace_roots(tree: ast.AST, fns: List[_FnInfo]) -> List[_FnInfo]:
    """Functions handed to jit/shard_map/pallas_call — as a call
    argument or via decorators (@jax.jit, @partial(jax.jit, ...))."""
    by_name: Dict[str, List[_FnInfo]] = {}
    by_method: Dict[Tuple[str, str], _FnInfo] = {}
    for fi in fns:
        by_name.setdefault(fi.node.name, []).append(fi)
        if fi.cls is not None:
            by_method[(fi.cls, fi.node.name)] = fi

    roots: List[_FnInfo] = []
    seen: Set[int] = set()

    def add(fi: Optional[_FnInfo]) -> None:
        if fi is not None and id(fi.node) not in seen:
            seen.add(id(fi.node))
            roots.append(fi)

    def resolve_arg(arg: ast.AST, cls_hint: Optional[str]) -> None:
        if isinstance(arg, ast.Name):
            for fi in by_name.get(arg.id, []):
                add(fi)
        elif isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and arg.value.id == "self":
            if cls_hint is not None:
                add(by_method.get((cls_hint, arg.attr)))
            else:
                for fi in by_name.get(arg.attr, []):
                    if fi.cls is not None:
                        add(fi)
        elif isinstance(arg, ast.Lambda):
            # Treat the lambda body as an anonymous root.
            add(_FnInfo(arg, cls_hint, "<lambda>"))

    def is_entry(call: ast.Call) -> bool:
        return _head(dotted(call.func)) in _TRACE_ENTRY_HEADS

    def is_partial_entry(call: ast.Call) -> bool:
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
        return (_head(dotted(call.func)) == "partial" and call.args
                and _head(dotted(call.args[0])) in _TRACE_ENTRY_HEADS)

    # A function whose body CONTAINS a pallas_call/shard_map dispatch
    # is itself executed at trace time of whatever (possibly
    # cross-module) jit wraps it — the kernel-dispatch wrappers in
    # kernels/ are the canonical case — so its body is held to the
    # same purity bar.
    for fi in fns:
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and _head(dotted(node.func)) \
                    in ("pallas_call", "shard_map"):
                add(fi)
                break

    # Walk with class context so `jax.jit(self._fn)` resolves.
    def walk(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            child_cls = child.name if isinstance(child, ast.ClassDef) \
                else cls
            if isinstance(child, ast.Call) and is_entry(child):
                if child.args:
                    resolve_arg(child.args[0], cls)
                for kw in child.keywords:
                    if kw.arg in ("fun", "f", "kernel"):
                        resolve_arg(kw.value, cls)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in child.decorator_list:
                    if (_head(dotted(dec)) in _TRACE_ENTRY_HEADS
                        or (isinstance(dec, ast.Call)
                            and (is_entry(dec) and not dec.args
                                 or is_partial_entry(dec)))):
                        for fi in by_name.get(child.name, []):
                            if fi.node is child:
                                add(fi)
            walk(child, child_cls)

    walk(tree, None)
    return roots


def _reachable(roots: List[_FnInfo], fns: List[_FnInfo]) -> List[_FnInfo]:
    by_name: Dict[str, List[_FnInfo]] = {}
    by_method: Dict[Tuple[str, str], _FnInfo] = {}
    for fi in fns:
        by_name.setdefault(fi.node.name, []).append(fi)
        if fi.cls is not None:
            by_method[(fi.cls, fi.node.name)] = fi

    out: List[_FnInfo] = []
    seen: Set[int] = set()
    stack = list(roots)
    while stack:
        fi = stack.pop()
        if id(fi.node) in seen:
            continue
        seen.add(id(fi.node))
        out.append(fi)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                stack.extend(by_name.get(node.func.id, []))
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self" and fi.cls is not None:
                target = by_method.get((fi.cls, node.func.attr))
                if target is not None:
                    stack.append(target)
    return out


def _has_import(tree: ast.AST, module: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == module or (a.asname or a.name) == module
                   for a in node.names):
                return True
    return False


def check(ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    sanctioned = set(ctx.cfg.sanctioned_env_readers)
    for pf in ctx.files:
        if pf.tree is None:
            continue
        fns = _collect_functions(pf.tree)
        roots = _trace_roots(pf.tree, fns)
        if not roots:
            continue
        host_random = _has_import(pf.tree, "random")
        for fi in _reachable(roots, fns):
            name = getattr(fi.node, "name", "<lambda>")
            if name in sanctioned:
                continue
            findings.extend(
                _check_body(pf, fi, host_random=host_random))
    return findings


def _check_body(pf: ParsedFile, fi: _FnInfo, *,
                host_random: bool) -> List[Finding]:
    out: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        out.append(Finding(
            rule=RULE, path=pf.path, line=node.lineno,
            symbol=f"{fi.qual}:{what}",
            message=(f"{what} inside jit-reachable function "
                     f"`{fi.qual}` — host state read/mutated at trace "
                     "time; hoist it to the call site or use a "
                     "sanctioned trace-time reader"),
        ))

    for node in ast.walk(fi.node):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in _CLOCK_CALLS:
                flag(node, name)
            elif name is not None and host_random and \
                    name.startswith("random."):
                flag(node, name)
            elif name is not None and (name.startswith("np.random.")
                                       or name.startswith(
                                           "numpy.random.")):
                flag(node, name)
            elif name in ("os.getenv", "getenv"):
                flag(node, "os.getenv")
        elif isinstance(node, ast.Attribute) and node.attr == "environ":
            base = dotted(node)
            if base == "os.environ":
                flag(node, "os.environ")
        elif isinstance(node, ast.Global):
            flag(node, f"global {','.join(node.names)}")
    return out
