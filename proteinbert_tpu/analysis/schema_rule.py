"""Rule 4 — `event-schema`: every emit call site checked statically.

`obs/events.EVENT_FIELDS` is the runtime schema's single source of
truth, but until now an emitter drifting from it (unknown event name,
missing required field, wrongly-typed literal) surfaced only when the
record was actually emitted — and the never-raises telemetry contract
means it surfaced as a dropped record, not a failure. This rule moves
the check to lint time: every `*.emit("<literal>", k=v, ...)` in the
scanned tree is validated against the schema.

The schema is extracted by PARSING `events.py` (the dict literal is
read off the AST), never by importing it: importing the obs package
pulls the `proteinbert_tpu` root, which imports jax — and `pbt check`
must run jax-free as a pre-test gate.

Checked per call site with a literal event name:
- the event type exists in EVENT_FIELDS;
- every required field is present as a keyword (skipped when the call
  spreads `**fields` — the analyzer never guesses at dynamic payloads);
- keyword values that are LITERALS type-check against the declared
  type (variables pass — runtime validation still backstops them).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple, Type

from proteinbert_tpu.analysis.context import CheckContext, dotted
from proteinbert_tpu.analysis.findings import Finding

RULE = "event-schema"

_TYPE_NAMES: Dict[str, type] = {
    "str": str, "int": int, "float": float, "dict": dict,
    "list": list, "bool": bool, "tuple": tuple,
}


class SchemaExtractionError(ValueError):
    pass


def extract_event_fields(source: str, filename: str,
                         ) -> Dict[str, Dict[str, Tuple[type, ...]]]:
    """{event: {field: accepted types}} parsed from the EVENT_FIELDS
    dict literal — raises SchemaExtractionError when the assignment is
    missing or not statically readable (the gate must go red, not
    silently check nothing)."""
    tree = ast.parse(source, filename=filename)
    target: Optional[ast.Dict] = None
    for node in tree.body:
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "EVENT_FIELDS" and \
                isinstance(node.value, ast.Dict):
            target = node.value
        elif isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "EVENT_FIELDS"
                    for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            target = node.value
    if target is None:
        raise SchemaExtractionError(
            f"{filename}: no statically-readable EVENT_FIELDS dict")
    out: Dict[str, Dict[str, Tuple[type, ...]]] = {}
    for k, v in zip(target.keys, target.values):
        if not isinstance(k, ast.Constant) or not isinstance(k.value, str):
            raise SchemaExtractionError(
                f"{filename}: non-literal EVENT_FIELDS key "
                f"at line {getattr(k, 'lineno', '?')}")
        if not isinstance(v, ast.Dict):
            raise SchemaExtractionError(
                f"{filename}: EVENT_FIELDS[{k.value!r}] is not a dict "
                "literal")
        fields: Dict[str, Tuple[type, ...]] = {}
        for fk, fv in zip(v.keys, v.values):
            if not isinstance(fk, ast.Constant):
                raise SchemaExtractionError(
                    f"{filename}: non-literal field name in "
                    f"EVENT_FIELDS[{k.value!r}]")
            fields[fk.value] = _parse_types(fv, filename, k.value)
        out[k.value] = fields
    return out


def _parse_types(node: ast.AST, filename: str,
                 event: str) -> Tuple[type, ...]:
    names: List[ast.AST] = (list(node.elts)
                            if isinstance(node, ast.Tuple) else [node])
    types: List[type] = []
    for n in names:
        name = dotted(n)
        t = _TYPE_NAMES.get(name or "")
        if t is None:
            raise SchemaExtractionError(
                f"{filename}: EVENT_FIELDS[{event!r}] declares unknown "
                f"type {name!r}")
        types.append(t)
    return tuple(types)


def _literal_type(node: ast.AST) -> Optional[type]:
    """The python type of a literal keyword value; None = dynamic."""
    if isinstance(node, ast.Constant):
        return type(node.value)
    if isinstance(node, ast.Dict):
        return dict
    if isinstance(node, (ast.List, ast.ListComp)):
        return list
    if isinstance(node, ast.Tuple):
        return tuple
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)) and \
            isinstance(node.operand, ast.Constant):
        return type(node.operand.value)
    return None


def check(ctx: CheckContext) -> List[Finding]:
    events_pf = ctx.load(ctx.cfg.events_py)
    if events_pf is None or events_pf.tree is None:
        ctx.errors.append(
            f"{ctx.cfg.events_py}: events schema source missing or "
            "unparseable — event-schema rule cannot run")
        return []
    try:
        schema = extract_event_fields(events_pf.source, events_pf.path)
    except SchemaExtractionError as e:
        ctx.errors.append(str(e))
        return []

    findings: List[Finding] = []
    for pf in ctx.files:
        if pf.tree is None or pf.path == ctx.cfg.events_py:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            head = dotted(node.func)
            if head is None or head.rsplit(".", 1)[-1] != "emit":
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue  # dynamic event name: runtime validation's job
            findings.extend(_check_call(pf.path, node, schema))
    return findings


def _check_call(path: str, node: ast.Call,
                schema: Dict[str, Dict[str, Tuple[type, ...]]],
                ) -> List[Finding]:
    event = node.args[0].value
    line = node.lineno
    out: List[Finding] = []
    if event not in schema:
        out.append(Finding(
            rule=RULE, path=path, line=line,
            symbol=f"emit:{event}:unknown-event",
            message=(f"emit of unknown event type {event!r} — not in "
                     "obs.events.EVENT_FIELDS (add it to the schema "
                     "with a make_example fixture, or fix the name)"),
        ))
        return out
    required = schema[event]
    kws = {kw.arg: kw.value for kw in node.keywords if kw.arg is not None}
    has_spread = any(kw.arg is None for kw in node.keywords)
    for field, types in required.items():
        if field not in kws:
            if not has_spread:
                out.append(Finding(
                    rule=RULE, path=path, line=line,
                    symbol=f"emit:{event}:missing:{field}",
                    message=(f"emit({event!r}) is missing required "
                             f"field {field!r} — the record would be "
                             "dropped by the never-raises writer at "
                             "runtime"),
                ))
            continue
        lit = _literal_type(kws[field])
        if lit is None:
            continue
        # bool is an int subclass but the runtime validator rejects it
        # for int-typed fields across the schema; mirror that.
        ok = lit in types or (lit is int and float in types)
        if lit is bool and bool not in types:
            ok = False
        if not ok:
            names = "/".join(t.__name__ for t in types)
            out.append(Finding(
                rule=RULE, path=path, line=line,
                symbol=f"emit:{event}:type:{field}",
                message=(f"emit({event!r}): field {field!r} literal is "
                         f"{lit.__name__}, schema requires {names}"),
            ))
    return out
