"""Rule 3 — `durability-protocol`: tmp → fsync → rename, or nothing.

`mapper/store.py` owns the crash-safety story (PR 13: objects and
cursors are absent-or-complete because every write goes tmp → flush →
fsync → atomic rename) and `train/checkpoint.py` rides orbax's
equivalent. This rule pins the protocol in the durability files
(`cfg.durability_files`):

1. **rename-without-fsync**: an `os.replace`/`os.rename` whose SOURCE
   expression was opened for write in the same function must have an
   `os.fsync` between the open and the rename — otherwise the rename
   can land before the data and a crash leaves a "complete" name with
   torn bytes (precisely the torn-survivor class the drills hunt).
2. **bare-final-write**: opening a path for (over)write whose handle is
   never the source of a rename in that function writes bytes straight
   to a FINAL path — a crash mid-write leaves a torn file under its
   real name. Append mode is exempt (the quarantine/event sidecars are
   append-only by design, torn-tail-tolerant at read time).

Matching is per-function and textual on the path expression
(`ast.unparse`), which is exactly how the real code is shaped: every
atomic write in this repo opens `tmp` and replaces `tmp → path` within
one function (`_atomic_write`).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from proteinbert_tpu.analysis.context import CheckContext, dotted
from proteinbert_tpu.analysis.findings import Finding

RULE = "durability-protocol"

_WRITE_MODES = ("w", "wb", "w+", "wb+", "w+b", "x", "xb")


def _open_write_target(node: ast.Call) -> Optional[str]:
    """The unparsed path expression of an `open(path, "w*")` /
    `os.fdopen(fd, "w*")` call, or None when not a write-mode open."""
    name = dotted(node.func)
    if name not in ("open", "os.fdopen"):
        return None
    mode: Optional[str] = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str) or mode not in _WRITE_MODES:
        return None
    if not node.args:
        return None
    return ast.unparse(node.args[0])


def check(ctx: CheckContext) -> List[Finding]:
    import os

    findings: List[Finding] = []
    for rel in ctx.cfg.durability_files:
        if not os.path.exists(ctx.cfg.abspath(rel)):
            continue  # tree without this subsystem (fixture roots)
        pf = ctx.load(rel)
        if pf is None or pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_check_function(pf.path, node))
    return findings


def _check_function(path: str, fn: ast.AST) -> List[Finding]:
    opens: List[Tuple[int, str]] = []     # (line, path expr)
    fsyncs: List[int] = []                # lines
    renames: List[Tuple[int, str, ast.Call]] = []  # (line, src expr)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        target = _open_write_target(node)
        if target is not None:
            opens.append((node.lineno, target))
            continue
        name = dotted(node.func)
        if name in ("os.fsync", "fsync"):
            fsyncs.append(node.lineno)
        elif name in ("os.replace", "os.rename") and node.args:
            renames.append((node.lineno, ast.unparse(node.args[0]),
                            node))

    out: List[Finding] = []
    fname = getattr(fn, "name", "<fn>")
    renamed_exprs = {src for _, src, _ in renames}
    for rline, src, _node in renames:
        matching = [(oline, t) for oline, t in opens
                    if t == src and oline <= rline]
        if not matching:
            continue  # source not opened here (caller's durable bytes)
        oline = max(o for o, _ in matching)
        if not any(oline <= f <= rline for f in fsyncs):
            out.append(Finding(
                rule=RULE, path=path, line=rline,
                symbol=f"{fname}:rename-without-fsync:{src}",
                message=(f"`os.replace({src}, ...)` in `{fname}` renames "
                         "a file opened for write in this function with "
                         "no os.fsync between write and rename — the "
                         "rename can land before the data (torn "
                         "survivor); fsync before renaming"),
            ))
    for oline, target in opens:
        if target in renamed_exprs:
            continue  # tmp half of a tmp→rename pair
        out.append(Finding(
            rule=RULE, path=path, line=oline,
            symbol=f"{fname}:bare-final-write:{target}",
            message=(f"`open({target}, 'w…')` in `{fname}` writes bytes "
                     "directly to a final path (no tmp→fsync→rename in "
                     "this function) — a crash mid-write leaves a torn "
                     "file under its real name; write a tmp sibling and "
                     "os.replace it"),
        ))
    return out
