"""Shared parse context for `pbt check` rules.

Every rule consumes the same one-pass artifacts: each scanned file is
read and `ast.parse`d exactly once, `# guarded-by:` / `# lock-held:`
comment annotations are extracted from raw source lines (the AST drops
comments), and a cheap per-file identifier index serves the dead-export
sweep. Rules never touch the filesystem themselves — fixture tests
point a `CheckConfig` at a temp tree and get identical behavior to the
repo run.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

# --- comment annotations -------------------------------------------------
# `self.attr = ...  # guarded-by: _lock` declares that `self.attr` may
# only be touched inside `with self._lock`. `def m(...):  # lock-held:
# _lock` declares a method whose CALLERS hold the lock (the body is
# treated as locked). Both are per-line, next to the code they govern.
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
LOCK_HELD_RE = re.compile(r"#\s*lock-held:\s*([A-Za-z_]\w*)")


@dataclasses.dataclass
class ParsedFile:
    path: str                 # repo-relative, forward slashes
    abspath: str
    source: str
    lines: List[str]
    tree: Optional[ast.AST]   # None when the file failed to parse
    parse_error: Optional[str] = None

    def guarded_by(self, lineno: int) -> Optional[str]:
        m = GUARDED_BY_RE.search(self._line(lineno))
        return m.group(1) if m else None

    def lock_held(self, lineno: int) -> Optional[str]:
        m = LOCK_HELD_RE.search(self._line(lineno))
        return m.group(1) if m else None

    def _line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


@dataclasses.dataclass
class CheckConfig:
    """Everything the rules need to know about one tree. Built for the
    real repo by `runner.default_config`; fixture tests construct it by
    hand against a temp directory."""

    root: str
    # Directories/files (repo-relative) the AST rules scan.
    scan_roots: Tuple[str, ...] = ("proteinbert_tpu", "tools", "bench.py")
    # Files under the tmp→fsync→rename durability contract (rule 3).
    durability_files: Tuple[str, ...] = (
        "proteinbert_tpu/mapper/store.py",
        "proteinbert_tpu/train/checkpoint.py",
    )
    # The event schema's single source of truth (rule 4), parsed by
    # AST — never imported, so the checker stays jax-free even though
    # importing obs pulls the package root (which imports jax).
    events_py: str = "proteinbert_tpu/obs/events.py"
    # The observability reference both drift directions check (rule 5).
    docs_md: str = "docs/observability.md"
    # Extra corpus consulted when deciding whether an export is dead
    # (rule 6) — tests/examples legitimately keep an export alive.
    reference_roots: Tuple[str, ...] = (
        "proteinbert_tpu", "tools", "tests", "examples", "experiments",
        "bench.py",
    )
    # Functions allowed to read os.environ at trace time (rule 1): the
    # documented trace-time readers, e.g. PBT_FORCE_REFERENCE_KERNEL's.
    sanctioned_env_readers: Tuple[str, ...] = (
        "force_reference_requested",)
    # Metric/event names the doc may mention without a live
    # registration (rule 5) — documented-as-removed history.
    docs_allow: Tuple[str, ...] = ("fused_kernel_fallback_total",)

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)


class CheckContext:
    def __init__(self, cfg: CheckConfig):
        self.cfg = cfg
        self.errors: List[str] = []
        self._cache: Dict[str, ParsedFile] = {}
        self.files: List[ParsedFile] = []
        for rel in sorted(_walk_py(cfg.root, cfg.scan_roots)):
            pf = self.load(rel)
            if pf is not None:
                self.files.append(pf)

    # ------------------------------------------------------------ loading

    def load(self, rel: str) -> Optional[ParsedFile]:
        """Parse one repo-relative file (cached). Unreadable files are
        context errors (exit 2); unparseable ones carry parse_error and
        become findings in the runner (a syntax error in a scanned file
        must fail the gate, not vanish)."""
        rel = rel.replace(os.sep, "/")
        if rel in self._cache:
            return self._cache[rel]
        abspath = self.cfg.abspath(rel)
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            self.errors.append(f"{rel}: unreadable: {e}")
            self._cache[rel] = None  # type: ignore[assignment]
            return None
        tree: Optional[ast.AST] = None
        parse_error: Optional[str] = None
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            parse_error = f"line {e.lineno}: {e.msg}"
        pf = ParsedFile(path=rel, abspath=abspath, source=source,
                        lines=source.splitlines(), tree=tree,
                        parse_error=parse_error)
        self._cache[rel] = pf
        return pf

    def read_text(self, rel: str) -> Optional[str]:
        try:
            with open(self.cfg.abspath(rel), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    # ------------------------------------------- identifier index (rule 6)

    def identifier_index(self) -> Dict[str, Set[str]]:
        """{repo-relative path: every identifier the file mentions}
        over the reference corpus — Name ids, Attribute attrs, and
        import names. Coarse by design: the dead-export sweep must err
        toward 'used', never flag a live name."""
        index: Dict[str, Set[str]] = {}
        for rel in sorted(_walk_py(self.cfg.root,
                                   self.cfg.reference_roots)):
            pf = self.load(rel)
            if pf is None or pf.tree is None:
                continue
            ids: Set[str] = set()
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Name):
                    ids.add(node.id)
                elif isinstance(node, ast.Attribute):
                    ids.add(node.attr)
                elif isinstance(node, (ast.Import, ast.ImportFrom)):
                    for alias in node.names:
                        ids.add(alias.name.split(".")[0]
                                if isinstance(node, ast.Import)
                                else alias.name)
                        if alias.asname:
                            ids.add(alias.asname)
            index[rel] = ids
        return index


def _walk_py(root: str, rel_roots: Iterable[str]) -> List[str]:
    out: List[str] = []
    for rel in rel_roots:
        top = os.path.join(root, rel)
        if os.path.isfile(top) and rel.endswith(".py"):
            out.append(rel.replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"
                           and not d.startswith(".")]
            for fn in filenames:
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    out.append(os.path.relpath(full, root)
                               .replace(os.sep, "/"))
    return out


# ----------------------------------------------------- small AST helpers

def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def qualname(stack: List[str], name: str) -> str:
    return ".".join(stack + [name]) if stack else name
