"""`pbt check` — the project-invariant static analyzer (ISSUE 15).

Orchestrates the six rules over one shared parse of the tree, applies
the checked-in suppression baseline, and renders text or the JSON
artifact. Exit codes follow the validator-tool convention:

    0  no non-baselined findings (stale baseline entries warn only)
    1  new findings (the tier-1 gate's failure)
    2  config/internal errors (broken baseline, unreadable schema,
       syntax error in a scanned file)

Entry points:
- `python tools/pbt_check.py` — jax-free (stub-package import trick,
  see that file) — the tier-1 stage;
- `pbt check` (cli/main.py) — the operator verb, same runner;
- `run_check(cfg)` — the library call fixture tests drive.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from proteinbert_tpu.analysis import (
    docs_rule, durability, exports_rule, locks, purity, schema_rule,
)
from proteinbert_tpu.analysis.context import CheckConfig, CheckContext
from proteinbert_tpu.analysis.findings import (
    BaselineError, Finding, load_baseline, report_dict, save_baseline,
    split_by_baseline,
)

DEFAULT_BASELINE = "tools/check_baseline.json"

RULES = {
    purity.RULE: purity.check,
    locks.RULE: locks.check,
    durability.RULE: durability.check,
    schema_rule.RULE: schema_rule.check,
    docs_rule.RULE: docs_rule.check,
    exports_rule.RULE: exports_rule.check,
}


def run_check(cfg: CheckConfig,
              rules: Optional[List[str]] = None) -> Dict[str, Any]:
    """Run the selected rules; returns {"findings": [Finding...],
    "errors": [...], "rules": [...]} BEFORE baseline filtering (the
    caller owns suppression so fixture tests see raw findings)."""
    selected = list(RULES) if not rules else rules
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown}; have "
                         f"{sorted(RULES)}")
    ctx = CheckContext(cfg)
    findings: List[Finding] = []
    # A scanned file that does not parse is itself a finding: the gate
    # must not silently skip whatever the syntax error hides.
    for pf in ctx.files:
        if pf.parse_error is not None:
            findings.append(Finding(
                rule="parse", path=pf.path, line=1,
                symbol="syntax-error",
                message=f"file does not parse: {pf.parse_error}"))
    for name in selected:
        findings.extend(RULES[name](ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return {"findings": findings, "errors": list(ctx.errors),
            "rules": selected}


def main(argv: Optional[List[str]] = None,
         repo_root: Optional[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pbt check",
        description="project-invariant static analyzer (jit purity, "
                    "lock discipline, durability protocol, event "
                    "schema, doc drift, dead exports)")
    ap.add_argument("--root", default=repo_root or os.getcwd(),
                    help="tree to analyze (default: repo root)")
    ap.add_argument("--rule", action="append", metavar="NAME",
                    help=f"run only this rule (repeatable); one of "
                         f"{sorted(RULES)}")
    ap.add_argument("--baseline", default=None,
                    help="suppression baseline JSON (default: "
                         f"<root>/{DEFAULT_BASELINE})")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report to stdout")
    ap.add_argument("--json-artifact", default=None, metavar="PATH",
                    help="ALSO write the JSON report here (the "
                         "bench-trajectory check_findings_total input)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding into the "
                         "baseline file (reasons stubbed for human "
                         "review) and exit 0")
    ap.add_argument("--events-jsonl", default=None, metavar="PATH",
                    help="mirror the counts as a note(kind="
                         "check_capture) event on this stream — the "
                         "trajectory sentinel's suppression-creep "
                         "series")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    cfg = CheckConfig(root=root)
    try:
        result = run_check(cfg, rules=args.rule)
    except ValueError as e:
        print(f"pbt check: {e}", file=sys.stderr)
        return 2
    try:
        baseline = load_baseline(baseline_path)
    except BaselineError as e:
        print(f"pbt check: {e}", file=sys.stderr)
        return 2

    findings = result["findings"]
    if args.write_baseline:
        if result["errors"]:
            for err in result["errors"]:
                print(f"CONFIG ERROR: {err}", file=sys.stderr)
            print("pbt check: refusing to write a baseline while "
                  "config errors hide findings", file=sys.stderr)
            return 2
        # Syntax errors are never suppressible: a baselined parse
        # finding would let every rule silently skip that file forever.
        parse_findings = [f for f in findings if f.rule == "parse"]
        if parse_findings:
            for f in parse_findings:
                print(str(f), file=sys.stderr)
            print("pbt check: fix the syntax error(s) above before "
                  "writing a baseline", file=sys.stderr)
            return 2
        entries = dict(baseline)
        for f in findings:
            entries.setdefault(
                f.key, "UNREVIEWED (added by --write-baseline; "
                       "justify or fix)")
        save_baseline(baseline_path, entries)
        print(f"wrote {len(entries)} suppression(s) to {baseline_path}")
        return 0

    new, suppressed, stale = split_by_baseline(findings, baseline)
    report = report_dict(new, suppressed, stale, baseline,
                         result["rules"], errors=result["errors"])
    if args.events_jsonl:
        # obs.events is stdlib-only, so this stays jax-free under the
        # tools/pbt_check.py stub-package import.
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(args.events_jsonl)
        # platform="static" keys the same trajectory series
        # ("check_findings_total/static") as the fresh --check-json
        # artifact point, so checked-in history and the tier-1 run's
        # point accumulate into ONE judged series.
        ev.emit("note", source="pbt_check", kind="check_capture",
                platform="static",
                check_findings_total=report["counts"][
                    "check_findings_total"],
                check_baselined_total=report["counts"]["baselined"])
        ev.close()
    if args.json_artifact:
        with open(args.json_artifact, "w") as f:
            json.dump(report, f, indent=1)
    if args.json:
        json.dump(report, sys.stdout, indent=1)
        print()
    else:
        for f in new:
            print(str(f))
        for f in suppressed:
            print(f"baselined: {f} — {baseline.get(f.key)}")
        for key in stale:
            print(f"STALE baseline entry (matched nothing — delete "
                  f"it): {key}")
        for err in result["errors"]:
            print(f"CONFIG ERROR: {err}", file=sys.stderr)
        print(f"pbt check: {len(new)} finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'}, "
              f"{len(result['errors'])} error(s) "
              f"[rules: {', '.join(result['rules'])}]")
    if result["errors"]:
        return 2
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
