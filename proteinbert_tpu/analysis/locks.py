"""Rule 2 — `lock-discipline`: guarded attributes + static lock order.

The threaded layers (serve/scheduler, serve/fleet, obs) guard mutable
state with per-instance locks, but nothing enforced the pairing — the
unlocked `stats()`-path reads this rule was built to catch are silent
data races that only surface as flickering drill numbers. The registry
is declared IN THE SOURCE, next to the state it protects:

    self.accepted_total = 0     # guarded-by: _lock

declares that `self.accepted_total` may only be read or written inside
a lexical `with self._lock:` block (any method of the same class).
Exceptions are explicit, never inferred:

- `__init__` is exempt (construction is single-threaded by contract);
- a method whose CALLERS hold the lock declares it on its `def` line:
      def _transition(self, ...):  # lock-held: _lock

The second half is a static lock-ORDER check: within each function,
`with <lockA>:` regions that acquire `<lockB>` (directly, via a
module-local call whose body acquires it, or via a known external
acquirer like `.emit(...)` → the telemetry lock) contribute a lockA →
lockB edge; a cycle across the collected edges is a potential deadlock
and fails the gate. Lock identity is `Class._lockattr` (or
`module:name` for bare names); the known-acquirers table maps
`.emit(...)` to the shared telemetry lock — the one cross-module
acquisition this codebase actually has.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from proteinbert_tpu.analysis.context import CheckContext, ParsedFile
from proteinbert_tpu.analysis.findings import Finding

RULE = "lock-discipline"

# Method names that acquire a lock OUTSIDE the scanned module when
# called on any receiver. `.emit(...)` (EventLog/Telemetry) is the one
# real cross-module acquisition in this codebase; its lock never calls
# back out, so modeling it as a single leaf node is faithful.
KNOWN_EXTERNAL_ACQUIRERS: Dict[str, str] = {
    "emit": "obs.telemetry._lock",
}


def _lock_name(expr: ast.AST) -> Optional[str]:
    """The lock attribute/name acquired by a `with` item, if it looks
    like a lock (threading convention: name contains 'lock')."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr if "lock" in expr.attr.lower() else None
    if isinstance(expr, ast.Name):
        return expr.id if "lock" in expr.id.lower() else None
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.guarded: Dict[str, str] = {}   # attr -> lock attr


def _collect_classes(pf: ParsedFile) -> List[_ClassInfo]:
    out: List[_ClassInfo] = []
    if pf.tree is None:
        return out
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = _ClassInfo(node)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = pf.guarded_by(sub.lineno)
                if lock is None:
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        ci.guarded[t.attr] = lock
        if ci.guarded:
            out.append(ci)
    return out


class _LockRegionVisitor(ast.NodeVisitor):
    """Walk one method body tracking which declared locks are held
    lexically, flagging guarded-attribute touches outside them."""

    def __init__(self, pf: ParsedFile, cls: str, method: str,
                 guarded: Dict[str, str], held: Set[str]):
        self.pf = pf
        self.cls = cls
        self.method = method
        self.guarded = guarded
        self.held = set(held)
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = [ln for item in node.items
                    if (ln := _lock_name(item.context_expr)) is not None]
        self.held.update(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held.difference_update(acquired)
        # with-items themselves (the lock expression) need no check.

    # Nested defs get their own top-level walk via _method_findings
    # (a closure does not inherit the lexical lock region at CALL
    # time — it may run later, lock released).
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node) -> None:  # pragma: no cover
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and node.attr in self.guarded:
            lock = self.guarded[node.attr]
            if lock not in self.held:
                access = ("write" if isinstance(node.ctx,
                                                (ast.Store, ast.Del))
                          else "read")
                self.findings.append(Finding(
                    rule=RULE, path=self.pf.path, line=node.lineno,
                    symbol=f"{self.cls}.{self.method}:{node.attr}",
                    message=(f"unlocked {access} of `self.{node.attr}` "
                             f"(declared guarded-by `{lock}`) in "
                             f"`{self.cls}.{self.method}` — wrap it in "
                             f"`with self.{lock}:` or mark the method "
                             f"`# lock-held: {lock}`"),
                ))
        self.generic_visit(node)


def _method_findings(pf: ParsedFile, ci: _ClassInfo) -> List[Finding]:
    out: List[Finding] = []
    for item in ci.node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Walk the method and every CLOSURE inside it as separate
        # regions (a closure body runs with no lexical lock held).
        defs: List[ast.AST] = [item]
        for sub in ast.walk(item):
            if sub is not item and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append(sub)
        for d in defs:
            name = d.name  # type: ignore[attr-defined]
            if d is item and name == "__init__":
                break  # constructor (and its closures) exempt
            held: Set[str] = set()
            held_decl = pf.lock_held(d.lineno)
            if held_decl is not None:
                held.add(held_decl)
            visitor = _LockRegionVisitor(
                pf, ci.node.name,
                name if d is item else f"{item.name}.{name}",
                ci.guarded, held)
            for stmt in d.body:  # type: ignore[attr-defined]
                visitor.visit(stmt)
            out.extend(visitor.findings)
    return out


# ------------------------------------------------------------ lock order

def _function_acquisitions(fn: ast.AST, lock_id) -> Set[str]:
    """Locks a function's body acquires: direct `with` items plus the
    known external acquirers it calls (so a helper that only emits
    still contributes the telemetry lock to its callers' regions)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                ln = _lock_name(item.context_expr)
                if ln is not None:
                    out.add(lock_id(ln))
        elif isinstance(node, ast.Call):
            callee = (node.func.attr if isinstance(node.func,
                                                   ast.Attribute)
                      else node.func.id if isinstance(node.func,
                                                      ast.Name)
                      else None)
            ext = KNOWN_EXTERNAL_ACQUIRERS.get(callee or "")
            if ext is not None:
                out.add(ext)
    return out


def _order_edges(pf: ParsedFile) -> Dict[Tuple[str, str], int]:
    """{(held lock, acquired lock): first line} across the file."""
    if pf.tree is None:
        return {}
    edges: Dict[Tuple[str, str], int] = {}

    # Map function/method names to their direct acquisitions so a call
    # under a held lock contributes its callee's locks (one level).
    fn_acquires: Dict[str, Set[str]] = {}
    classes: Dict[ast.AST, str] = {}
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    classes[sub] = node.name

    def lock_id_for(cls: Optional[str]):
        def lock_id(name: str) -> str:
            return f"{cls}.{name}" if cls else f"{pf.path}:{name}"
        return lock_id

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = classes.get(node)
            fn_acquires.setdefault(node.name, set()).update(
                _function_acquisitions(node, lock_id_for(cls)))

    def walk_region(body, held: List[str], cls: Optional[str]) -> None:
        lock_id = lock_id_for(cls)
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # closures run later, outside the region
            if isinstance(node, ast.With):
                acquired = [lock_id(ln) for item in node.items
                            if (ln := _lock_name(item.context_expr))]
                for a in acquired:
                    for h in held:
                        if h != a:
                            edges.setdefault((h, a), node.lineno)
                walk_region(node.body, held + acquired, cls)
                continue
            if held:
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = None
                    if isinstance(sub.func, ast.Name):
                        callee = sub.func.id
                    elif isinstance(sub.func, ast.Attribute):
                        callee = sub.func.attr
                    if callee is None:
                        continue
                    targets: Set[str] = set()
                    ext = KNOWN_EXTERNAL_ACQUIRERS.get(callee)
                    if ext is not None:
                        targets.add(ext)
                    targets |= fn_acquires.get(callee, set())
                    for a in targets:
                        for h in held:
                            if h != a:
                                edges.setdefault((h, a), sub.lineno)
            walk_region(list(ast.iter_child_nodes(node)), held, cls)

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_region(node.body, [], classes.get(node))
    return edges


def _find_cycle(edges: Dict[Tuple[str, str], int]) -> Optional[List[str]]:
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(u: str) -> Optional[List[str]]:
        color[u] = GRAY
        stack.append(u)
        for v in graph.get(u, []):
            c = color.get(v, WHITE)
            if c == GRAY:
                return stack[stack.index(v):] + [v]
            if c == WHITE:
                cyc = dfs(v)
                if cyc is not None:
                    return cyc
        stack.pop()
        color[u] = BLACK
        return None

    for u in list(graph):
        if color.get(u, WHITE) == WHITE:
            cyc = dfs(u)
            if cyc is not None:
                return cyc
    return None


def check(ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    all_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for pf in ctx.files:
        if pf.tree is None:
            continue
        for ci in _collect_classes(pf):
            findings.extend(_method_findings(pf, ci))
        for edge, line in _order_edges(pf).items():
            all_edges.setdefault(edge, (pf.path, line))
    cyc = _find_cycle({e: 0 for e in all_edges})
    if cyc is not None:
        first = all_edges.get((cyc[0], cyc[1]), ("", 0))
        findings.append(Finding(
            rule=RULE, path=first[0] or "<multiple>", line=first[1] or 1,
            symbol="lock-order:" + "->".join(cyc),
            message=("inconsistent lock acquisition order (potential "
                     "deadlock): " + " -> ".join(cyc)
                     + " — acquire these locks in one global order"),
        ))
    return findings
