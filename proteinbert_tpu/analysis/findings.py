"""Typed findings + the checked-in suppression baseline (ISSUE 15).

A `Finding` is one rule violation at one `file:line`. Its `key` is
deliberately LINE-NUMBER-FREE — `rule::file::symbol` — so a checked-in
suppression survives unrelated edits to the file above it, and a
suppressed violation that MOVES (same symbol) stays suppressed while a
NEW violation (different symbol) in the same file still fails the gate.

The baseline file is the explicit debt ledger: every entry carries a
mandatory human-written `reason` (an entry without one is a config
error, exit 2 — suppressions must never be silent), and entries that no
longer match any finding are reported as STALE so paid-down debt gets
deleted instead of rotting.

Stdlib-only, like everything in this package: the analyzer must run as
a pre-test gate with no jax (or even numpy) import.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

BASELINE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str        # rule id, e.g. "jit-purity"
    path: str        # repo-relative, forward slashes
    line: int        # 1-indexed
    symbol: str      # stable anchor: "Class.method" / "func" / name
    message: str     # human sentence, pinpointing

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.symbol}"

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "key": self.key}

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message} "
                f"(key: {self.key})")


class BaselineError(ValueError):
    """Malformed baseline file — a CONFIG error (exit 2), never a
    finding: a broken suppression ledger must not silently un-suppress
    (gate goes red for the wrong reason) or over-suppress."""


def load_baseline(path: str) -> Dict[str, str]:
    """{finding key: reason}. Missing file = empty baseline (a repo
    with zero accepted debt needs no file). Every entry must carry a
    non-empty reason string."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except FileNotFoundError:
        return {}
    except ValueError as e:
        raise BaselineError(f"{path}: not JSON: {e}") from None
    if not isinstance(raw, dict) or raw.get("v") != BASELINE_VERSION:
        raise BaselineError(
            f"{path}: expected {{'v': {BASELINE_VERSION}, "
            f"'suppressions': [...]}}, got {type(raw).__name__} "
            f"v={raw.get('v') if isinstance(raw, dict) else None!r}")
    entries = raw.get("suppressions")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: 'suppressions' must be a list")
    out: Dict[str, str] = {}
    for i, ent in enumerate(entries):
        if not isinstance(ent, dict):
            raise BaselineError(f"{path}: suppression #{i} is not an "
                                "object")
        key, reason = ent.get("key"), ent.get("reason")
        if not isinstance(key, str) or "::" not in key:
            raise BaselineError(
                f"{path}: suppression #{i}: 'key' must be a "
                f"'rule::file::symbol' string, got {key!r}")
        if not isinstance(reason, str) or not reason.strip():
            raise BaselineError(
                f"{path}: suppression #{i} ({key}): every suppression "
                "must carry a non-empty human 'reason'")
        if key in out:
            raise BaselineError(f"{path}: duplicate suppression {key}")
        out[key] = reason
    return out


def save_baseline(path: str, entries: Dict[str, str]) -> None:
    doc = {"v": BASELINE_VERSION,
           "suppressions": [{"key": k, "reason": entries[k]}
                            for k in sorted(entries)]}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")


def split_by_baseline(
    findings: List[Finding], baseline: Dict[str, str],
) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, suppressed, stale_keys): `new` fails the gate, `suppressed`
    matched a baseline entry, `stale_keys` are baseline entries that
    matched nothing (debt already paid — delete them)."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    hit: set = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            hit.add(f.key)
        else:
            new.append(f)
    stale = sorted(set(baseline) - hit)
    return new, suppressed, stale


def report_dict(new: List[Finding], suppressed: List[Finding],
                stale: List[str], baseline: Dict[str, str],
                rules_run: List[str],
                errors: Optional[List[str]] = None) -> Dict[str, Any]:
    """The `pbt check --json` artifact. `check_findings_total` counts
    new + suppressed — the series `tools/bench_trajectory.py` fits, so
    suppression creep moves the trajectory even while the gate is
    green."""
    return {
        "v": 1,
        "kind": "pbt_check_report",
        "rules": sorted(rules_run),
        "findings": [f.to_dict() for f in new],
        "baselined": [dict(f.to_dict(), reason=baseline.get(f.key, ""))
                      for f in suppressed],
        "stale_baseline": stale,
        "counts": {
            "new": len(new),
            "baselined": len(suppressed),
            "stale_baseline": len(stale),
            "check_findings_total": len(new) + len(suppressed),
        },
        "errors": list(errors or []),
        "ok": not new and not (errors or []),
    }
