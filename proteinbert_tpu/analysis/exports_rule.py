"""Rule 6 — `dead-export`: package `__init__.py` names nobody uses.

Every package `__init__.py` re-exports its public surface (plus
`__all__`). Exports rot: a refactor moves the last caller and the
re-export lingers, advertising API that nothing exercises and that no
test would catch breaking. This rule flags any exported name that is
referenced NOWHERE else in the repo — not in the package, not in
tools/tests/examples/bench.

Matching is identifier-based and deliberately coarse (any `Name`,
`Attribute` attr, or import of the same identifier anywhere counts as
a use): the rule must never flag a live name; a dead one that shares
its identifier with something alive simply stays below the radar.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from proteinbert_tpu.analysis.context import CheckContext
from proteinbert_tpu.analysis.findings import Finding

RULE = "dead-export"

_DUNDER = ("__version__", "__all__")


def _exported_names(tree: ast.AST) -> Dict[str, int]:
    """{name: line} exported by one __init__: the literal __all__ when
    present, else every top-level import alias."""
    all_node = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets):
            all_node = node
    out: Dict[str, int] = {}
    if all_node is not None and isinstance(all_node.value,
                                           (ast.List, ast.Tuple)):
        for elt in all_node.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                            str):
                out[elt.value] = elt.lineno
        return out
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                if not name.startswith("_"):
                    out[name] = node.lineno
    return out


def check(ctx: CheckContext) -> List[Finding]:
    index = ctx.identifier_index()
    findings: List[Finding] = []
    for pf in ctx.files:
        if pf.tree is None or not pf.path.endswith("/__init__.py"):
            continue
        exported = _exported_names(pf.tree)
        if not exported:
            continue
        used: Set[str] = set()
        for rel, ids in index.items():
            if rel == pf.path:
                continue
            used |= ids & set(exported)
        for name in sorted(set(exported) - used):
            if name in _DUNDER:
                continue
            findings.append(Finding(
                rule=RULE, path=pf.path, line=exported[name],
                symbol=f"export:{name}",
                message=(f"`{name}` is exported from {pf.path} but "
                         "referenced nowhere else in the repo — drop "
                         "the re-export (and __all__ entry) or add the "
                         "missing consumer/test"),
            ))
    return findings
