"""`pbt check` — project-invariant static analysis (ISSUE 15).

Six stdlib-`ast` rules derived from the repo's own correctness
contracts, each with positive/negative fixture self-tests
(tests/test_analysis.py) and a checked-in suppression baseline
(tools/check_baseline.json — every entry carries a reason):

- `jit-purity`       — no host clocks/randomness/env reads/global
                       mutation reachable from jit/shard_map/
                       pallas_call (analysis/purity.py);
- `lock-discipline`  — `# guarded-by: _lock` attributes only touched
                       under their lock, plus a static lock-order
                       cycle check (analysis/locks.py);
- `durability-protocol` — tmp→fsync→rename or nothing in the durable
                       writers (analysis/durability.py);
- `event-schema`     — every `emit("<name>", ...)` call site checked
                       against EVENT_FIELDS, statically
                       (analysis/schema_rule.py);
- `obs-doc-drift`    — events/metrics vs docs/observability.md, both
                       directions (analysis/docs_rule.py);
- `dead-export`      — `__init__` exports nothing references
                       (analysis/exports_rule.py).

This package imports NOTHING from the rest of the repo (the event
schema is parsed off the AST, never imported) so `tools/pbt_check.py`
can run it without jax — see docs/analysis.md.
"""

from proteinbert_tpu.analysis.context import CheckConfig, CheckContext
from proteinbert_tpu.analysis.findings import (
    BaselineError, Finding, load_baseline, report_dict, save_baseline,
    split_by_baseline,
)
from proteinbert_tpu.analysis.runner import RULES, main, run_check

__all__ = [
    "CheckConfig", "CheckContext", "Finding", "BaselineError",
    "load_baseline", "save_baseline", "split_by_baseline",
    "report_dict", "RULES", "run_check", "main",
]
