import sys

from proteinbert_tpu.cli.main import main

sys.exit(main())
