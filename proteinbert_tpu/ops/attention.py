"""Local→global broadcast attention (reference C9/C10, paper-corrected).

The global track attends over the local (per-residue) track with a single
query set derived from the global vector — O(H·k·L), not O(L²). This is
the architecture's native answer to long sequences (SURVEY C19).

Paper-faithful redesign of the reference implementation, which has three
bugs this module deliberately does not reproduce:
- heads lived in a plain Python list, so their parameters were untrained
  and unserialized (reference modules.py:73-81; here they are pytree
  leaves, stacked on a head axis and computed as one batched einsum
  instead of a Python loop over heads, reference modules.py:87-92);
- softmax ran over the tiled-query axis instead of the sequence axis
  (reference modules.py:34,58; here softmax is over L);
- the reference tiles the global vector `key_dim` times to manufacture a
  (B, k, G) query block (reference modules.py:51) — an artifact of the
  first two bugs; here each head has ONE query, as in the paper.

Shapes (B=batch, L=seq, C=local_dim, G=global_dim, H=heads, k=key_dim,
v=value_dim=G/H):
  q = tanh(global · Wq)        (B,G)·(H,G,k)   -> (B,H,k)
  K = tanh(local · Wk)         (B,L,C)·(H,C,k) -> (B,H,L,k)
  V = gelu(local · Wv)         (B,L,C)·(H,C,v) -> (B,H,L,v)
  scores = q·K / sqrt(k)                       -> (B,H,L)   [pad-masked]
  out = softmax_L(scores)·V                    -> (B,H,v)   -> (B,G)

The tanh/gelu activations on Q/K/V follow the reference heads (reference
modules.py:49-56), which mirror the original Keras ProteinBERT. Projections
are bias-free like the reference's raw `randn` parameter matrices
(reference modules.py:27-32). Softmax is computed in float32.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from proteinbert_tpu.ops.layers import Params

_proj_init = jax.nn.initializers.lecun_normal(in_axis=1, out_axis=2)


def global_attention_init(
    key: jax.Array, local_dim: int, global_dim: int, key_dim: int, num_heads: int
) -> Params:
    assert global_dim % num_heads == 0, (
        f"global_dim {global_dim} % num_heads {num_heads} != 0"
    )  # reference modules.py:108
    value_dim = global_dim // num_heads  # reference modules.py:119
    kq, kk, kv = jax.random.split(key, 3)
    return {
        "wq": _proj_init(kq, (num_heads, global_dim, key_dim), jnp.float32),
        "wk": _proj_init(kk, (num_heads, local_dim, key_dim), jnp.float32),
        "wv": _proj_init(kv, (num_heads, local_dim, value_dim), jnp.float32),
    }


def global_attention_apply(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    pad_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Attend from the global vector over local positions.

    Args:
      local: (B, L, C) local track.
      global_: (B, G) global track.
      pad_mask: optional (B, L) bool, True at REAL positions. Padding is
        excluded from the softmax (the reference attends over padding,
        reference modules.py:58 — corrected here).
    Returns:
      (B, G) attention output in the activation dtype of `local`.
    """
    dtype = local.dtype
    wq = params["wq"].astype(dtype)
    wk = params["wk"].astype(dtype)
    wv = params["wv"].astype(dtype)
    key_dim = wq.shape[-1]

    q = jnp.tanh(jnp.einsum("bg,hgk->bhk", global_, wq))
    k = jnp.tanh(jnp.einsum("blc,hck->bhlk", local, wk))
    v = jax.nn.gelu(jnp.einsum("blc,hcv->bhlv", local, wv))

    scores = jnp.einsum("bhk,bhlk->bhl", q, k) / jnp.sqrt(
        jnp.asarray(key_dim, dtype)
    )
    scores = scores.astype(jnp.float32)
    if pad_mask is not None:
        scores = jnp.where(pad_mask[:, None, :], scores, jnp.float32(-1e30))
    weights = jax.nn.softmax(scores, axis=-1).astype(dtype)

    out = jnp.einsum("bhl,bhlv->bhv", weights, v)
    b, h, vd = out.shape
    return out.reshape(b, h * vd)


def packed_global_attention_apply(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    segment_ids: jax.Array,
    real_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-SEGMENT global attention over a packed row (data/packing.py).

    Each of a row's S packed proteins carries its own global vector and
    attends ONLY over its own positions: scores outside the segment are
    masked to -1e30, whose exp underflows to exactly 0.0 in float32 —
    so another segment's values contribute exact zeros to the weighted
    sum, and the cross-segment-leakage test can assert bit-identity
    (tests/test_packing.py). Segment slots with no positions in the row
    get a zero output (their uniform softmax over masked scores would
    otherwise mix arbitrary values; they carry zero loss weight either
    way, but zeroing keeps the (B, S, G) state leak-proof too).

    Args:
      local: (B, L, C) local track.
      global_: (B, S, G) per-segment global track.
      segment_ids: (B, L) int, 0 = pad, 1..S = segment index.
      real_mask: optional (B, L) bool, True at REAL (non-<pad>) token
        positions. Training packs carry no pad inside a segment, so it
        defaults to every in-segment position; the ragged SERVING path
        (serve/dispatch.RaggedDispatcher) packs bucket-quantized spans
        whose tails hold <pad> tokens — those must stay out of the
        softmax exactly as the bucketed path's pad_mask keeps them out.
    Returns:
      (B, S, G) attention output in the activation dtype of `local`.
    """
    dtype = local.dtype
    wq = params["wq"].astype(dtype)
    wk = params["wk"].astype(dtype)
    wv = params["wv"].astype(dtype)
    key_dim = wq.shape[-1]
    S = global_.shape[1]

    q = jnp.tanh(jnp.einsum("bsg,hgk->bshk", global_, wq))
    k = jnp.tanh(jnp.einsum("blc,hck->bhlk", local, wk))
    v = jax.nn.gelu(jnp.einsum("blc,hcv->bhlv", local, wv))

    scores = jnp.einsum("bshk,bhlk->bshl", q, k) / jnp.sqrt(
        jnp.asarray(key_dim, dtype)
    )
    scores = scores.astype(jnp.float32)
    seg_mask = (
        segment_ids[:, None, :]
        == jnp.arange(1, S + 1, dtype=segment_ids.dtype)[None, :, None]
    )  # (B, S, L)
    if real_mask is not None:
        seg_mask = seg_mask & real_mask[:, None, :]
    scores = jnp.where(seg_mask[:, :, None, :], scores, jnp.float32(-1e30))
    weights = jax.nn.softmax(scores, axis=-1).astype(dtype)

    out = jnp.einsum("bshl,bhlv->bshv", weights, v)
    seg_exists = seg_mask.any(axis=-1)  # (B, S)
    out = jnp.where(seg_exists[:, :, None, None], out,
                    jnp.zeros((), dtype))
    b, s, h, vd = out.shape
    return out.reshape(b, s, h * vd)
