"""Core functional layers: dense, LayerNorm, dilated Conv1d, embedding.

Design: every layer is a pair of pure functions — `*_init(key, ...) ->
params` (a plain dict pytree, always fp32 leaves) and `*_apply(params, x)`
(computes in the activation dtype of `x`, which the model sets to bfloat16
on TPU so matmuls/convs hit the MXU natively). This replaces the
reference's `nn.Module` layers (reference ProteinBERT/modules.py) with
jit/scan/shard-friendly pytrees; in particular every parameter is a pytree
leaf, fixing the reference bug where attention-head parameters lived in a
plain Python list and were invisible to the optimizer (reference
modules.py:73-81, SURVEY ledger #1).

Numerics:
- LayerNorm statistics are computed in float32 regardless of activation
  dtype, and normalize over the FEATURE axis only. The reference
  normalizes jointly over (seq_len, channels), which hard-codes the
  sequence length into the weight shapes (reference modules.py:148-151,
  SURVEY ledger #4); per-feature LN is paper-correct and required for
  length-bucketing and sequence sharding.
- Conv1d uses feature-last (B, L, C) layout — the natural layout for XLA
  TPU spatial convolution (and for sequence-sharding the L axis). The
  reference keeps channels-first (B, C, L) torch layout (reference
  modules.py:205-211).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

Params = Dict[str, jax.Array]

_dense_init = jax.nn.initializers.lecun_normal()
_conv_init = jax.nn.initializers.lecun_normal(in_axis=(0, 1), out_axis=2)
_embed_init = jax.nn.initializers.normal(stddev=1.0)


def dense_init(key: jax.Array, in_dim: int, out_dim: int, use_bias: bool = True) -> Params:
    p = {"kernel": _dense_init(key, (in_dim, out_dim), jnp.float32)}
    if use_bias:
        p["bias"] = jnp.zeros((out_dim,), jnp.float32)
    return p


def dense_apply(params: Params, x: jax.Array) -> jax.Array:
    """y = x @ W (+ b), contracting the last axis of x."""
    y = x @ params["kernel"].astype(x.dtype)
    if "bias" in params:
        y = y + params["bias"].astype(x.dtype)
    return y


def layer_norm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32),
            "bias": jnp.zeros((dim,), jnp.float32)}


def layer_norm_apply(params: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Per-position LN over the last (feature) axis; fp32 statistics."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = x32.var(axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype)


def conv1d_init(key: jax.Array, kernel_size: int, in_dim: int, out_dim: int) -> Params:
    return {
        "kernel": _conv_init(key, (kernel_size, in_dim, out_dim), jnp.float32),
        "bias": jnp.zeros((out_dim,), jnp.float32),
    }


def conv1d_apply(params: Params, x: jax.Array, dilation: int = 1) -> jax.Array:
    """'SAME'-padded 1D convolution in (B, L, C) layout.

    TPU-idiomatic lowering of the reference's torch Conv1d pair — the
    narrow k=9 d=1 and wide k=9 d=5 local-track convs (reference
    modules.py:124-147). XLA maps this onto the MXU as an implicit GEMM
    and, under a sequence-sharded `jit`, inserts the halo exchange for the
    (k-1)/2 * dilation boundary rows automatically.
    """
    y = lax.conv_general_dilated(
        x,
        params["kernel"].astype(x.dtype),
        window_strides=(1,),
        padding="SAME",
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    # Named for selective rematerialisation: the convs are ~85% of block
    # FLOPs, so model.remat_policy="convs" saves exactly these outputs
    # and recomputes only the cheap elementwise/LN tail in the backward
    # pass (models/proteinbert.encode).
    return checkpoint_name(y + params["bias"].astype(x.dtype), "conv_out")


def embedding_init(key: jax.Array, vocab_size: int, dim: int) -> Params:
    return {"embedding": _embed_init(key, (vocab_size, dim), jnp.float32)}


def embedding_apply(params: Params, ids: jax.Array, dtype: Optional[jnp.dtype] = None) -> jax.Array:
    table = params["embedding"]
    if dtype is not None:
        table = table.astype(dtype)
    return jnp.take(table, ids, axis=0)
