from proteinbert_tpu.ops.layers import (
    dense_init, dense_apply,
    layer_norm_init, layer_norm_apply,
    conv1d_init, conv1d_apply,
    embedding_init, embedding_apply,
)
from proteinbert_tpu.ops.attention import (
    global_attention_init, global_attention_apply,
)

__all__ = [
    "dense_init", "dense_apply",
    "layer_norm_init", "layer_norm_apply",
    "conv1d_init", "conv1d_apply",
    "embedding_init", "embedding_apply",
    "global_attention_init", "global_attention_apply",
]
