"""Inference on a pretrained trunk: embeddings, GO prediction, residue filling.

The reference repo's end goal — per the ProteinBERT paper it replicates
(reference README.md:9) — is a pretrained encoder whose representations
feed downstream protein tasks, but it ships no inference path at all (the
README defers even the pretrained model to "Soon(TM)", reference
README.md:5-6; the only forward passes live inside the training loop,
reference utils.py:291). This module supplies that missing surface,
TPU-style: one jitted batched forward reused across every entry point,
static shapes (pad to the config seq_len, fixed batch), host code doing
only string work.

Entry points:
- `load_trunk`       — restore pretrained params from an orbax run dir.
- `embed`            — (N, G) global + length-masked mean (N, C) local
                       representations (the fine-tune features of
                       models/finetune.py, exposed for external use).
- `predict_go`       — sigmoid GO-annotation probabilities / top-k.
- `predict_residues` — per-position amino-acid distributions; fills
                       '?'-masked positions with the argmax residue.

Annotations default to the all-zero vector: the corruption pipeline
explicitly trains this "no annotations known" input via its p=0.5
hide-all branch (reference data_processing.py:127-128, kept as a feature
— SURVEY ledger #5), so it is the principled query input for a sequence
whose GO terms are unknown.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from proteinbert_tpu.configs import ModelConfig, PretrainConfig
from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID, UNK_ID, get_vocab
from proteinbert_tpu.models import proteinbert

logger = logging.getLogger(__name__)

MASK_CHAR = "?"  # maps to <unk>: the "residue unknown, predict it" input

# Process-wide count of sequences whose tail was truncated to fit the
# model window (the serving layer additionally counts its own
# serve_truncated_total metric). Mutable one-slot list so callers can
# read a stable reference.
TRUNCATED_TOTAL = [0]


class SequenceTooLongError(ValueError):
    """A sequence exceeds the model window (seq_len - 2 residues) and the
    caller asked for rejection instead of truncate-and-count
    (`on_overflow="error"`, or the serving layer's `on_long="reject"`)."""


def load_state(checkpoint_dir: str, cfg: PretrainConfig):
    """Restore the full TrainState (and step) from a pretrain run dir.

    `cfg` must describe the pretrain run (preset + overrides) so the
    restore template matches the saved pytree — same contract as the
    finetune CLI's --pretrained flag (cli/main.py).
    """
    from proteinbert_tpu.train import Checkpointer, create_train_state

    template = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    ck = Checkpointer(checkpoint_dir, async_save=False)
    try:
        state, _ = ck.restore(template)
    finally:
        ck.close()
    if state is None:
        raise FileNotFoundError(f"no checkpoint found in {checkpoint_dir}")
    return state, int(state.step)


def load_trunk(checkpoint_dir: str, cfg: PretrainConfig):
    """Restore pretrained params (and step) — load_state for callers that
    only need the model weights."""
    state, step = load_state(checkpoint_dir, cfg)
    return state.params, step


@partial(jax.jit, static_argnames=("cfg", "per_residue"))
def _encode_batch(params, tokens, annotations, cfg: ModelConfig,
                  per_residue: bool = False):
    local, global_ = proteinbert.encode(params, tokens, annotations, cfg)
    mask = (tokens != PAD_ID).astype(jnp.float32)[:, :, None]
    local = local.astype(jnp.float32)
    out = {
        "local_mean": (local * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0),
        "global": global_.astype(jnp.float32),
    }
    if per_residue:  # only ship the big (B, L, C) track when asked
        out["local"] = local
    return out


def _segment_real_mask(tokens, segment_ids, num_segments: int):
    """(B, S, L) bool: True where position l belongs to segment s AND
    holds a real (non-<pad>) token. A ragged serving span is bucket-
    quantized (serve/dispatch.RaggedDispatcher), so its tail holds
    <pad> tokens that must be excluded from pooling/attention exactly
    as the bucketed path's pad_mask excludes them."""
    seg = (segment_ids[:, None, :]
           == jnp.arange(1, num_segments + 1,
                         dtype=segment_ids.dtype)[None, :, None])
    return seg & (tokens != PAD_ID)[:, None, :]


@partial(jax.jit, static_argnames="cfg")
def _packed_encode_batch(params, tokens, segment_ids, annotations,
                         cfg: ModelConfig):
    """The ragged serving form of `_encode_batch`: one fixed-shape
    (rows, seq_len) packed batch of up to S segments per row →
    {"local_mean": (B, S, C), "global": (B, S, G)} float32 per-SEGMENT
    representations. Per-segment math mirrors the bucketed entry
    row-for-row (mask-weighted mean over real positions), so a span's
    outputs match the bucketed dispatcher's within jitted tolerance
    (docs/serving.md, ragged batching). Under cfg.use_pallas the local
    track runs the segment-aware fused Pallas kernel on supported
    shapes (kernels/fused_block.fused_local_track_segments, ISSUE 10)
    — the packed executables this builds are fast-path executables,
    counted in fused_kernel_path_total{path=pallas,reason=packed}."""
    pad_mask = tokens != PAD_ID
    local, global_ = proteinbert.encode(params, tokens, annotations, cfg,
                                        pad_mask=pad_mask,
                                        segment_ids=segment_ids)
    m = _segment_real_mask(tokens, segment_ids,
                           annotations.shape[1]).astype(jnp.float32)
    local = local.astype(jnp.float32)
    local_mean = (jnp.einsum("bsl,blc->bsc", m, local)
                  / jnp.maximum(m.sum(-1)[..., None], 1.0))
    return {"local_mean": local_mean, "global": global_.astype(jnp.float32)}


@partial(jax.jit, static_argnames="cfg")
def _packed_go_probs_batch(params, tokens, segment_ids, annotations,
                           cfg: ModelConfig):
    """(B, S, A) sigmoid GO probabilities per packed segment."""
    _, global_logits = proteinbert.apply(
        params, tokens, annotations, cfg, pad_mask=(tokens != PAD_ID),
        segment_ids=segment_ids)
    return jax.nn.sigmoid(global_logits)


@partial(jax.jit, static_argnames="cfg")
def _packed_residue_probs_batch(params, tokens, segment_ids, annotations,
                                cfg: ModelConfig):
    """(B, L, V) per-position softmax over a packed batch; callers
    slice each segment's span back out (the span's rows line up with
    the bucketed entry's (bucket_len, V) output)."""
    local_logits, _ = proteinbert.apply(
        params, tokens, annotations, cfg, pad_mask=(tokens != PAD_ID),
        segment_ids=segment_ids)
    return jax.nn.softmax(local_logits, -1)


@partial(jax.jit, static_argnames="cfg")
def _go_probs_batch(params, tokens, annotations, cfg: ModelConfig):
    _, global_logits = proteinbert.apply(params, tokens, annotations, cfg)
    return jax.nn.sigmoid(global_logits)


@partial(jax.jit, static_argnames="cfg")
def _residue_probs_batch(params, tokens, annotations, cfg: ModelConfig):
    local_logits, _ = proteinbert.apply(params, tokens, annotations, cfg)
    return jax.nn.softmax(local_logits, -1)


def _tokenize_masked(seqs: Sequence[str], seq_len: int,
                     on_overflow: str = "warn") -> np.ndarray:
    """Tokenize with MASK_CHAR → <unk> (no random crop: inference is
    deterministic).

    Over-length handling is never silent (the seed behavior clipped
    quietly): sequences longer than seq_len-2 residues are either
    rejected with SequenceTooLongError (`on_overflow="error"`) or
    truncated AND counted in TRUNCATED_TOTAL, with one warning per call
    (`on_overflow="warn"`, the default; "count" skips the log line for
    callers that surface the count themselves — the serving layer does,
    via its own serve_truncated_total metric in Server.submit).
    """
    if on_overflow not in ("warn", "error", "count"):
        raise ValueError(f"on_overflow must be 'warn', 'error', or "
                         f"'count', got {on_overflow!r}")
    window = seq_len - 2
    too_long = [i for i, s in enumerate(seqs) if len(s) > window]
    if too_long:
        if on_overflow == "error":
            raise SequenceTooLongError(
                f"{len(too_long)} sequence(s) exceed the model window of "
                f"{window} residues (first: index {too_long[0]}, length "
                f"{len(seqs[too_long[0]])}); raise data.seq_len, split "
                "the sequence, or allow truncation")
        TRUNCATED_TOTAL[0] += len(too_long)
        if on_overflow == "warn":
            logger.warning(
                "truncating %d sequence(s) longer than the %d-residue "
                "model window to their first %d residues (counted in "
                "inference.TRUNCATED_TOTAL)", len(too_long), window,
                window)
    vocab = get_vocab()
    out = np.full((len(seqs), seq_len), PAD_ID, dtype=np.int32)
    for i, seq in enumerate(seqs):
        seq = seq[:window]
        ids = vocab.encode(seq)  # MASK_CHAR is outside the alphabet → <unk>
        out[i, 0] = SOS_ID
        out[i, 1 : 1 + len(ids)] = ids
        out[i, 1 + len(ids)] = EOS_ID
    return out


def check_annotations(annotations: Optional[np.ndarray], n: int,
                      cfg: PretrainConfig) -> np.ndarray:
    """Default-and-validate a query annotation matrix to (n, A) float32
    (None → the trained "no annotations known" all-zero input). Shared
    by the offline batch path, the bucketed path, and the serving
    layer's submit-time validation."""
    if annotations is None:
        annotations = np.zeros((n, cfg.model.num_annotations), np.float32)
    annotations = np.asarray(annotations, np.float32)
    if annotations.shape != (n, cfg.model.num_annotations):
        raise ValueError(
            f"annotations shape {annotations.shape} != "
            f"({n}, {cfg.model.num_annotations})"
        )
    return annotations


def fill_masked_residues(seq: str, probs: np.ndarray, window: int) -> str:
    """Fill each MASK_CHAR in seq[:window] with the argmax amino acid
    from `probs` — one (L, V) softmax row, position 0 = <sos> — never
    choosing pad/sos/eos/unk; the un-modeled tail beyond `window`
    passes through unchanged. Shared by offline `predict_residues` and
    the serving finalizer (serve/server.py) so the fill rule cannot
    drift between the two surfaces."""
    aa = np.asarray(probs).copy()
    aa[:, : UNK_ID + 1] = 0.0  # only amino-acid tokens are valid fills
    vocab = get_vocab()
    chars = list(seq[:window])
    for pos, ch in enumerate(chars):
        if ch == MASK_CHAR:
            chars[pos] = vocab.itos[int(aa[pos + 1].argmax())]
    return "".join(chars) + seq[window:]


def _batched(
    params, cfg: PretrainConfig, tokens: np.ndarray,
    annotations: Optional[np.ndarray], batch_size: int, fn,
) -> List:
    """Run `fn(params, tokens, annotations, model_cfg)` over fixed-size
    batches (last one padded so every call hits the same compiled shape);
    returns the per-batch outputs trimmed back to the true row count.
    `fn` must return only what the caller keeps — every leaf is copied to
    host and retained across the whole run."""
    n = tokens.shape[0]
    if n == 0:
        raise ValueError("no sequences given")
    annotations = check_annotations(annotations, n, cfg)
    outs = []
    for start in range(0, n, batch_size):
        tb = tokens[start : start + batch_size]
        ab = annotations[start : start + batch_size]
        rows = tb.shape[0]
        if rows < batch_size:  # pad the tail batch to the compiled shape
            tb = np.pad(tb, ((0, batch_size - rows), (0, 0)))
            ab = np.pad(ab, ((0, batch_size - rows), (0, 0)))
        res = fn(params, jnp.asarray(tb), jnp.asarray(ab), cfg.model)
        outs.append(jax.tree.map(lambda a: np.asarray(a)[:rows], res))
    return outs


def embed_batches(
    params, cfg: PretrainConfig, seqs: Sequence[str],
    annotations: Optional[np.ndarray] = None, batch_size: int = 32,
    per_residue: bool = False, on_overflow: str = "warn",
):
    """Yield per-batch representation dicts — the streaming form of
    `embed` (host memory stays O(batch), so million-sequence FASTA runs
    can write each batch straight to disk; the embed CLI does exactly
    that for HDF5 output).

    Each yielded dict holds float32 "global" (b, G) and "local_mean"
    (b, C) — plus "local" (b, seq_len, C) and int32 "tokens"
    (b, seq_len) with `per_residue=True` — where b ≤ batch_size is the
    batch's true row count.
    """
    n = len(seqs)
    if n == 0:
        raise ValueError("no sequences given")
    for start in range(0, n, batch_size):
        # Tokenize per chunk — this is what keeps host memory O(batch).
        chunk_tokens = _tokenize_masked(seqs[start : start + batch_size],
                                        cfg.data.seq_len, on_overflow)
        chunk_ann = (annotations[start : start + batch_size]
                     if annotations is not None else None)
        out = _batched(
            params, cfg, chunk_tokens, chunk_ann, batch_size,
            partial(_encode_batch, per_residue=per_residue))[0]
        if per_residue:
            out["tokens"] = chunk_tokens
        yield out


def _bucketed_rows(params, cfg: PretrainConfig, kind: str,
                   tokens: np.ndarray, annotations: Optional[np.ndarray],
                   batch_size: int, buckets):
    """Route an offline batch job through the serving layer's bucket
    dispatcher (serve/dispatch.py): rows grouped by length bucket, each
    group run at its bucket length instead of the full seq_len, results
    reassembled in input order. Shares the jitted kernels with the
    unbucketed path, so with buckets=(seq_len,) the output is
    bit-identical to it (tests/test_serve.py proves this)."""
    from proteinbert_tpu.serve.dispatch import BucketDispatcher

    if tokens.shape[0] == 0:
        raise ValueError("no sequences given")

    dispatcher = BucketDispatcher(
        params, cfg, buckets=buckets, max_batch=batch_size,
        batch_classes=(batch_size,))
    return dispatcher.run_rows(kind, tokens, annotations, batch_size)


def embed(
    params, cfg: PretrainConfig, seqs: Sequence[str],
    annotations: Optional[np.ndarray] = None, batch_size: int = 32,
    per_residue: bool = False, bucketed: bool = False, buckets=None,
    on_overflow: str = "warn",
) -> Dict[str, np.ndarray]:
    """Trunk representations for downstream use.

    Returns {"global": (N, G), "local_mean": (N, C)} float32 — and, with
    `per_residue=True`, "local": (N, seq_len, C) plus "tokens":
    (N, seq_len) int32 so callers can mask pad positions themselves.
    Holds all N rows in memory; for large N use `embed_batches`.

    `bucketed=True` routes through the serving bucket dispatcher: rows
    run at their length bucket (`buckets` ascending, last == seq_len;
    default cfg.data.buckets, else the single full-length bucket)
    instead of all padding to seq_len — same numbers, fewer FLOPs for
    short sequences. Incompatible with `per_residue` (whose output is
    full-seq_len shaped by contract).
    """
    if bucketed:
        if per_residue:
            raise ValueError(
                "per_residue output is (N, seq_len, C) by contract; "
                "bucketed execution would change its shape — use "
                "bucketed=False for per-residue embeddings")
        n = len(seqs)
        if n == 0:
            raise ValueError("no sequences given")
        tokens = _tokenize_masked(seqs, cfg.data.seq_len, on_overflow)
        annotations = check_annotations(annotations, n, cfg)
        return _bucketed_rows(params, cfg, "embed", tokens, annotations,
                              batch_size, buckets)
    outs = list(embed_batches(params, cfg, seqs, annotations, batch_size,
                              per_residue, on_overflow))
    return {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}


def predict_go(
    params, cfg: PretrainConfig, seqs: Sequence[str],
    batch_size: int = 32, top_k: Optional[int] = None,
    bucketed: bool = False, buckets=None, on_overflow: str = "warn",
):
    """GO-annotation probabilities from sequence alone.

    Returns (N, A) sigmoid probabilities; with `top_k`, instead a list of
    N descending [(annotation_index, prob), ...] lists. The indices are
    rows of the HDF5 builder's `included_annotations` mapping
    (etl/h5_builder.py) — join against the GO-meta CSV for names.
    `bucketed=True` runs each row at its length bucket (see `embed`).
    """
    tokens = _tokenize_masked(seqs, cfg.data.seq_len, on_overflow)
    if bucketed:
        probs = _bucketed_rows(params, cfg, "predict_go", tokens, None,
                               batch_size, buckets)
    else:
        outs = _batched(params, cfg, tokens, None, batch_size,
                        _go_probs_batch)
        probs = np.concatenate(outs)
    if top_k is None:
        return probs
    k = min(top_k, probs.shape[1])
    idx = np.argsort(-probs, axis=1)[:, :k]
    return [
        [(int(j), float(p)) for j, p in zip(row, prob_row[row])]
        for row, prob_row in zip(idx, probs)
    ]


def predict_residues(
    params, cfg: PretrainConfig, seqs: Sequence[str], batch_size: int = 32,
    bucketed: bool = False, buckets=None, on_overflow: str = "warn",
) -> Tuple[List[str], np.ndarray]:
    """Per-position amino-acid prediction; '?' marks residues to fill.

    '?' positions enter the model as <unk> — the same "identity lost"
    condition the denoising pretraining's token randomization teaches the
    model to repair (reference data_processing.py:86-105). Returns
    (filled_seqs, probs (N, seq_len, V) softmax over the full vocab).

    Sequences longer than cfg.data.seq_len - 2 with a '?' in the
    truncated tail are rejected (the model never sees those positions,
    so "filling" them would silently return the mask unchanged).

    `bucketed=True` runs each row at its length bucket (see `embed`);
    probability rows beyond a row's bucket length come back zero-filled
    (those positions are pad by construction).
    """
    window = cfg.data.seq_len - 2
    for i, seq in enumerate(seqs):
        if MASK_CHAR in seq[window:]:
            raise ValueError(
                f"sequence {i} has a {MASK_CHAR!r} beyond position "
                f"{window} — outside the model's seq_len window; raise "
                "data.seq_len (--pretrained-set data.seq_len=...) or "
                "split the sequence")
    tokens = _tokenize_masked(seqs, cfg.data.seq_len, on_overflow)
    if bucketed:
        probs = _bucketed_rows(params, cfg, "predict_residues", tokens,
                               None, batch_size, buckets)
    else:
        outs = _batched(params, cfg, tokens, None, batch_size,
                        _residue_probs_batch)
        probs = np.concatenate(outs)
    filled = [fill_masked_residues(seq, probs[i], window)
              for i, seq in enumerate(seqs)]
    return filled, probs
