"""SQLite + FASTA → HDF5 pretraining dataset (reference C3/C4, redesigned).

Reference behavior (uniref_dataset.py:201-320): read the GO-meta CSV, keep
annotations with >=100 records, run the SQLite↔FASTA join TWICE (once just
to count rows, once to write), and slice-assign 10k-row chunks into fixed
h5 datasets. Here the join runs ONCE into resizable chunked datasets —
halving ETL wall-clock on a corpus that takes hours to scan — and the
dataset names/layout match the reference's exactly (`included_annotations`,
`uniprot_ids`, `seqs`, `seq_lengths`, `annotation_masks`) so the reader in
data/dataset.py serves either origin.

The per-host sharded training feed then slices this one file by row range
(data/dataset.py make_pretrain_iterator) — no per-host file splits needed.
"""

from __future__ import annotations

import json
import sqlite3
from typing import Iterator, List, Optional, Tuple

import numpy as np

from proteinbert_tpu.etl.fasta import FastaReader
from proteinbert_tpu.etl.go_ontology import load_meta_csv
from proteinbert_tpu.utils.logging import log


def load_seqs_and_annotations(
    sqlite_path: str,
    fasta_path: str,
    shuffle: bool = True,
    seed: int = 0,
    records_limit: Optional[int] = None,
    verbose: bool = True,
    log_progress_every: int = 100_000,
    stats: Optional[dict] = None,
) -> Iterator[Tuple[str, str, List[int]]]:
    """Yield (uniprot_id, sequence, completed_annotation_indices) by
    joining SQLite records to FASTA via the `UniRef90_<accession>` key
    (reference uniref_dataset.py:274-320). Deterministic shuffle keeps
    the reference's reproducible-ordering property (its seed-0 sample at
    uniref_dataset.py:294) without materializing a DataFrame.

    `stats`: optional dict the generator fills as it runs —
    {'n_yielded', 'n_unjoinable'} — so callers (and hostile-input
    tests) can assert how many annotation records had no FASTA
    sequence instead of trusting a log line. Unjoinable ids are
    counted and skipped, never a crash.
    """
    # Stream in O(fetch_chunk) row memory: materialize only the int64 key
    # column (8 bytes/row — fine even at UniRef90's ~10^8 rows), shuffle
    # the keys, then batch-fetch rows by key chunk. A fetchall of the
    # string columns here would hold tens of GB of Python objects.
    fetch_chunk = 10_000
    if stats is None:
        stats = {}
    stats.update(n_yielded=0, n_unjoinable=0)
    conn = sqlite3.connect(sqlite_path)
    try:
        keys = np.fromiter(
            (r[0] for r in conn.execute(
                "SELECT entry_index FROM protein_annotations ORDER BY entry_index"
                + (f" LIMIT {int(records_limit)}" if records_limit else ""))),
            dtype=np.int64,
        )
        if verbose:
            log(f"joining {len(keys)} annotation records from {sqlite_path}")
        if shuffle:
            np.random.default_rng(seed).shuffle(keys)

        n_failed = 0
        with FastaReader(fasta_path) as fasta:
            for lo in range(0, len(keys), fetch_chunk):
                chunk = keys[lo : lo + fetch_chunk]
                placeholders = ",".join("?" * len(chunk))
                fetched = dict(
                    (k, (name, raw)) for k, name, raw in conn.execute(
                        "SELECT entry_index, uniprot_name, "
                        "complete_go_annotation_indices FROM protein_annotations "
                        f"WHERE entry_index IN ({placeholders})",
                        [int(k) for k in chunk],
                    )
                )
                for pos, k in enumerate(chunk):
                    if verbose and (lo + pos) % log_progress_every == 0 and lo + pos:
                        log(f"join: {lo + pos}/{len(keys)}")
                    uniprot_name, raw_indices = fetched[int(k)]
                    fasta_id = f"UniRef90_{uniprot_name.split('_')[0]}"
                    if fasta_id not in fasta:
                        n_failed += 1
                        stats["n_unjoinable"] = n_failed
                        continue
                    stats["n_yielded"] += 1
                    yield uniprot_name, fasta.fetch(fasta_id), json.loads(raw_indices)
    finally:
        conn.close()
    if verbose:
        log(f"join finished; {n_failed}/{len(keys)} records had no sequence")


def create_h5_dataset(
    sqlite_path: str,
    fasta_path: str,
    go_meta_csv_path: str,
    output_h5_path: str,
    shuffle: bool = True,
    seed: int = 0,
    min_records_to_keep_annotation: int = 100,
    records_limit: Optional[int] = None,
    chunk_size: int = 10_000,
    verbose: bool = True,
) -> int:
    """Build the HDF5 pretraining dataset in ONE pass; returns row count."""
    import h5py

    meta = load_meta_csv(go_meta_csv_path)
    common = sorted(
        (r for r in meta if r["count"] >= min_records_to_keep_annotation),
        key=lambda r: r["id"],
    )
    # original dense ontology index → position in the common subset
    # (reference uniref_dataset.py:216-217).
    orig_to_common = {r["index"]: i for i, r in enumerate(common)}
    n_common = len(common)
    if n_common == 0:
        raise ValueError(
            f"no GO annotation has >= {min_records_to_keep_annotation} records "
            f"in {go_meta_csv_path}; lower min_records_to_keep_annotation "
            "(--min-records) for small corpora")
    if verbose:
        log(f"encoding the {n_common} annotations with >= "
            f"{min_records_to_keep_annotation} records")

    str_dt = h5py.string_dtype()
    with h5py.File(output_h5_path, "w") as h5f:
        h5f.create_dataset(
            "included_annotations",
            data=np.array([r["id"].encode("ascii") for r in common], dtype=object),
            dtype=str_dt,
        )
        uniprot_ids = h5f.create_dataset(
            "uniprot_ids", shape=(0,), maxshape=(None,), dtype=str_dt,
            chunks=(chunk_size,))
        seqs = h5f.create_dataset(
            "seqs", shape=(0,), maxshape=(None,), dtype=str_dt,
            chunks=(chunk_size,))
        seq_lengths = h5f.create_dataset(
            "seq_lengths", shape=(0,), maxshape=(None,), dtype=np.int32,
            chunks=(chunk_size,))
        annotation_masks = h5f.create_dataset(
            "annotation_masks", shape=(0, n_common), maxshape=(None, n_common),
            dtype=bool, chunks=(min(chunk_size, 1024), n_common))

        n = 0
        buf_ids: List[str] = []
        buf_seqs: List[str] = []
        buf_ann: List[List[int]] = []

        def flush():
            nonlocal n
            if not buf_ids:
                return
            lo, hi = n, n + len(buf_ids)
            for ds in (uniprot_ids, seqs, seq_lengths):
                ds.resize((hi,))
            annotation_masks.resize((hi, n_common))
            uniprot_ids[lo:hi] = buf_ids
            seqs[lo:hi] = buf_seqs
            seq_lengths[lo:hi] = np.fromiter(
                (len(s) for s in buf_seqs), dtype=np.int32, count=len(buf_seqs))
            mask = np.zeros((len(buf_ids), n_common), dtype=bool)
            for r, idxs in enumerate(buf_ann):
                cols = [orig_to_common[i] for i in idxs if i in orig_to_common]
                mask[r, cols] = True
            annotation_masks[lo:hi] = mask
            n = hi
            buf_ids.clear(); buf_seqs.clear(); buf_ann.clear()

        for uid, seq, ann_indices in load_seqs_and_annotations(
            sqlite_path, fasta_path, shuffle=shuffle, seed=seed,
            records_limit=records_limit, verbose=verbose,
        ):
            buf_ids.append(uid)
            buf_seqs.append(seq)
            buf_ann.append(ann_indices)
            if len(buf_ids) >= chunk_size:
                flush()
        flush()

    if verbose:
        log(f"wrote {n} rows x {n_common} annotations to {output_h5_path}")
    return n
