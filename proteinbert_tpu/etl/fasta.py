"""Random-access FASTA reader (replaces the reference's pyfaidx dependency).

The reference joins SQLite annotation records to sequences through
`pyfaidx.Faidx` (reference uniref_dataset.py:299-313). pyfaidx is not in
this image, and the join only ever needs whole-record fetches by id — so
this is a minimal two-level design: an index pass recording
(byte offset, sequence length, line layout) per record in `.fai` format
(samtools-compatible: name, rlen, offset, line bases, line bytes), and an
O(1) fetch that seeks and strips newlines. Gzip inputs are supported for
indexing by streaming (no random access; `fetch` requires the plain file).
"""

from __future__ import annotations

import gzip
import os
import tempfile
from typing import Dict, Iterator, Tuple


def _open_text(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "r")


def iter_fasta(path: str) -> Iterator[Tuple[str, str]]:
    """Stream (name, sequence) pairs; name is the first word of the header."""
    name, parts = None, []
    with _open_text(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith(">"):
                if name is not None:
                    yield name, "".join(parts)
                name, parts = line[1:].split()[0] if len(line) > 1 else "", []
            elif line:
                parts.append(line.rstrip("\r"))
        if name is not None:
            yield name, "".join(parts)


def build_index(fasta_path: str, index_path: str | None = None,
                use_native: bool = True) -> str:
    """Write a samtools-style .fai index; returns its path.

    Dispatches to the C++ scanner (native/fasta_index.cpp) when available
    — UniRef90's FASTA is tens of GB and this loop is the index-build
    bottleneck; the pure-Python path below is the semantic ground truth
    (parity-tested in tests/test_native.py) and the automatic fallback.

    The index is written to a temp path and renamed into place only on
    success: FastaReader trusts any existing .fai, so a build that raises
    (ragged input) must not leave a truncated index behind.
    """
    index_path = index_path or fasta_path + ".fai"
    # mkstemp (not a pid suffix): concurrent builders in the SAME process
    # (reader threads racing to index) must not share a temp file.
    fd, tmp_path = tempfile.mkstemp(
        prefix=os.path.basename(index_path) + ".tmp",
        dir=os.path.dirname(os.path.abspath(index_path)))
    os.close(fd)
    try:
        _build_index_impl(fasta_path, tmp_path, use_native)
        os.chmod(tmp_path, 0o644)  # mkstemp is 0600
        os.replace(tmp_path, index_path)
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
    return index_path


def _build_index_impl(fasta_path: str, index_path: str,
                      use_native: bool) -> None:
    if use_native:
        from proteinbert_tpu.native.fasta_index import build_fai_native

        if build_fai_native(fasta_path, index_path) is not None:
            return
    with open(fasta_path, "rb") as f, open(index_path, "w") as out:
        name = None
        rlen = 0
        seq_offset = 0
        line_bases = 0
        line_bytes = 0
        offset = 0
        short_line_seen = False  # a narrower line is only legal as the LAST
        for raw in f:
            if raw.startswith(b">"):
                if name is not None:
                    out.write(f"{name}\t{rlen}\t{seq_offset}\t{line_bases}\t{line_bytes}\n")
                header = raw[1:].split()
                name = header[0].decode() if header else ""
                rlen = 0
                line_bases = 0
                line_bytes = 0
                short_line_seen = False
                seq_offset = offset + len(raw)
            else:
                stripped = raw.rstrip(b"\r\n")
                if stripped:
                    # The offset arithmetic in fetch() only holds for
                    # uniformly wrapped records (all lines equal width,
                    # except possibly the last). Reject anything else
                    # rather than silently truncate.
                    if short_line_seen or (line_bases and len(stripped) > line_bases):
                        raise ValueError(
                            f"record {name!r} in {fasta_path} has non-uniform "
                            "line widths; re-wrap the FASTA before indexing")
                    if line_bases == 0:
                        line_bases = len(stripped)
                        line_bytes = len(raw)
                    elif len(stripped) < line_bases:
                        short_line_seen = True
                    rlen += len(stripped)
                elif line_bases:
                    # A blank line inside a record is a width-0 line: legal
                    # only if nothing follows (same rule as a short line).
                    short_line_seen = True
            offset += len(raw)
        if name is not None:
            out.write(f"{name}\t{rlen}\t{seq_offset}\t{line_bases}\t{line_bytes}\n")


class FastaReader:
    """O(1) whole-record fetch by id over an indexed plain-text FASTA."""

    def __init__(self, fasta_path: str):
        if fasta_path.endswith(".gz"):
            raise ValueError(
                "random access needs an uncompressed FASTA; gunzip first "
                "(indexing via iter_fasta works on .gz)"
            )
        fai = fasta_path + ".fai"
        if not os.path.exists(fai):
            build_index(fasta_path, fai)
        self.index: Dict[str, Tuple[int, int, int, int]] = {}
        with open(fai) as f:
            for line in f:
                name, rlen, off, lb, lw = line.rstrip("\n").split("\t")
                self.index[name] = (int(rlen), int(off), int(lb), int(lw))
        self._f = open(fasta_path, "rb")

    def __contains__(self, name: str) -> bool:
        return name in self.index

    def __len__(self) -> int:
        return len(self.index)

    def length(self, name: str) -> int:
        return self.index[name][0]

    def fetch(self, name: str) -> str:
        """Full sequence for `name` (KeyError if absent, like pyfaidx)."""
        return self.fetch_range(name, 0, self.index[name][0])

    def fetch_range(self, name: str, start: int, end: int) -> str:
        """Bases [start, end) (0-based) of record `name`, via
        coordinate→byte-offset arithmetic — one seek + one read, the
        random-access primitive the reference's ChromosomeReader builds
        its genome coordinates on (reference
        shared_utils/reference_genome.py:67-99)."""
        rlen, off, line_bases, line_bytes = self.index[name]
        start = max(0, start)
        end = min(rlen, end)
        if end <= start:
            return ""
        newline = line_bytes - line_bases
        byte_lo = off + start + (start // line_bases) * newline
        last = end - 1
        byte_hi = off + last + (last // line_bases) * newline + 1
        self._f.seek(byte_lo)
        raw = self._f.read(byte_hi - byte_lo)
        return raw.replace(b"\n", b"").replace(b"\r", b"").decode()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
