"""Reference-genome reader (reference C23 parity).

The reference vendors `GenomeReader`/`ChromosomeReader` — random-access
per-chromosome FASTA with coordinate→byte-offset arithmetic and
chromosome-name synonym resolution (X/23, Y/24, M/MT/25-26, 'chr'
prefixes; reference shared_utils/reference_genome.py:14-130). Unused by
the ProteinBERT path there and here, but part of the vendored surface, so
provided: the byte arithmetic lives in etl/fasta.FastaReader.fetch_range
(one implementation for proteins and genomes); this module adds the
genome-specific naming and 1-based coordinate conventions.
"""

from __future__ import annotations

from typing import Dict, List

from proteinbert_tpu.etl.fasta import FastaReader

# Numeric aliases follow the reference's convention (reference
# shared_utils/reference_genome.py:103-126): X=23, Y=24, M/MT=25/26.
_NUMERIC_ALIASES = {"23": "X", "24": "Y", "25": "M", "26": "M"}
_MITO_ALIASES = {"M", "MT"}


class GenomeReader:
    """Random-access genome FASTA with chromosome-name resolution.

    `fetch(chrom, start, end)` uses 1-BASED INCLUSIVE coordinates (the
    genomics convention the reference reader follows); `fetch0` is the
    0-based half-open equivalent.
    """

    def __init__(self, fasta_path: str):
        self._reader = FastaReader(fasta_path)
        self._resolve: Dict[str, str] = {}
        for name in self._reader.index:
            for syn in self._synonyms(name):
                self._resolve.setdefault(syn, name)

    @staticmethod
    def _synonyms(name: str) -> List[str]:
        syns = [name, name.upper()]
        bare = name[3:] if name.lower().startswith("chr") else name
        syns += [bare, bare.upper(), "chr" + bare, "CHR" + bare.upper()]
        up = bare.upper()
        if up in _NUMERIC_ALIASES.values() or up in _MITO_ALIASES:
            canonical = "M" if up in _MITO_ALIASES else up
            for num, alias in _NUMERIC_ALIASES.items():
                if alias == canonical:
                    syns += [num, "chr" + num]
            if canonical == "M":
                syns += ["M", "MT", "chrM", "chrMT"]
        return syns

    def chromosome_name(self, chrom) -> str:
        """Resolve any accepted synonym to the FASTA's record name."""
        key = str(chrom)
        for cand in (key, key.upper(), _NUMERIC_ALIASES.get(key, key)):
            if cand in self._resolve:
                return self._resolve[cand]
        raise KeyError(f"unknown chromosome {chrom!r}")

    def __contains__(self, chrom) -> bool:
        try:
            self.chromosome_name(chrom)
            return True
        except KeyError:
            return False

    def length(self, chrom) -> int:
        return self._reader.length(self.chromosome_name(chrom))

    def fetch(self, chrom, start: int, end: int) -> str:
        """Bases [start, end] — 1-based inclusive."""
        return self._reader.fetch_range(
            self.chromosome_name(chrom), start - 1, end)

    def fetch0(self, chrom, start: int, end: int) -> str:
        """Bases [start, end) — 0-based half-open."""
        return self._reader.fetch_range(self.chromosome_name(chrom), start, end)

    def close(self) -> None:
        self._reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
