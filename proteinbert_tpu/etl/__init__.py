"""Offline ETL: UniRef90 XML → SQLite → HDF5 (reference C1-C4, rebuilt).

Pipeline (mirrors the reference's two CLI stages, reference
create_uniref_db.py / creare_uniref_h5_db.py):
  1. parse_obo(go.txt) → GoOntology (DAG + ancestor closure)
  2. UnirefToSqliteParser: uniref90.xml.gz → protein_annotations SQLite
     (shardable across a task array; merge_shard_dbs recombines)
  3. create_h5_dataset: SQLite + indexed FASTA → one HDF5 file the
     training feed reads (data/dataset.py HDF5PretrainingDataset)
"""

from proteinbert_tpu.etl.fasta import FastaReader, build_index, iter_fasta
from proteinbert_tpu.etl.genome import GenomeReader
from proteinbert_tpu.etl.go_ontology import (
    GoOntology,
    GoTerm,
    load_meta_csv,
    parse_obo,
    save_meta_csv,
)
from proteinbert_tpu.etl.h5_builder import (
    create_h5_dataset,
    load_seqs_and_annotations,
)
from proteinbert_tpu.etl.uniref_parser import (
    GO_ANNOTATION_CATEGORIES,
    UnirefToSqliteParser,
    merge_shard_dbs,
    read_aggregates,
)

__all__ = [
    "FastaReader", "GenomeReader", "build_index", "iter_fasta",
    "GoOntology", "GoTerm", "parse_obo", "save_meta_csv", "load_meta_csv",
    "create_h5_dataset", "load_seqs_and_annotations",
    "UnirefToSqliteParser", "merge_shard_dbs", "read_aggregates",
    "GO_ANNOTATION_CATEGORIES",
]
