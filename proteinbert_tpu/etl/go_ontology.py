"""GO ontology (OBO) parsing + DAG closure (reference C2, redesigned).

The reference regex-parses the CAFA `go.txt` into a pandas DataFrame and
builds ancestor/offspring closures by BFS from the roots (reference
uniref_dataset.py:158-198, 323-360). Here the ontology is a plain
`GoOntology` object: dict-backed, no DataFrame in the hot path, closures
computed by one topological propagation pass. Crucially `complete()`
really ancestor-completes a term set — the reference computes the
completion and then throws it away (reference uniref_dataset.py:124-126;
SURVEY ledger #6).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Sequence, Set

_TERM_BLOCK = re.compile(r"\[Term\]\n((?:[\w-]+: .*\n?)+)")
_FIELD_LINE = re.compile(r"([\w-]+): (.*)")


@dataclasses.dataclass
class GoTerm:
    id: str
    index: int                      # dense index in parse order
    name: str = ""
    namespace: str = ""
    is_obsolete: bool = False
    parents: Set[str] = dataclasses.field(default_factory=set)   # direct is_a
    children: Set[str] = dataclasses.field(default_factory=set)


class GoOntology:
    """Parsed GO DAG with transitive-ancestor closure.

    `ancestors[go_id]` includes the term itself (matching the reference's
    closure convention, uniref_dataset.py:346).
    """

    def __init__(self, terms: Dict[str, GoTerm]):
        self.terms = terms
        self.id_to_index = {t.id: t.index for t in terms.values()}
        self.index_to_id = {t.index: t.id for t in terms.values()}
        self.ancestors = self._close(lambda t: t.parents)
        self.offspring = self._close(lambda t: t.children)

    def __len__(self) -> int:
        return len(self.terms)

    def _close(self, up) -> Dict[str, Set[str]]:
        """Transitive closure along `up` edges via iterative DFS with
        memoization (the DAG is small: ~47k terms)."""
        closure: Dict[str, Set[str]] = {}

        def visit(root: str) -> Set[str]:
            stack = [root]
            while stack:
                gid = stack[-1]
                if gid in closure:
                    stack.pop()
                    continue
                pending = [p for p in up(self.terms[gid])
                           if p not in closure and p in self.terms]
                if pending:
                    stack.extend(pending)
                    continue
                out = {gid}
                for p in up(self.terms[gid]):
                    if p in self.terms:
                        out |= closure[p]
                closure[gid] = out
                stack.pop()
            return closure[root]

        for gid in self.terms:
            visit(gid)
        return closure

    def complete(self, go_ids: Iterable[str]) -> Set[str]:
        """Ancestor-complete a set of GO ids; unknown ids are dropped
        (the caller counts them — see UnirefToSqliteParser)."""
        out: Set[str] = set()
        for gid in go_ids:
            anc = self.ancestors.get(gid)
            if anc is not None:
                out |= anc
        return out

    def complete_indices(self, go_ids: Iterable[str]) -> List[int]:
        """Sorted dense indices of the ancestor-completed set. This is
        what the reference MEANT to store (ledger #6)."""
        return sorted(self.id_to_index[g] for g in self.complete(go_ids))

    def roots(self) -> List[str]:
        return [t.id for t in self.terms.values() if not t.parents]


def parse_obo(path: str) -> GoOntology:
    """Parse an OBO-style file (the CAFA go.txt format the reference
    consumes, reference uniref_dataset.py:158-198) into a GoOntology."""
    with open(path, "r") as f:
        raw = f.read()

    terms: Dict[str, GoTerm] = {}
    for match in _TERM_BLOCK.finditer(raw):
        fields: Dict[str, List[str]] = {}
        for line in match.group(1).splitlines():
            m = _FIELD_LINE.match(line)
            if not m:
                continue
            fields.setdefault(m.group(1), []).append(m.group(2))
        gid = fields["id"][0]
        if gid in terms:
            raise ValueError(f"duplicate GO id {gid}")
        term = GoTerm(
            id=gid,
            index=len(terms),
            name=fields.get("name", [""])[0],
            namespace=fields.get("namespace", [""])[0],
            is_obsolete=fields.get("is_obsolete", ["false"])[0] == "true",
        )
        for raw_is_a in fields.get("is_a", []):
            # "GO:0000001 ! parent name" — keep only the id.
            term.parents.add(raw_is_a.split(" ! ")[0].strip())
        terms[gid] = term

    # Wire children from parents (second pass; parents may appear later
    # in the file than their children).
    for t in terms.values():
        for p in list(t.parents):
            if p in terms:
                terms[p].children.add(t.id)

    return GoOntology(terms)


def save_meta_csv(
    onto: GoOntology, path: str, counts: Dict[str, int] | None = None,
    total_records: int = 0,
) -> None:
    """Write the per-term metadata CSV the h5 builder consumes (columns
    id,index,name,namespace,count,freq — superset of what the reference's
    create_h5_dataset reads, reference uniref_dataset.py:211)."""
    import csv

    counts = counts or {}
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["id", "index", "name", "namespace", "count", "freq"])
        for gid in sorted(onto.terms, key=lambda g: onto.terms[g].index):
            t = onto.terms[gid]
            c = counts.get(gid, 0)
            freq = c / total_records if total_records else 0.0
            w.writerow([t.id, t.index, t.name, t.namespace, c, freq])


def load_meta_csv(path: str) -> List[dict]:
    import csv

    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    for r in rows:
        r["index"] = int(r["index"])
        r["count"] = int(float(r["count"]))
        r["freq"] = float(r["freq"])
    return rows
